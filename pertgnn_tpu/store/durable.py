"""graftvault durable-write protocol: the one way bytes reach a store.

Five on-disk stores (AOT executables, arena cache, delta arenas, the
checkpoint config sidecar, the capture journal) used to hand-roll
persistence with bare ``os.replace``, no ``fsync``, no payload
checksums, and no cross-process locking. A host crash mid-write, a
torn page written whole, or two fleet workers warming from one shared
store directory could silently corrupt the state every warm-start and
zero-compile guarantee depends on. This module is the single
implementation — and therefore the single proof — of the durability
contract:

- **atomic replace**: ``durable_write`` goes write-to-temp →
  ``fsync(file)`` → ``os.replace`` → ``fsync(dir)``. A crash at any
  instant leaves the destination bit-identical to either the old or
  the new contents — never a third thing (tests/test_durable.py
  SIGKILLs a real writer subprocess at every hook site and asserts
  exactly that).
- **checksummed manifests**: store metadata rides a CRC32C-checksummed
  JSON envelope (``write_json``/``read_json``); blob/array payloads
  get a per-file CRC32C recorded in the entry's manifest so bit-rot is
  detectable (``python -m pertgnn_tpu.store.scrub``) instead of a
  mystery mis-prediction.
- **single-rename entries**: directory entries (arena/delta stores)
  commit through :class:`EntryWriter` — files land in a tmp dir,
  the dir is renamed to an immutable generation (``<key>@g<N>``), and
  THE commit is one ``durable_write`` of the ``<key>.manifest.json``
  pointer. This replaces the unprotected double-``os.replace`` backup
  dance (a crash between the two replaces lost the current entry while
  the backup pointed at the same generation).
- **advisory locks**: :class:`StoreLock` (``flock``) serializes
  concurrent writers — two autoscale spares warming the shared AOT
  store, trainer vs. fleet on the delta store — instead of letting
  them race renames.
- **crash injection**: the protocol fires ``store.write.pre_fsync`` /
  ``post_fsync`` / ``pre_rename`` / ``post_rename`` fault sites
  (testing/faults.py, armed via ``$PERTGNN_FAULT_PLAN``); a ``kill``
  fault is enacted here as ``os._exit(137)`` — the deterministic
  stand-in for power loss the crash matrix is built on.

Telemetry: ``store.fsync_seconds`` / ``store.lock_wait_ms`` histograms
(tag ``store``), plus the scrub CLI's ``store.scrub.*`` /
``store.quarantined`` counters (docs/OBSERVABILITY.md).

Import-light by design (stdlib only; numpy is imported lazily inside
``EntryWriter.put_array``): telemetry/capture.py — a pure-host module
the watcher's bare-python one-liners import between polls — rides this
module too.
"""

from __future__ import annotations

import io
import json
import logging
import os
import time

from pertgnn_tpu.testing import faults

log = logging.getLogger(__name__)

try:
    import fcntl
except ImportError:  # non-posix: locks degrade to no-ops, loudly
    fcntl = None

# The crash-injection hook sites (testing/faults.py site table). One
# occurrence each per durable_write/append; EntryWriter.commit adds one
# pre/post_fsync occurrence (the tmp-dir fsync pass) and one
# pre/post_rename occurrence (the generation-dir rename) BEFORE its
# manifest durable_write.
SITE_PRE_FSYNC = "store.write.pre_fsync"
SITE_POST_FSYNC = "store.write.post_fsync"
SITE_PRE_RENAME = "store.write.pre_rename"
SITE_POST_RENAME = "store.write.post_rename"

ENVELOPE_KEY = "graftvault"
ENVELOPE_VERSION = 1


class StoreCorruption(RuntimeError):
    """A checksummed manifest or blob failed verification. Typed so
    load paths and the scrubber can route EXACTLY the corrupt entry to
    the store's existing single-entry rebuild path (fresh compile /
    arena rebuild / one-shard re-ingest) — never a whole-store
    invalidation."""

    def __init__(self, message: str, *, store: str = "?",
                 path: str | None = None, reason: str = "corrupt"):
        super().__init__(message)
        self.store = store
        self.path = path
        self.reason = reason


class StoreLockTimeout(RuntimeError):
    """A StoreLock wait exceeded its bound — a wedged or dead writer
    is holding the store; failing loudly beats queuing forever."""


# -- CRC32C (Castagnoli) -------------------------------------------------
# google_crc32c (hardware-accelerated) when the wheel is present; a
# pure-python table fallback otherwise. Both compute REAL CRC32C
# (polynomial 0x1EDC6F41, reflected) — the recorded algorithm never
# silently degrades to zlib.crc32, so checksums written by one host
# verify on any other.

try:
    import google_crc32c as _gcrc
except ImportError:
    _gcrc = None

_CRC_TABLE: list[int] | None = None


def _crc_table() -> list[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes, value: int = 0) -> int:
    """CRC32C (Castagnoli) of ``data``, continuing from ``value``."""
    if _gcrc is not None:
        return _gcrc.extend(value, data)
    crc = value ^ 0xFFFFFFFF
    table = _crc_table()
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def file_crc32c(path: str, chunk: int = 1 << 20) -> tuple[int, int]:
    """(crc32c, byte count) of a file, chunked (scrub's blob verify)."""
    crc, n = 0, 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc, n
            crc = crc32c(block, crc)
            n += len(block)


# -- checksummed JSON envelope ------------------------------------------

def canonical_body_bytes(body) -> bytes:
    """The bytes the envelope CRC covers: a canonical (sorted, compact)
    dump, reproducible from the parsed body at read time."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


def checksummed_dumps(body: dict) -> bytes:
    env = {ENVELOPE_KEY: ENVELOPE_VERSION,
           "crc32c": crc32c(canonical_body_bytes(body)),
           "body": body}
    return json.dumps(env, indent=1, sort_keys=True,
                      default=str).encode("utf-8")


def checksummed_loads(data: bytes, *, store: str = "?",
                      path: str | None = None) -> dict:
    """The verified body of a checksummed envelope, or StoreCorruption
    (undecodable, not an envelope, or CRC mismatch)."""
    try:
        env = json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise StoreCorruption(f"manifest is not valid JSON ({e})",
                              store=store, path=path,
                              reason="undecodable") from e
    if not isinstance(env, dict) or ENVELOPE_KEY not in env:
        raise StoreCorruption("manifest is not a graftvault envelope",
                              store=store, path=path,
                              reason="not_envelope")
    body = env.get("body")
    want = env.get("crc32c")
    got = crc32c(canonical_body_bytes(body))
    if got != want:
        raise StoreCorruption(
            f"manifest CRC32C mismatch (recorded {want!r}, computed "
            f"{got})", store=store, path=path, reason="crc_mismatch")
    return body


# -- the protocol --------------------------------------------------------

def _bus(bus=None):
    if bus is not None:
        return bus
    from pertgnn_tpu import telemetry
    return telemetry.get_bus()


def _fire(site: str) -> None:
    """One crash-injection hook. A ``kill`` fault is enacted HERE
    (``os._exit(137)`` — no atexit, no flush: the closest a test can
    get to power loss); ``error`` raises inside ``plan.fire``."""
    plan = faults.active()
    if plan is None:
        return
    if plan.fire(site) == "kill":
        os._exit(137)


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename into it survives power loss (the
    rename itself is atomic; its durability is the dir's)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def durable_write(path: str, data: bytes, *, store: str,
                  bus=None) -> None:
    """THE atomic write: tmp → fsync(file) → os.replace → fsync(dir).

    A crash at any point leaves ``path`` bit-identical to its old or
    new contents. The tmp name is pid-suffixed so concurrent writers
    (already serialized by StoreLock, but belt over braces) never share
    a tmp; a failed write removes its tmp and re-raises."""
    t0 = time.perf_counter()
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:  # graftlint: allow-durable-write
            f.write(data)
            _fire(SITE_PRE_FSYNC)
            f.flush()
            os.fsync(f.fileno())
        _fire(SITE_POST_FSYNC)
        _fire(SITE_PRE_RENAME)
        os.replace(tmp, path)  # graftlint: allow-durable-write
        _fire(SITE_POST_RENAME)
        fsync_dir(parent)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _bus(bus).histogram("store.fsync_seconds",
                        time.perf_counter() - t0, store=store)


def write_json(path: str, body: dict, *, store: str, bus=None) -> None:
    """Durably replace ``path`` with a checksummed envelope of
    ``body``."""
    durable_write(path, checksummed_dumps(body), store=store, bus=bus)


def read_json(path: str, *, store: str) -> dict:
    """The verified body at ``path``. FileNotFoundError propagates
    (absent is the caller's cache-miss path, not corruption);
    StoreCorruption on a torn or tampered envelope."""
    with open(path, "rb") as f:
        data = f.read()
    return checksummed_loads(data, store=store, path=path)


def append_line(path: str, line: bytes, *, store: str, bus=None) -> None:
    """Durable journal append: write one full line, fsync. No rename —
    append-only files recover at line granularity (the reader skips a
    torn tail), so the fsync IS the commit."""
    t0 = time.perf_counter()
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "ab") as f:  # graftlint: allow-durable-write
        f.write(line)
        _fire(SITE_PRE_FSYNC)
        f.flush()
        os.fsync(f.fileno())
    _fire(SITE_POST_FSYNC)
    _bus(bus).histogram("store.fsync_seconds",
                        time.perf_counter() - t0, store=store)


# -- advisory store locks ------------------------------------------------

class StoreLock:
    """Advisory ``flock`` on a lock FILE (``<root>/.lock`` by
    convention): concurrent writers serialize instead of racing
    ``os.replace``. Readers never take it — the rename protocol makes
    every read see a complete old or new state. Reentrant across
    processes only in the flock sense (same fd family); emit
    ``store.lock_wait_ms`` so contention is observable."""

    def __init__(self, path: str, *, store: str,
                 timeout_s: float = 30.0, poll_s: float = 0.005,
                 bus=None):
        self.path = path
        self.store = store
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        self._injected_bus = bus
        self._f = None

    def __enter__(self) -> "StoreLock":
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        t0 = time.perf_counter()
        # the lock file itself is never replaced, only flocked — a
        # plain append-mode open creates it without truncating anyone
        f = open(self.path, "a")  # graftlint: allow-durable-write
        if fcntl is None:
            log.warning("flock unavailable on this platform — store "
                        "lock %s is a no-op", self.path)
            self._f = f
            return self
        deadline = t0 + self.timeout_s
        while True:
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.perf_counter() > deadline:
                    f.close()
                    raise StoreLockTimeout(
                        f"could not lock {self.path} within "
                        f"{self.timeout_s:.1f}s — is a writer wedged?")
                time.sleep(self.poll_s)
        self._f = f
        _bus(self._injected_bus).histogram(
            "store.lock_wait_ms", (time.perf_counter() - t0) * 1e3,
            store=self.store)
        return self

    def __exit__(self, *exc) -> None:
        if self._f is not None:
            if fcntl is not None:
                try:
                    fcntl.flock(self._f.fileno(), fcntl.LOCK_UN)
                except OSError:
                    pass
            self._f.close()
            self._f = None


# -- directory entries: generations + one manifest rename ---------------

def manifest_path(root: str, key: str) -> str:
    return os.path.join(root, f"{key}.manifest.json")


def _gen_of(name: str, key: str) -> int | None:
    """The generation number of a ``<key>@g<N>`` dir name, else None."""
    prefix = f"{key}@g"
    if not name.startswith(prefix):
        return None
    try:
        return int(name[len(prefix):])
    except ValueError:
        return None


def iter_manifests(root: str):
    """(key, manifest path) for every entry manifest under ``root``."""
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return
    for name in names:
        if name.endswith(".manifest.json"):
            yield name[:-len(".manifest.json")], os.path.join(root, name)


def resolve_entry(root: str, key: str, *, store: str
                  ) -> tuple[str, dict] | None:
    """(entry dir, manifest body) for ``key``, or None when absent.
    Raises StoreCorruption on a torn manifest or a manifest whose
    generation dir is gone — the caller's single-entry rebuild path."""
    mp = manifest_path(root, key)
    if not os.path.exists(mp):
        return None
    body = read_json(mp, store=store)
    name = str(body.get("dir", ""))
    if _gen_of(name, key) is None:
        raise StoreCorruption(
            f"manifest for {key} names a foreign dir {name!r}",
            store=store, path=mp, reason="bad_dir")
    d = os.path.join(root, name)
    if not os.path.isdir(d):
        raise StoreCorruption(
            f"manifest for {key} points at missing generation {name}",
            store=store, path=mp, reason="missing_generation")
    return d, body


class EntryWriter:
    """Single-rename commit for a directory entry.

    Files accumulate in ``<root>/.tmp.<key>.<pid>`` with a CRC32C
    recorded per file; ``commit(meta)`` fsyncs them, renames the dir to
    the next immutable generation ``<key>@g<N>`` (the target never
    pre-exists — no backup dance), then durably replaces
    ``<key>.manifest.json`` — the ONE atomic commit point. A crash
    before the manifest rename leaves an orphan generation nothing
    references (the scrubber sweeps it); a crash after it leaves the
    new entry fully committed. Older generations are garbage-collected
    after the commit."""

    def __init__(self, root: str, key: str, *, store: str, bus=None):
        self.root = root
        self.key = key
        self.store = store
        self._injected_bus = bus
        self._tmp = os.path.join(root, f".tmp.{key}.{os.getpid()}")
        self._files: dict[str, dict] = {}
        if os.path.isdir(self._tmp):  # a previous crashed writer's
            import shutil
            shutil.rmtree(self._tmp, ignore_errors=True)
        os.makedirs(self._tmp, exist_ok=True)

    def __enter__(self) -> "EntryWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is not None:
            self.abort()

    def put_bytes(self, filename: str, data: bytes) -> None:
        path = os.path.join(self._tmp, filename)
        with open(path, "wb") as f:  # graftlint: allow-durable-write
            f.write(data)
        self._files[filename] = {"crc32c": crc32c(data),
                                 "bytes": len(data)}

    def put_array(self, filename: str, arr) -> int:
        """np.save an array (``allow_pickle=False`` — the stores' trust
        boundary) through the checksummed path; returns nbytes."""
        import numpy as np

        a = np.ascontiguousarray(np.asarray(arr))
        buf = io.BytesIO()
        # in-memory serialize, not a file write — the bytes then go
        # through put_bytes' checksummed fsync'd path
        np.save(buf, a, allow_pickle=False)  # graftlint: allow-durable-write
        self.put_bytes(filename, buf.getvalue())
        return a.nbytes

    def put_text_lines(self, filename: str, lines) -> None:
        """One JSON string per line (raw ids can contain anything a
        hand-rolled escape would round-trip wrong)."""
        data = "".join(json.dumps(str(v)) + "\n" for v in lines)
        self.put_bytes(filename, data.encode("utf-8"))

    def abort(self) -> None:
        import shutil
        shutil.rmtree(self._tmp, ignore_errors=True)

    def _next_generation(self) -> int:
        gens = [0]
        try:
            for name in os.listdir(self.root):
                g = _gen_of(name, self.key)
                if g is not None:
                    gens.append(g)
        except OSError:
            pass
        return max(gens) + 1

    def commit(self, meta_body: dict) -> str:
        """Durably commit the entry; returns the generation dir path."""
        self.put_bytes("meta.json", json.dumps(
            meta_body, indent=1, sort_keys=True,
            default=str).encode("utf-8"))
        # fsync every file, then the tmp dir, BEFORE the dir becomes
        # reachable — a renamed-but-unsynced file is the torn entry
        # this module exists to kill
        _fire(SITE_PRE_FSYNC)
        for filename in self._files:
            fd = os.open(os.path.join(self._tmp, filename), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        fsync_dir(self._tmp)
        _fire(SITE_POST_FSYNC)
        gen = self._next_generation()
        gen_dir = os.path.join(self.root, f"{self.key}@g{gen}")
        _fire(SITE_PRE_RENAME)
        os.replace(self._tmp, gen_dir)  # graftlint: allow-durable-write
        _fire(SITE_POST_RENAME)
        fsync_dir(self.root)
        # THE commit point: one durable manifest replace
        write_json(manifest_path(self.root, self.key),
                   {"key": self.key, "generation": gen,
                    "dir": os.path.basename(gen_dir),
                    "files": self._files, "meta": meta_body},
                   store=self.store, bus=self._injected_bus)
        self._gc(keep_gen=gen)
        return gen_dir

    def _gc(self, keep_gen: int) -> None:
        """Best-effort sweep of superseded generations and stale tmp
        dirs for THIS key (a racing reader may still mmap an old
        generation's arrays on posix — unlink keeps the pages alive
        until it closes)."""
        import shutil

        try:
            names = os.listdir(self.root)
        except OSError:
            return
        stale_tmp = f".tmp.{self.key}."
        for name in names:
            g = _gen_of(name, self.key)
            if (g is not None and g != keep_gen) or \
                    name.startswith(stale_tmp):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
