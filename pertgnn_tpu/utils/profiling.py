"""Profiling hooks: jax.profiler traces + step timing.

The reference's only instrumentation is tqdm bars (SURVEY.md §5.1). Here:
- `StepTimer` — wall-clock EMA per step with one-line summaries;
- `profile_epochs` — a `fit(profile_hook=...)` hook that captures a
  jax.profiler trace (viewable in TensorBoard/Perfetto) for chosen epochs.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Sequence

import jax

log = logging.getLogger(__name__)


class StepTimer:
    def __init__(self, alpha: float = 0.1):
        self.alpha = alpha
        self.ema = None
        self.count = 0
        self._t = None

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t
        self.ema = dt if self.ema is None else (
            (1 - self.alpha) * self.ema + self.alpha * dt)
        self.count += 1
        return False

    def summary(self) -> str:
        if self.ema is None:
            return "no steps timed"
        return f"{self.count} steps, ema {self.ema * 1e3:.2f} ms/step"


def profile_epochs(log_dir: str, epochs: Sequence[int] = (1,)
                   ) -> Callable[[int, dict], None]:
    """Hook for `fit(profile_hook=...)`: trace the NEXT epoch after each
    epoch in `epochs` completes (epoch 0 compiles, so default traces
    epoch 2's steps by starting after epoch 1)."""
    state = {"active": False}

    def hook(epoch: int, row: dict) -> None:
        if state["active"]:
            jax.profiler.stop_trace()
            state["active"] = False
            log.info("profiler trace for epoch %d written to %s", epoch,
                     log_dir)
        if epoch in epochs:
            jax.profiler.start_trace(log_dir)
            state["active"] = True

    def close() -> None:
        """Flush an open trace if training ended mid-capture (fit calls
        this after the epoch loop)."""
        if state["active"]:
            jax.profiler.stop_trace()
            state["active"] = False
            log.info("profiler trace (final epoch) written to %s", log_dir)

    hook.close = close
    return hook
