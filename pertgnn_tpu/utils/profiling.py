"""Profiling hooks: jax.profiler traces + step timing.

The reference's only instrumentation is tqdm bars (SURVEY.md §5.1). Here:
- `StepTimer` — wall-clock EMA per step with one-line summaries;
- `LatencyRecorder` — percentile latency tracking for the serving engine
  (p50/p95/p99, throughput) — serve/engine.py and benchmarks/serve_bench.py;
- `profile_epochs` — a `fit(profile_hook=...)` hook that captures a
  jax.profiler trace (viewable in TensorBoard/Perfetto) for chosen epochs.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Sequence

import jax

log = logging.getLogger(__name__)


class StepTimer:
    def __init__(self, alpha: float = 0.1):
        self.alpha = alpha
        self.ema = None
        self.count = 0
        self._t = None

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t
        self.ema = dt if self.ema is None else (
            (1 - self.alpha) * self.ema + self.alpha * dt)
        self.count += 1
        return False

    def summary(self) -> str:
        if self.ema is None:
            return "no steps timed"
        return f"{self.count} steps, ema {self.ema * 1e3:.2f} ms/step"


class LatencyRecorder:
    """Latency samples + percentile summary for the serving path.

    Samples are kept raw (one float per observation) rather than binned:
    serving streams are at most ~1e6 requests per process lifetime here,
    so exact percentiles cost nothing and the bench JSON stays honest.
    Not thread-safe on its own — the serving engine serializes all
    recording behind the microbatch queue's single worker."""

    def __init__(self) -> None:
        self._ms: list[float] = []

    def record_s(self, seconds: float) -> None:
        self._ms.append(seconds * 1e3)

    def time(self):
        """Context manager recording one sample."""
        return _LatencySpan(self)

    @property
    def count(self) -> int:
        return len(self._ms)

    def percentile_ms(self, q: float) -> float:
        if not self._ms:
            return float("nan")
        import numpy as np

        return float(np.percentile(np.asarray(self._ms), q))

    def summary_dict(self) -> dict:
        """p50/p95/p99/mean latency (ms) + sample count — the serving
        metrics schema shared by engine stats and serve_bench JSON."""
        import numpy as np

        if not self._ms:
            return {"count": 0, "p50_ms": None, "p95_ms": None,
                    "p99_ms": None, "mean_ms": None}
        a = np.asarray(self._ms)
        return {
            "count": len(a),
            "p50_ms": float(np.percentile(a, 50)),
            "p95_ms": float(np.percentile(a, 95)),
            "p99_ms": float(np.percentile(a, 99)),
            "mean_ms": float(a.mean()),
        }


class _LatencySpan:
    def __init__(self, rec: LatencyRecorder):
        self._rec = rec

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._rec.record_s(time.perf_counter() - self._t)
        return False


def profile_epochs(log_dir: str, epochs: Sequence[int] = (1,)
                   ) -> Callable[[int, dict], None]:
    """Hook for `fit(profile_hook=...)`: trace the NEXT epoch after each
    epoch in `epochs` completes (epoch 0 compiles, so default traces
    epoch 2's steps by starting after epoch 1)."""
    state = {"active": False}

    def hook(epoch: int, row: dict) -> None:
        if state["active"]:
            jax.profiler.stop_trace()
            state["active"] = False
            log.info("profiler trace for epoch %d written to %s", epoch,
                     log_dir)
        if epoch in epochs:
            jax.profiler.start_trace(log_dir)
            state["active"] = True

    def close() -> None:
        """Flush an open trace if training ended mid-capture (fit calls
        this after the epoch loop)."""
        if state["active"]:
            jax.profiler.stop_trace()
            state["active"] = False
            log.info("profiler trace (final epoch) written to %s", log_dir)

    hook.close = close
    return hook
