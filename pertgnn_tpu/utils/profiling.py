"""Profiling hooks: jax.profiler traces + step timing.

The reference's only instrumentation is tqdm bars (SURVEY.md §5.1). Here:
- `StepTimer` — per-step wall-clock stats (EMA + min/max/percentiles)
  reporting through the SAME summary schema as serving latency, so train
  and serve metrics are one shape;
- `LatencyRecorder` — percentile latency tracking for the serving engine
  (p50/p95/p99, throughput) — serve/engine.py and benchmarks/serve_bench.py.
  Raw samples are capped by reservoir sampling so a long-lived serving
  process has bounded memory; percentiles are exact below the cap;
- `profile_epochs` — a `fit(profile_hook=...)` hook that captures a
  jax.profiler trace (viewable in TensorBoard/Perfetto) for chosen epochs
  and cross-references the capture into the telemetry JSONL stream
  (profiler.trace_start/stop events tagged with the epoch — see
  docs/OBSERVABILITY.md for joining the two).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Sequence

from pertgnn_tpu import telemetry

log = logging.getLogger(__name__)

# The shared train/serve latency-summary schema: LatencyRecorder
# .summary_dict and StepTimer.summary_dict both emit exactly these keys
# (StepTimer adds ema_ms on top).
SUMMARY_KEYS = ("count", "p50_ms", "p95_ms", "p99_ms", "mean_ms",
                "min_ms", "max_ms")


class LatencyRecorder:
    """Latency samples + percentile summary for the serving path.

    Memory is bounded: up to `max_samples` raw observations are kept (so
    percentiles are EXACT below the cap); past it, reservoir sampling
    (Algorithm R, seeded — deterministic) keeps a uniform sample while
    count/mean/min/max stay exact over the full stream. The default cap
    (100k float64s = 0.8 MB) is far above any bench horizon here but
    makes a months-lived serving process safe by construction.

    Recording is serialized by the serving engine behind the microbatch
    queue's single worker; the internal lock exists for READERS — a
    long-lived server calling summary_dict/percentile_ms from another
    thread (engine.publish_stats) must see a consistent
    count/sum/reservoir snapshot."""

    def __init__(self, max_samples: int = 100_000, seed: int = 0) -> None:
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1 (got {max_samples})")
        self.max_samples = max_samples
        self._ms: list[float] = []
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def record_s(self, seconds: float) -> None:
        ms = seconds * 1e3
        with self._lock:
            self._count += 1
            self._sum += ms
            self._min = min(self._min, ms)
            self._max = max(self._max, ms)
            if len(self._ms) < self.max_samples:
                self._ms.append(ms)
            else:
                j = self._rng.randrange(self._count)
                if j < self.max_samples:
                    self._ms[j] = ms

    def time(self):
        """Context manager recording one sample."""
        return _LatencySpan(self)

    @property
    def count(self) -> int:
        """Total observations (NOT the retained-sample count)."""
        return self._count

    def percentile_ms(self, q: float) -> float:
        import numpy as np

        with self._lock:
            if not self._ms:
                return float("nan")
            a = np.asarray(self._ms)
        return float(np.percentile(a, q))

    def summary_dict(self) -> dict:
        """p50/p95/p99/mean/min/max latency (ms) + sample count — the
        metrics summary schema shared by serving stats, serve_bench JSON
        and StepTimer (SUMMARY_KEYS)."""
        import numpy as np

        with self._lock:
            if not self._count:
                return {k: (0 if k == "count" else None)
                        for k in SUMMARY_KEYS}
            a = np.asarray(self._ms)
            count, total = self._count, self._sum
            lo, hi = self._min, self._max
        return {
            "count": count,
            "p50_ms": float(np.percentile(a, 50)),
            "p95_ms": float(np.percentile(a, 95)),
            "p99_ms": float(np.percentile(a, 99)),
            "mean_ms": total / count,
            "min_ms": lo,
            "max_ms": hi,
        }


class _LatencySpan:
    def __init__(self, rec: LatencyRecorder):
        self._rec = rec

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._rec.record_s(time.perf_counter() - self._t)
        return False


class StepTimer:
    """Wall-clock step timer: EMA plus full distribution stats.

    Backed by a LatencyRecorder so train-side step timing reports the
    SAME summary shape as serving latency (`summary_dict`, SUMMARY_KEYS)
    with the EMA added as `ema_ms`."""

    def __init__(self, alpha: float = 0.1, max_samples: int = 100_000):
        self.alpha = alpha
        self.ema = None
        self._rec = LatencyRecorder(max_samples=max_samples)
        self._t = None

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t
        self.ema = dt if self.ema is None else (
            (1 - self.alpha) * self.ema + self.alpha * dt)
        self._rec.record_s(dt)
        return False

    @property
    def count(self) -> int:
        return self._rec.count

    def summary_dict(self) -> dict:
        """The serving metrics summary schema + `ema_ms`."""
        out = self._rec.summary_dict()
        out["ema_ms"] = None if self.ema is None else self.ema * 1e3
        return out

    def summary(self) -> str:
        if self.ema is None:
            return "no steps timed"
        s = self._rec.summary_dict()
        return (f"{s['count']} steps, ema {self.ema * 1e3:.2f} ms/step, "
                f"p50 {s['p50_ms']:.2f} min {s['min_ms']:.2f} "
                f"max {s['max_ms']:.2f}")


def profile_epochs(log_dir: str, epochs: Sequence[int] = (1,),
                   profiler=None, bus=None) -> Callable[[int, dict], None]:
    """Hook for `fit(profile_hook=...)`: trace the NEXT epoch after each
    epoch in `epochs` completes (epoch 0 compiles, so default traces
    epoch 2's steps by starting after epoch 1).

    Each capture start/stop is mirrored onto the telemetry bus
    (profiler.trace_start / profiler.trace_stop, tagged with the epoch
    range) so the jax.profiler trace can be cross-referenced from the
    JSONL stream: the trace covers exactly the epochs between a start
    and its stop event. `profiler` defaults to `jax.profiler` — tests
    inject a stub to exercise the start/stop/close state machine without
    a real capture."""
    if profiler is None:
        import jax

        profiler = jax.profiler
    state = {"active": False, "start_epoch": None, "last_completed": None}

    def _bus():
        return bus if bus is not None else telemetry.get_bus()

    def _stop(last_epoch: int | None, final: bool) -> None:
        profiler.stop_trace()
        state["active"] = False
        _bus().event("profiler.trace_stop",
                     fields={"log_dir": log_dir, "final": final},
                     first_epoch=state["start_epoch"],
                     last_epoch=last_epoch)
        log.info("profiler trace (epochs %s..%s) written to %s",
                 state["start_epoch"], last_epoch, log_dir)

    def hook(epoch: int, row: dict) -> None:
        state["last_completed"] = epoch
        if state["active"]:
            _stop(epoch, final=False)
        if epoch in epochs:
            profiler.start_trace(log_dir)
            state["active"] = True
            state["start_epoch"] = epoch + 1
            _bus().event("profiler.trace_start",
                         fields={"log_dir": log_dir},
                         first_epoch=epoch + 1)

    def close() -> None:
        """Flush an open trace if training ended mid-capture (fit calls
        this after the epoch loop). last_epoch is the last epoch that
        ACTUALLY completed inside the capture — None when training ended
        before any did (the trigger epoch was the final one), so the
        JSONL cross-reference never names an epoch that never ran."""
        if state["active"]:
            last = state["last_completed"]
            if last is None or last < state["start_epoch"]:
                last = None
            _stop(last, final=True)

    hook.close = close
    return hook
