"""Structured logging setup (the reference prints; SURVEY.md §5.5)."""

from __future__ import annotations

import logging
import sys


def setup_logging(level: int = logging.INFO) -> None:
    root = logging.getLogger("pertgnn_tpu")
    if root.handlers:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s %(message)s",
        datefmt="%H:%M:%S"))
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False  # avoid double lines when the root logger has
    # a handler (absl installs one)
