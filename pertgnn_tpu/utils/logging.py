"""Structured logging setup (the reference prints; SURVEY.md §5.5).

Multihost-aware: once `set_process_context` is called with world size
> 1 (parallel/multihost.initialize does this after
jax.distributed.initialize), every line is prefixed with this process's
jax.process_index() so interleaved multi-host logs stay attributable.

The level is tunable without code changes: `$PERTGNN_LOG_LEVEL` names
the default, the CLIs' `--log_level` flag (cli/common.setup_telemetry ->
`set_level`) overrides it at runtime.
"""

from __future__ import annotations

import logging
import os
import sys

_BASE_FMT = "%(asctime)s %(name)s %(levelname)s %(message)s"
_DATE_FMT = "%H:%M:%S"


def _resolve_level(level: int | str | None) -> int:
    if level is None:
        level = os.environ.get("PERTGNN_LOG_LEVEL", "") or logging.INFO
    if isinstance(level, int):
        return level
    name = str(level).upper()
    resolved = logging.getLevelName(name)
    if not isinstance(resolved, int):
        raise ValueError(f"unknown log level {level!r}")
    return resolved


def setup_logging(level: int | str | None = None) -> None:
    """Idempotent handler setup; `level` accepts an int or a name and
    defaults to $PERTGNN_LOG_LEVEL (INFO when unset)."""
    root = logging.getLogger("pertgnn_tpu")
    if root.handlers:
        if level is not None:
            root.setLevel(_resolve_level(level))
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_BASE_FMT, datefmt=_DATE_FMT))
    root.addHandler(handler)
    root.setLevel(_resolve_level(level))
    root.propagate = False  # avoid double lines when the root logger has
    # a handler (absl installs one)


def set_level(level: int | str) -> None:
    """Adjust the package log level (handler setup if not done yet).
    setup_logging already applies the level in both of its branches."""
    setup_logging(level)


def set_process_context(process_index: int, process_count: int) -> None:
    """Stamp `[pN]` into the log format when world size > 1 so multihost
    stderr streams are attributable. Called by
    parallel/multihost.initialize AFTER jax.distributed.initialize (this
    module never queries jax itself — doing so could be the first thing
    to dial a wedged backend)."""
    if process_count <= 1:
        return
    setup_logging()
    fmt = logging.Formatter(
        f"%(asctime)s [p{int(process_index)}] " + _BASE_FMT.split(" ", 1)[1],
        datefmt=_DATE_FMT)
    for handler in logging.getLogger("pertgnn_tpu").handlers:
        handler.setFormatter(fmt)
