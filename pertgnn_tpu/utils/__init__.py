from pertgnn_tpu.utils.profiling import StepTimer, profile_epochs
from pertgnn_tpu.utils.logging import setup_logging
