from pertgnn_tpu.utils.profiling import (LatencyRecorder, StepTimer,
                                         profile_epochs)
from pertgnn_tpu.utils.logging import setup_logging
