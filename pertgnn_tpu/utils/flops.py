"""FLOPs/bytes accounting: MFU, MBU, and the HBM roofline.

The reference publishes no efficiency numbers at all (BASELINE.md); here
every benchmark can relate graphs/s to what the chip could do: FLOPs and
bytes-accessed per compiled program come from XLA's own cost model
(`jit(...).lower(...).compile().cost_analysis()`), chip peaks from a
device-kind table. MFU = achieved FLOPs/s / peak FLOPs/s; MBU = achieved
bytes/s / peak HBM bytes/s. For a graph transformer whose arithmetic
intensity (FLOPs/byte) sits far below the chip's roofline knee
(peak_flops / peak_bw, ~240 FLOP/B on v5e), MBU is the honest
utilization number and `roofline_graphs_per_s` the honest ceiling —
see RESULTS.md deep_wide.

Caveats, stated so the numbers are interpretable:
- XLA's `flops`/`bytes accessed` count the optimized HLO (post-fusion):
  hardware FLOPs, and materialized-buffer traffic which can overestimate
  true HBM traffic when buffers stay VMEM-resident;
- peaks are the published dense bf16/f32-accumulate MXU numbers per chip;
  this workload's GEMMs are small (hidden 32 default), so low MFU means
  "dispatch/HBM-bound", not "broken" — see RESULTS.md.
"""

from __future__ import annotations

import logging

import jax

log = logging.getLogger(__name__)

# peak dense matmul FLOPs/s per chip (bf16 with f32 accumulate — the MXU
# path XLA uses for f32 model dtypes too, via 3-pass bf16 decomposition
# it counts as-is). Public numbers: cloud.google.com/tpu/docs.
_PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),     # v5e reports device_kind "TPU v5 lite"
    ("v5", 459e12),
    ("v4 lite", 138e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_for_kind(kind: str | None) -> float | None:
    """Peak FLOPs/s for a TPU device-kind STRING (e.g. \"TPU v5 lite\"),
    or None when unknown/non-TPU. Takes the string rather than a live
    device so capture-time kinds recorded in partial files can be
    resolved later on a host whose backend differs (bench.py
    --finalize-partial runs forced-CPU)."""
    kind = (kind or "").lower()
    if "tpu" not in kind:
        return None
    for key, peak in _PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    log.warning("unknown TPU device_kind %r — MFU unavailable", kind)
    return None


def peak_flops_per_chip() -> float | None:
    """Peak FLOPs/s of one local device, or None when unknown (CPU)."""
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        return None
    # platform says tpu but the kind string may not: pass a marker the
    # kind-table's "tpu" gate accepts
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return peak_flops_for_kind(kind if "tpu" in kind else f"tpu {kind}")


# peak HBM bandwidth bytes/s per chip (public: cloud.google.com/tpu/docs).
_PEAK_HBM_BW_BY_KIND = (
    ("v6e", 1640e9),
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5 lite", 819e9),      # v5e reports device_kind "TPU v5 lite"
    ("v5", 2765e9),
    ("v4 lite", 614e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def peak_hbm_bw_for_kind(kind: str | None) -> float | None:
    """Peak HBM bytes/s for a TPU device-kind STRING, or None when
    unknown/non-TPU (same contract as peak_flops_for_kind)."""
    kind = (kind or "").lower()
    if "tpu" not in kind:
        return None
    for key, bw in _PEAK_HBM_BW_BY_KIND:
        if key in kind:
            return bw
    log.warning("unknown TPU device_kind %r — MBU unavailable", kind)
    return None


def peak_hbm_bw_per_chip() -> float | None:
    """Peak HBM bytes/s of one local device, or None when unknown (CPU)."""
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        return None
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return peak_hbm_bw_for_kind(kind if "tpu" in kind else f"tpu {kind}")


def compiled_cost(jitted, *args) -> tuple[float | None, float | None]:
    """(flops, bytes_accessed) of ONE invocation of an already-jitted
    callable on `args`, from XLA's cost analysis (None fields when the
    backend doesn't report them)."""
    try:
        compiled = jitted.lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax: one dict per program
            cost = cost[0]
        f = cost.get("flops")
        b = cost.get("bytes accessed")
        return (float(f) if f and f > 0 else None,
                float(b) if b and b > 0 else None)
    except Exception as e:  # pragma: no cover — backend-dependent
        log.info("cost_analysis unavailable: %s", e)
        return None, None


def compiled_flops(jitted, *args) -> float | None:
    """FLOPs of ONE invocation of an already-jitted callable on `args`,
    from XLA's cost analysis (None if the backend doesn't report it)."""
    return compiled_cost(jitted, *args)[0]


def mfu(graphs_per_s: float, flops_per_graph: float | None,
        peak: float | None = None) -> float | None:
    """Achieved fraction of chip peak at `graphs_per_s` throughput. `peak`
    overrides the live-backend query (e.g. finalizing a capture on a host
    whose backend differs from the one that measured)."""
    if peak is None:
        peak = peak_flops_per_chip()
    if peak is None or flops_per_graph is None:
        return None
    return graphs_per_s * flops_per_graph / peak


def mbu(graphs_per_s: float, bytes_per_graph: float | None,
        bw: float | None = None) -> float | None:
    """Achieved fraction of peak HBM bandwidth — the honest utilization
    number when arithmetic intensity sits below the roofline knee. `bw`
    overrides the live-backend query."""
    if bw is None:
        bw = peak_hbm_bw_per_chip()
    if bw is None or bytes_per_graph is None:
        return None
    return graphs_per_s * bytes_per_graph / bw


def roofline_graphs_per_s(flops_per_graph: float | None,
                          bytes_per_graph: float | None,
                          peak_f: float | None = None,
                          peak_b: float | None = None) -> float | None:
    """min(compute, bandwidth) roofline ceiling for this chip, in graphs/s:
    the hard upper bound implied by the compiled program's FLOPs and bytes
    against the device's peaks (overridable, as above)."""
    if peak_f is None:
        peak_f = peak_flops_per_chip()
    if peak_b is None:
        peak_b = peak_hbm_bw_per_chip()
    bounds = []
    if peak_f is not None and flops_per_graph:
        bounds.append(peak_f / flops_per_graph)
    if peak_b is not None and bytes_per_graph:
        bounds.append(peak_b / bytes_per_graph)
    return min(bounds) if bounds else None
