"""FLOPs/bytes accounting: MFU, MBU, and the HBM roofline.

The reference publishes no efficiency numbers at all (BASELINE.md); here
every benchmark can relate graphs/s to what the chip could do: FLOPs and
bytes-accessed per compiled program come from XLA's own cost model
(`jit(...).lower(...).compile().cost_analysis()`), chip peaks from a
device-kind table. MFU = achieved FLOPs/s / peak FLOPs/s; MBU = achieved
bytes/s / peak HBM bytes/s. For a graph transformer whose arithmetic
intensity (FLOPs/byte) sits far below the chip's roofline knee
(peak_flops / peak_bw, ~240 FLOP/B on v5e), MBU is the honest
utilization number and `roofline_graphs_per_s` the honest ceiling —
see RESULTS.md deep_wide.

Caveats, stated so the numbers are interpretable:
- XLA's `flops`/`bytes accessed` count the optimized HLO (post-fusion):
  hardware FLOPs, and materialized-buffer traffic which can overestimate
  true HBM traffic when buffers stay VMEM-resident;
- peaks are the published dense bf16/f32-accumulate MXU numbers per chip;
  this workload's GEMMs are small (hidden 32 default), so low MFU means
  "dispatch/HBM-bound", not "broken" — see RESULTS.md.
"""

from __future__ import annotations

import logging

import jax

log = logging.getLogger(__name__)

# peak dense matmul FLOPs/s per chip (bf16 with f32 accumulate — the MXU
# path XLA uses for f32 model dtypes too, via 3-pass bf16 decomposition
# it counts as-is). Public numbers: cloud.google.com/tpu/docs.
_PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),     # v5e reports device_kind "TPU v5 lite"
    ("v5", 459e12),
    ("v4 lite", 138e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_for_kind(kind: str | None) -> float | None:
    """Peak FLOPs/s for a TPU device-kind STRING (e.g. \"TPU v5 lite\"),
    or None when unknown/non-TPU. Takes the string rather than a live
    device so capture-time kinds recorded in partial files can be
    resolved later on a host whose backend differs (bench.py
    --finalize-partial runs forced-CPU)."""
    kind = (kind or "").lower()
    if "tpu" not in kind:
        return None
    for key, peak in _PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    log.warning("unknown TPU device_kind %r — MFU unavailable", kind)
    return None


def peak_flops_per_chip() -> float | None:
    """Peak FLOPs/s of one local device, or None when unknown (CPU)."""
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        return None
    # platform says tpu but the kind string may not: pass a marker the
    # kind-table's "tpu" gate accepts
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return peak_flops_for_kind(kind if "tpu" in kind else f"tpu {kind}")


# peak HBM bandwidth bytes/s per chip (public: cloud.google.com/tpu/docs).
_PEAK_HBM_BW_BY_KIND = (
    ("v6e", 1640e9),
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5 lite", 819e9),      # v5e reports device_kind "TPU v5 lite"
    ("v5", 2765e9),
    ("v4 lite", 614e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def peak_hbm_bw_for_kind(kind: str | None) -> float | None:
    """Peak HBM bytes/s for a TPU device-kind STRING, or None when
    unknown/non-TPU (same contract as peak_flops_for_kind)."""
    kind = (kind or "").lower()
    if "tpu" not in kind:
        return None
    for key, bw in _PEAK_HBM_BW_BY_KIND:
        if key in kind:
            return bw
    log.warning("unknown TPU device_kind %r — MBU unavailable", kind)
    return None


def peak_hbm_bw_per_chip() -> float | None:
    """Peak HBM bytes/s of one local device, or None when unknown (CPU)."""
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        return None
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return peak_hbm_bw_for_kind(kind if "tpu" in kind else f"tpu {kind}")


def executable_cost(compiled) -> tuple[float | None, float | None]:
    """(flops, bytes_accessed) of an ALREADY-compiled executable (e.g. a
    serve-engine AOT rung) from XLA's cost analysis — None fields when
    the backend/serialization path doesn't report them (deserialized
    executables may not carry an HLO cost model)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax: one dict per program
            cost = cost[0]
        f = cost.get("flops")
        b = cost.get("bytes accessed")
        return (float(f) if f and f > 0 else None,
                float(b) if b and b > 0 else None)
    except Exception as e:  # pragma: no cover — backend-dependent
        log.info("cost_analysis unavailable: %s", e)
        return None, None


def compiled_cost(jitted, *args) -> tuple[float | None, float | None]:
    """(flops, bytes_accessed) of ONE invocation of an already-jitted
    callable on `args`, from XLA's cost analysis (None fields when the
    backend doesn't report them)."""
    try:
        compiled = jitted.lower(*args).compile()
    except Exception as e:  # pragma: no cover — backend-dependent
        log.info("cost_analysis unavailable: %s", e)
        return None, None
    return executable_cost(compiled)


def compiled_flops(jitted, *args) -> float | None:
    """FLOPs of ONE invocation of an already-jitted callable on `args`,
    from XLA's cost analysis (None if the backend doesn't report it)."""
    return compiled_cost(jitted, *args)[0]


def mfu(graphs_per_s: float, flops_per_graph: float | None,
        peak: float | None = None) -> float | None:
    """Achieved fraction of chip peak at `graphs_per_s` throughput. `peak`
    overrides the live-backend query (e.g. finalizing a capture on a host
    whose backend differs from the one that measured)."""
    if peak is None:
        peak = peak_flops_per_chip()
    if peak is None or flops_per_graph is None:
        return None
    return graphs_per_s * flops_per_graph / peak


def mbu(graphs_per_s: float, bytes_per_graph: float | None,
        bw: float | None = None) -> float | None:
    """Achieved fraction of peak HBM bandwidth — the honest utilization
    number when arithmetic intensity sits below the roofline knee. `bw`
    overrides the live-backend query."""
    if bw is None:
        bw = peak_hbm_bw_per_chip()
    if bw is None or bytes_per_graph is None:
        return None
    return graphs_per_s * bytes_per_graph / bw


def variant_attribution(*, attention_impl: str, dtype: str,
                        graphs_per_s: float | None,
                        flops_per_graph: float | None,
                        bytes_per_graph: float | None,
                        peak_f: float | None = None,
                        peak_b: float | None = None) -> dict:
    """One roofline-attribution row for a (kernel variant, dtype) pair —
    the shared schema bench.py / serve_bench.py / kernel_bench.py emit so
    every measured number says WHICH hot-path implementation produced it
    (segment / pallas / pallas_fused / blocked_dense x f32/bf16/int8).
    mfu/mbu/roofline degrade to None off-chip (no peak published for a
    host CPU) while flops/bytes stay — a CPU row is still attributable,
    just not utilization-scored."""
    row = {
        "attention_impl": attention_impl,
        "dtype": dtype,
        "flops_per_graph": (round(flops_per_graph)
                            if flops_per_graph is not None else None),
        "bytes_per_graph": (round(bytes_per_graph)
                            if bytes_per_graph is not None else None),
        "mfu_pct": None, "mbu_pct": None, "roofline_graphs_per_s": None,
    }
    if graphs_per_s is not None:
        eff = mfu(graphs_per_s, flops_per_graph, peak=peak_f)
        bw_eff = mbu(graphs_per_s, bytes_per_graph, bw=peak_b)
        if eff is not None:
            row["mfu_pct"] = round(100 * eff, 2)
        if bw_eff is not None:
            row["mbu_pct"] = round(100 * bw_eff, 2)
    ceiling = roofline_graphs_per_s(flops_per_graph, bytes_per_graph,
                                    peak_f=peak_f, peak_b=peak_b)
    if ceiling is not None:
        row["roofline_graphs_per_s"] = round(ceiling, 1)
    return row


def publish_attribution(bus, row: dict, *, prefix: str = "roofline") -> None:
    """Emit a variant_attribution row's numeric fields as telemetry
    gauges (`<prefix>.mfu_pct` etc), tagged with the variant and dtype so
    capture JSONLs carry per-variant utilization next to the counters
    (docs/OBSERVABILITY.md)."""
    tags = {"impl": row["attention_impl"], "dtype": row["dtype"]}
    for field in ("mfu_pct", "mbu_pct", "roofline_graphs_per_s",
                  "flops_per_graph", "bytes_per_graph"):
        if row.get(field) is not None:
            # names enumerated by the tuple above under the caller's
            # prefix — serve_bench passes "serve.roofline", documented
            # as docs/OBSERVABILITY.md's roofline table
            bus.gauge(f"{prefix}.{field}", row[field], **tags)  # graftlint: allow-telemetry-drift


def roofline_graphs_per_s(flops_per_graph: float | None,
                          bytes_per_graph: float | None,
                          peak_f: float | None = None,
                          peak_b: float | None = None) -> float | None:
    """min(compute, bandwidth) roofline ceiling for this chip, in graphs/s:
    the hard upper bound implied by the compiled program's FLOPs and bytes
    against the device's peaks (overridable, as above)."""
    if peak_f is None:
        peak_f = peak_flops_per_chip()
    if peak_b is None:
        peak_b = peak_hbm_bw_per_chip()
    bounds = []
    if peak_f is not None and flops_per_graph:
        bounds.append(peak_f / flops_per_graph)
    if peak_b is not None and bytes_per_graph:
        bounds.append(peak_b / bytes_per_graph)
    return min(bounds) if bounds else None
