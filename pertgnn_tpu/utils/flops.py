"""FLOPs accounting and MFU (model FLOPs utilization).

The reference publishes no efficiency numbers at all (BASELINE.md); here
every benchmark can relate graphs/s to what the chip could do: FLOPs per
compiled program come from XLA's own cost model
(`jit(...).lower(...).compile().cost_analysis()`), peak chip FLOPs from a
device-kind table. MFU = achieved FLOPs/s / peak FLOPs/s.

Caveats, stated so the number is interpretable:
- XLA's `flops` counts the optimized HLO (post-fusion), i.e. hardware
  FLOPs, not a paper-model count;
- peaks are the published dense bf16/f32-accumulate MXU numbers per chip;
  this workload's GEMMs are small (hidden 32 default), so low MFU means
  "dispatch/HBM-bound", not "broken" — see RESULTS.md.
"""

from __future__ import annotations

import logging

import jax

log = logging.getLogger(__name__)

# peak dense matmul FLOPs/s per chip (bf16 with f32 accumulate — the MXU
# path XLA uses for f32 model dtypes too, via 3-pass bf16 decomposition
# it counts as-is). Public numbers: cloud.google.com/tpu/docs.
_PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),     # v5e reports device_kind "TPU v5 lite"
    ("v5", 459e12),
    ("v4 lite", 138e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_chip() -> float | None:
    """Peak FLOPs/s of one local device, or None when unknown (CPU)."""
    dev = jax.devices()[0]
    kind = (getattr(dev, "device_kind", "") or "").lower()
    if dev.platform != "tpu":
        return None
    for key, peak in _PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    log.warning("unknown TPU device_kind %r — MFU unavailable", kind)
    return None


def compiled_flops(jitted, *args) -> float | None:
    """FLOPs of ONE invocation of an already-jitted callable on `args`,
    from XLA's cost analysis (None if the backend doesn't report it)."""
    try:
        compiled = jitted.lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax: one dict per program
            cost = cost[0]
        f = cost.get("flops")
        return float(f) if f and f > 0 else None
    except Exception as e:  # pragma: no cover — backend-dependent
        log.info("cost_analysis unavailable: %s", e)
        return None


def mfu(graphs_per_s: float, flops_per_graph: float | None) -> float | None:
    """Achieved fraction of chip peak at `graphs_per_s` throughput."""
    peak = peak_flops_per_chip()
    if peak is None or flops_per_graph is None:
        return None
    return graphs_per_s * flops_per_graph / peak
