"""graftmemo — the router's content-keyed semantic prediction cache.

Predictions are PURE functions of (checkpoint epoch, arena fingerprint,
entry, ts bucket, lens/what-if variant) and bit-deterministic — the
property every prior layer fought for (PARITY.md; hedging and requeue
are safe because of it).  Loadgen's Zipf popularity model says real
traffic re-asks the same hot requests constantly, so the fastest
inference is the one never run: the router consults this memo at
``submit`` and resolves a hit's Future immediately, skipping admission,
dispatch, the wire, and the engine entirely (ROADMAP item 4).

Design rules, in the order they matter:

- **keyed on content, not time.**  A key is (generation, entry,
  ts_bucket, canonical lens payload), where the GENERATION pins the
  semantic version of the answer: (checkpoint_epoch,
  arena_fingerprint, quantile taus) — everything a served bit depends
  on besides the request itself.  The lens payload is canonicalized
  (lens/canon.py) so equivalent counterfactual scripts share one
  entry.
- **invalidated by construction, not by TTL.**  The store holds ONE
  generation.  A blue/green rollout (fleet/rollout.py) calls
  ``retire_generation`` the moment the first worker drains — every old
  entry becomes unreachable atomically — and installs the new
  generation only after the whole fleet verified on the new
  checkpoint.  Mid-rollout the fleet serves two checkpoint versions,
  so mid-rollout the memo serves NOTHING and refuses inserts: lookups
  stamp the generation they saw, and ``insert`` drops any value whose
  stamp is no longer current (counter ``memo.stale_insert``).  A stale
  read is thereby impossible by construction — there is no window
  where an old-generation byte can be returned or stored.
- **bounded memory, wire-encoded values.**  Values are stored as
  single-row graftwire response frames (fleet/wire.py) with the
  ``cache_hit`` flag already set: byte-exact accounting for the LRU
  bound (``capacity_bytes``), decode on hit through the same
  ``decode_response`` path the binary transport uses (bit-identity is
  the codec's round-trip property, pinned in tests/test_wire.py), and
  a frame that could be forwarded to a binary/shm peer without
  re-serialization.  Eviction is LRU; a frame larger than the whole
  capacity is refused outright (``memo.oversize``) rather than
  thrashing the store.

Thread protocol (graftsync-clean by construction, not by allowlist):
one plain ``threading.Lock`` guards the store; nothing blocking — no
bus emission, no Future resolution, no I/O — ever runs under it.  The
``fleet.memo.lookup`` / ``fleet.memo.insert`` / ``fleet.memo.flip``
sync points (testing/schedules.py) sit BEFORE each lock acquisition so
tests/test_memo.py can script the rollout-flip vs in-flight race in
both orders.

Telemetry (docs/OBSERVABILITY.md): counters ``memo.hit`` /
``memo.miss`` / ``memo.insert`` / ``memo.evict`` / ``memo.retired`` /
``memo.stale_insert`` / ``memo.oversize``, gauges ``memo.bytes`` /
``memo.generation``; the router emits ``transport.cache_bytes_saved``
per hit for the wire bytes that never moved.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

from pertgnn_tpu import telemetry
from pertgnn_tpu.fleet import wire
from pertgnn_tpu.lens.canon import canonical_lens_key
from pertgnn_tpu.testing import schedules


@dataclasses.dataclass(frozen=True)
class MemoGeneration:
    """The semantic version a cached answer is valid for.  ``seq`` is a
    monotonically increasing install counter — two installs of the same
    (epoch, arena, taus) are still distinct generations, so a
    retire/reinstall cycle can never resurrect a stale stamp."""

    seq: int
    checkpoint_epoch: int
    arena_fingerprint: str
    taus: tuple


@dataclasses.dataclass(frozen=True)
class MemoToken:
    """A miss's insert permit: the generation the lookup ran under and
    the key it computed.  ``insert`` honors the token only while that
    generation is still current."""

    gen_seq: int
    key: tuple


class PredictionMemo:
    """Bounded content-keyed LRU over wire-encoded prediction rows."""

    def __init__(self, capacity_bytes: int, bus=None):
        if capacity_bytes <= 0:
            raise ValueError("PredictionMemo needs capacity_bytes > 0")
        self._capacity = int(capacity_bytes)
        self._injected_bus = bus
        self._lock = threading.Lock()
        self._gen: MemoGeneration | None = None
        self._gen_seq = 0
        self._store: OrderedDict[tuple, bytes] = OrderedDict()
        self._bytes = 0
        # counters mirrored to the bus (memo.* names)
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.retired = 0
        self.stale_inserts = 0
        self.oversize = 0

    @property
    def bus(self):
        if self._injected_bus is not None:
            return self._injected_bus
        return telemetry.get_bus()

    @property
    def capacity_bytes(self) -> int:
        return self._capacity

    @property
    def generation(self) -> MemoGeneration | None:
        with self._lock:
            return self._gen

    # -- generations -----------------------------------------------------

    def set_generation(self, checkpoint_epoch: int,
                       arena_fingerprint: str,
                       taus) -> MemoGeneration:
        """Install the active generation, retiring whatever was there.
        This IS the rollout flip's second half: the controller retires
        at drain start and the operator/launcher installs here once the
        fleet verified on the new checkpoint."""
        schedules.sync_point("fleet.memo.flip")
        taus = tuple(float(t) for t in taus)
        with self._lock:
            n_retired, freed = len(self._store), self._bytes
            self._gen_seq += 1
            gen = MemoGeneration(seq=self._gen_seq,
                                 checkpoint_epoch=int(checkpoint_epoch),
                                 arena_fingerprint=str(arena_fingerprint),
                                 taus=taus)
            self._gen = gen
            self._store = OrderedDict()
            self._bytes = 0
            self.retired += n_retired
        bus = self.bus
        if n_retired:
            bus.counter("memo.retired", n_retired, reason="flip",
                        bytes=freed)
        bus.gauge("memo.generation", gen.seq,
                  checkpoint_epoch=gen.checkpoint_epoch,
                  arena=gen.arena_fingerprint)
        bus.gauge("memo.bytes", 0)
        return gen

    def retire_generation(self, reason: str = "rollout") -> int:
        """Atomically drop the active generation and every entry —
        the memo serves nothing and refuses inserts until the next
        ``set_generation``.  Returns the number of entries retired."""
        schedules.sync_point("fleet.memo.flip")
        with self._lock:
            n_retired, freed = len(self._store), self._bytes
            self._gen = None
            self._store = OrderedDict()
            self._bytes = 0
            self.retired += n_retired
        bus = self.bus
        bus.counter("memo.retired", n_retired, reason=reason,
                    bytes=freed)
        bus.gauge("memo.generation", 0, active=False)
        bus.gauge("memo.bytes", 0)
        return n_retired

    # -- the read-mostly path --------------------------------------------

    @staticmethod
    def _key(entry_id: int, ts_bucket: int, lens_wire: dict | None):
        return (int(entry_id), int(ts_bucket),
                canonical_lens_key(lens_wire))

    def lookup(self, entry_id: int, ts_bucket: int,
               lens_wire: dict | None = None
               ) -> tuple[dict | None, MemoToken | None, int]:
        """(row, token, frame_bytes): a hit decodes the stored frame
        back into its wire row (``cache_hit: True`` travels with it) —
        (row, None, len(frame)); a miss returns (None, token, 0) with
        the insert permit (token None when no generation is active)."""
        key = self._key(entry_id, ts_bucket, lens_wire)
        schedules.sync_point("fleet.memo.lookup")
        with self._lock:
            gen = self._gen
            frame = self._store.get(key) if gen is not None else None
            if frame is not None:
                self._store.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        if frame is None:
            self.bus.counter("memo.miss", level=2, entry_id=entry_id)
            token = (MemoToken(gen_seq=gen.seq, key=key)
                     if gen is not None else None)
            return None, token, 0
        row = wire.decode_response(frame)[0]
        self.bus.counter("memo.hit", level=2, entry_id=entry_id)
        return row, None, len(frame)

    def insert(self, token: MemoToken | None, row: dict) -> bool:
        """Store one served wire row under a miss's token.  Dropped
        (returning False) when the token is absent, the row is not a
        prediction, the generation moved on (``memo.stale_insert`` —
        the in-flight-across-a-rollout race), or the frame alone
        exceeds the capacity (``memo.oversize``)."""
        if token is None or "pred" not in row or "error" in row:
            return False
        clean = {k: v for k, v in row.items() if k != "cache_hit"}
        frame = wire.encode_response([{**clean, "cache_hit": True}])
        if len(frame) > self._capacity:
            with self._lock:
                self.oversize += 1
            self.bus.counter("memo.oversize", level=2,
                             bytes=len(frame))
            return False
        schedules.sync_point("fleet.memo.insert")
        evicted = 0
        freed = 0
        with self._lock:
            if self._gen is None or self._gen.seq != token.gen_seq:
                self.stale_inserts += 1
                stored = False
            else:
                old = self._store.pop(token.key, None)
                if old is not None:
                    self._bytes -= len(old)
                self._store[token.key] = frame
                self._bytes += len(frame)
                while self._bytes > self._capacity:
                    _k, v = self._store.popitem(last=False)
                    self._bytes -= len(v)
                    evicted += 1
                    freed += len(v)
                self.inserts += 1
                self.evictions += evicted
                stored = True
            nbytes = self._bytes
        if not stored:
            self.bus.counter("memo.stale_insert", level=2)
            return False
        self.bus.counter("memo.insert", level=2, bytes=len(frame))
        if evicted:
            self.bus.counter("memo.evict", evicted, level=2,
                             bytes=freed)
        self.bus.gauge("memo.bytes", nbytes, level=2)
        return True

    # -- introspection ---------------------------------------------------

    def stats_dict(self) -> dict:
        with self._lock:
            gen = self._gen
            return {
                "generation": (None if gen is None else {
                    "seq": gen.seq,
                    "checkpoint_epoch": gen.checkpoint_epoch,
                    "arena_fingerprint": gen.arena_fingerprint,
                    "taus": list(gen.taus),
                }),
                "entries": len(self._store),
                "bytes": self._bytes,
                "capacity_bytes": self._capacity,
                "hits": self.hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "retired": self.retired,
                "stale_inserts": self.stale_inserts,
                "oversize": self.oversize,
            }
