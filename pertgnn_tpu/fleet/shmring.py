"""graftwire shared-memory transport — SPSC rings for same-host hops.

The binary codec (fleet/wire.py) removes the serialize cost; this
module removes the kernel round trips: one ``multiprocessing.shared_
memory`` ring per direction between the router's per-worker sender
thread and the worker's ring service thread. Single-producer/
single-consumer holds BY CONSTRUCTION (the router has exactly one
sender thread per worker — hedge legs ride the hedge target's own
sender — and the worker runs exactly one ring service thread), so the
ring needs no locks, only ordering:

- every slot is ``seq u64 | len u32 | crc u32 | payload``; the
  producer writes payload + length + payload CRC32C, and stamps the
  sequence number LAST — the sequence stamp IS the commit counter, so
  a crashed producer can never publish a half-written slot;
- the consumer reads the stamp, copies the payload out, RE-READS
  the stamp, and then verifies the copied bytes against the slot's
  CRC32C: a moved stamp or a checksum mismatch is a torn write
  (:class:`RingTornWrite`, counter ``transport.crc_rejects`` for the
  checksum case) and the peer is treated as gone, never trusted —
  the CRC catches the single-word corruptions (a partial cache-line
  flush, a stray write) the stamp discipline alone cannot see;
- backpressure is structural: the producer may claim slot ``seq`` only
  while ``seq - consumed <= slots`` (the consumer still owns the
  oldest slot otherwise), so a dead reader fills the ring and the
  writer's bounded wait times out instead of overwriting.

Wakeup is an eventfd-style DOORBELL, not a spin: a localhost TCP pair
(the stdlib's portable socketpair-across-processes) carries one-byte
tokens after every push, and both sides wait in ``select`` with
bounded timeouts feeding the router's existing watchdog/hedge
machinery. The doorbell doubles as the liveness signal — a SIGKILLed
peer resets it, which surfaces as :class:`RingPeerDead` and maps to
the transport's lost-worker path (every Future still resolves).

TRUST boundary (docs/GUIDE.md §14): the segments are same-host,
same-user only — names travel in the worker's probe body, payloads
are graftwire frames (ints/floats/UTF-8 JSON), and nothing on either
side ever unpickles a byte of shared memory.

graftsync's ring-protocol pass statically checks the commit-counter
ordering against the ``_payload_write``/``_seq_write`` /
``_seq_read``/``_payload_read`` helpers below — keep the names.
"""

from __future__ import annotations

import logging
import os
import select
import socket
import struct
import threading
import time

from pertgnn_tpu.store.durable import crc32c

log = logging.getLogger(__name__)

_HDR = struct.Struct("<IIII")            # magic, version, slots, slot_bytes
_MAGIC = 0x47575231                      # "GWR1"
RING_VERSION = 2                         # v2: per-slot payload CRC32C
_CTR = struct.Struct("<Q")               # produced / consumed counters
_PRODUCED_OFF = _HDR.size                # 16
_CONSUMED_OFF = _HDR.size + 8            # 24
_DATA_OFF = _HDR.size + 16               # 32
_SEQ = struct.Struct("<Q")               # per-slot commit stamp
_LEN = struct.Struct("<I")
_CRC = struct.Struct("<I")               # per-slot payload CRC32C
_SLOT_HDR = _SEQ.size + _LEN.size + _CRC.size   # 16
_CORR = struct.Struct("<Q")              # per-call correlation prefix


class RingError(RuntimeError):
    """Base of every ring failure the transport maps to its
    lost-worker/fallback machinery."""


class RingPeerDead(RingError):
    """The doorbell reset or closed: the peer process is gone."""


class RingTimeout(RingError):
    """A bounded ring wait expired (full ring with a dead reader, or
    no response within the dispatch timeout)."""


class RingTornWrite(RingError):
    """A slot's commit stamp changed across the payload copy, or a
    stamp from the future appeared — the ring's ordering contract is
    broken and the peer cannot be trusted."""


class RingFrameTooLarge(RingError):
    """The frame exceeds the slot payload capacity; the transport
    falls back to HTTP for this call (counter transport.fallback)."""


def _untrack(name: str) -> None:
    """Detach-side resource-tracker unregistration: before 3.13 the
    tracker registers ATTACHED segments too and unlinks them when the
    attaching process exits — which would tear the worker's live ring
    down under it. The creator side keeps ownership and unlinks."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(f"/{name}", "shared_memory")
    except Exception:  # lint: allow-silent-except
        # best-effort: on interpreters that don't track attached
        # segments there is nothing to unregister, and failing the
        # ATTACH because a bookkeeping opt-out failed would be absurd
        pass


class ShmRing:
    """One SPSC ring over a shared-memory segment. The same class
    serves both roles; which cursor advances is decided by which of
    ``try_push``/``try_pop`` the owner calls."""

    def __init__(self, shm, slots: int, slot_bytes: int,
                 owned: bool) -> None:
        self._shm = shm
        self._buf = shm.buf
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self.payload_max = self.slot_bytes - _SLOT_HDR
        self._owned = owned
        self._produced = self._load_ctr(_PRODUCED_OFF)
        self._consumed = self._load_ctr(_CONSUMED_OFF)

    @classmethod
    def create(cls, slots: int, slot_bytes: int) -> "ShmRing":
        from multiprocessing import shared_memory

        if slots < 2 or slot_bytes <= _SLOT_HDR:
            raise RingError(f"ring needs >= 2 slots and "
                            f"> {_SLOT_HDR}-byte slots "
                            f"(got {slots} x {slot_bytes})")
        size = _DATA_OFF + slots * slot_bytes
        shm = shared_memory.SharedMemory(create=True, size=size)
        shm.buf[:_DATA_OFF] = bytes(_DATA_OFF)
        _HDR.pack_into(shm.buf, 0, _MAGIC, RING_VERSION, slots,
                       slot_bytes)
        return cls(shm, slots, slot_bytes, owned=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        from multiprocessing import shared_memory

        try:
            shm = shared_memory.SharedMemory(name=name)
        except (OSError, ValueError) as exc:
            raise RingPeerDead(f"ring segment {name!r} gone: "
                               f"{exc}") from exc
        _untrack(shm.name)
        if len(shm.buf) < _DATA_OFF:
            shm.close()
            raise RingError(f"ring segment {name!r} too small")
        magic, version, slots, slot_bytes = _HDR.unpack_from(shm.buf)
        if magic != _MAGIC or version != RING_VERSION:
            shm.close()
            raise RingError(
                f"ring segment {name!r} version skew: magic "
                f"0x{magic:08x} v{version}, this build speaks "
                f"v{RING_VERSION}")
        if len(shm.buf) < _DATA_OFF + slots * slot_bytes:
            shm.close()
            raise RingError(f"ring segment {name!r} truncated")
        return cls(shm, slots, slot_bytes, owned=False)

    @property
    def name(self) -> str:
        return self._shm.name

    # -- raw slot access (ring-protocol pass checks the ordering) -----

    def _slot_off(self, seq: int) -> int:
        return _DATA_OFF + ((seq - 1) % self.slots) * self.slot_bytes

    def _seq_write(self, off: int, seq: int) -> None:
        _SEQ.pack_into(self._buf, off, seq)

    def _seq_read(self, off: int) -> int:
        return _SEQ.unpack_from(self._buf, off)[0]

    def _payload_write(self, off: int, payload: bytes) -> None:
        _LEN.pack_into(self._buf, off + _SEQ.size, len(payload))
        _CRC.pack_into(self._buf, off + _SEQ.size + _LEN.size,
                       crc32c(payload))
        start = off + _SLOT_HDR
        self._buf[start:start + len(payload)] = payload

    def _len_read(self, off: int) -> int:
        return _LEN.unpack_from(self._buf, off + _SEQ.size)[0]

    def _crc_read(self, off: int) -> int:
        return _CRC.unpack_from(self._buf, off + _SEQ.size + _LEN.size)[0]

    def _payload_read(self, off: int, n: int) -> bytes:
        start = off + _SLOT_HDR
        return bytes(self._buf[start:start + n])

    def _load_ctr(self, ctr_off: int) -> int:
        return _CTR.unpack_from(self._buf, ctr_off)[0]

    def _store_ctr(self, ctr_off: int, value: int) -> None:
        _CTR.pack_into(self._buf, ctr_off, value)

    # -- the SPSC protocol --------------------------------------------

    def try_push(self, payload: bytes) -> bool:
        """Publish one frame, or False when the consumer still owns
        the oldest slot (full-ring backpressure). Payload first,
        sequence stamp LAST — the stamp is the commit."""
        if len(payload) > self.payload_max:
            raise RingFrameTooLarge(
                f"{len(payload)}-byte frame > {self.payload_max}-byte "
                f"slot payload (raise --shm_slot_bytes)")
        seq = self._produced + 1
        if seq - self._load_ctr(_CONSUMED_OFF) > self.slots:
            return False
        off = self._slot_off(seq)
        self._payload_write(off, payload)
        self._seq_write(off, seq)
        self._produced = seq
        self._store_ctr(_PRODUCED_OFF, seq)
        return True

    def try_pop(self) -> bytes | None:
        """Consume one frame, or None when nothing is published.
        Stamp, copy, RE-READ the stamp: a moved stamp means the
        producer overwrote an unconsumed slot (torn write)."""
        seq = self._consumed + 1
        off = self._slot_off(seq)
        got = self._seq_read(off)
        if got != seq:
            if got > seq:
                raise RingTornWrite(
                    f"slot stamp {got} from the future (expected "
                    f"{seq}) — the producer overwrote an unconsumed "
                    f"slot")
            return None
        n = self._len_read(off)
        if n > self.payload_max:
            raise RingTornWrite(f"slot {seq} declares {n} payload "
                                f"bytes > {self.payload_max} capacity")
        payload = self._payload_read(off, n)
        want = self._crc_read(off)
        if self._seq_read(off) != seq:
            raise RingTornWrite(f"slot {seq} re-stamped mid-copy")
        got_crc = crc32c(payload)
        if got_crc != want:
            # the stamp discipline held but the bytes are wrong: a
            # single-word corruption the seq re-read cannot see
            try:
                from pertgnn_tpu import telemetry
                telemetry.get_bus().counter("transport.crc_rejects")
            except Exception:  # lint: allow-silent-except
                # a telemetry hiccup must never mask the integrity
                # failure being reported
                pass
            err = RingTornWrite(
                f"slot {seq} payload crc 0x{got_crc:08x} != stamped "
                f"0x{want:08x} — {n}-byte frame corrupt in shared "
                f"memory")
            err.crc_mismatch = True
            raise err
        self._consumed = seq
        self._store_ctr(_CONSUMED_OFF, seq)
        return payload

    def close(self) -> None:
        self._buf = None
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass
        if self._owned:
            try:
                # re-register before unlink: when creator and attacher
                # share a process (tests, benches), _untrack removed
                # the CREATION registration too, and unlink's own
                # unregister would spam the tracker with KeyErrors —
                # registering is a set-add, so this is a no-op when
                # the registration is still there
                from multiprocessing import resource_tracker
                resource_tracker.register(
                    getattr(self._shm, "_name", f"/{self._shm.name}"),
                    "shared_memory")
            except Exception:  # lint: allow-silent-except
                pass
            try:
                self._shm.unlink()
            except (OSError, FileNotFoundError):
                pass


class RingServer:
    """The worker side: owns a request ring + a response ring + the
    doorbell listener, and services frames on one daemon thread (the
    single consumer/producer). ``handle`` maps a request frame's
    payload to a response payload; its failures are the CALLER's
    contract (fleet/transport.py answers refusal frames)."""

    def __init__(self, handle, slots: int, slot_bytes: int) -> None:
        self._handle = handle
        self._req = ShmRing.create(slots, slot_bytes)
        self._rsp = ShmRing.create(slots, slot_bytes)
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self._sock.settimeout(0.25)
        self._thread = threading.Thread(target=self._serve_loop,
                                        daemon=True,
                                        name="graftwire-ring")
        self._thread.start()

    def advertisement(self) -> dict:
        """What the probe body carries so the router can attach: the
        segment names, the doorbell port, and the pid (same-host
        evidence — the router refuses an advert it cannot attach)."""
        return {"req": self._req.name, "rsp": self._rsp.name,
                "bell_port": self._sock.getsockname()[1],
                "slots": self._req.slots,
                "slot_bytes": self._req.slot_bytes,
                "pid": os.getpid()}

    def _serve_loop(self) -> None:
        """select over the listener AND every live doorbell conn: a
        stale connection nobody explicitly closed (a removed worker's
        sender thread's thread-local client, freed only at GC) must
        never starve a fresh attach — the new client's tokens are
        serviced even while the old connection lingers."""
        conns: list[socket.socket] = []
        try:
            while not self._stop.is_set():
                try:
                    ready, _, _ = select.select([self._sock, *conns],
                                                [], [], 0.25)
                except (OSError, ValueError):
                    return  # listener closed: shutdown
                for sock_ in ready:
                    if sock_ is self._sock:
                        try:
                            conn, _ = self._sock.accept()
                        except (socket.timeout, OSError):
                            continue
                        conn.settimeout(0.25)
                        # bell tokens must never sit in Nagle's buffer
                        # behind a delayed ACK — the doorbell IS the
                        # latency path
                        conn.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        conns.append(conn)
                    elif not self._recv_token(sock_):
                        conns.remove(sock_)
                        self._hangup(sock_)
                # drain on every wakeup — token, fresh attach, or the
                # bounded poll (belt over the bell: tokens coalesce);
                # a broken drain drops every attached client (they
                # re-probe) but keeps listening
                if conns and not self._drain(conns):
                    for conn in conns:
                        self._hangup(conn)
                    conns.clear()
        finally:
            for conn in conns:
                self._hangup(conn)

    @staticmethod
    def _hangup(conn) -> None:
        try:
            conn.close()
        except OSError:
            pass

    @staticmethod
    def _recv_token(conn) -> bool:
        """One ready doorbell read; False means the peer hung up."""
        try:
            token = conn.recv(64)
        except socket.timeout:
            return True   # raced the readiness away: still alive
        except OSError:
            return False
        return bool(token)

    def _drain(self, conns: list) -> bool:
        while True:
            try:
                frame = self._req.try_pop()
            except RingError as exc:
                log.error("ring service: request ring broken: %s", exc)
                return False
            if frame is None or len(frame) < _CORR.size:
                return True
            reply = frame[:_CORR.size] + self._handle(
                bytes(frame[_CORR.size:]))
            deadline = time.monotonic() + 5.0
            while not self._rsp.try_push(reply):
                # response ring full: the client stopped draining —
                # bounded wait, then drop the peer (it re-probes)
                if self._stop.is_set() or time.monotonic() > deadline:
                    return False
                time.sleep(0.0005)
            # ring every live bell — only the current client matches
            # the correlation id; stale conns just get a benign token
            for conn in list(conns):
                try:
                    conn.sendall(b"!")
                except OSError:
                    conns.remove(conn)
                    self._hangup(conn)

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)
        self._req.close()
        self._rsp.close()


class RingClient:
    """The router side: attaches one worker's rings and drives the
    serial call protocol from that worker's OWN sender thread (the
    single producer/consumer — never share a client across threads)."""

    def __init__(self, advert: dict, connect_timeout_s: float = 2.0):
        self._req = ShmRing.attach(advert["req"])
        self._rsp = None
        self._bell = None
        try:
            self._rsp = ShmRing.attach(advert["rsp"])
            self._bell = socket.create_connection(
                ("127.0.0.1", int(advert["bell_port"])),
                timeout=connect_timeout_s)
            self._bell.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
        except RingError:
            self.close()
            raise
        except OSError as exc:
            self.close()
            raise RingPeerDead(f"doorbell connect failed: "
                               f"{exc}") from exc

    def call(self, payload: bytes, timeout_s: float) -> bytes:
        """One bounded round trip. Raises RingTimeout past the
        deadline, RingPeerDead on a reset doorbell, RingTornWrite on a
        broken slot — the transport maps all of them to the
        lost-worker path, so every router Future still resolves."""
        # the correlation id IS the request's ring sequence number:
        # sequences live in shared memory and only ever advance, so an
        # id can never collide across attaches — a late response to a
        # call an earlier (since-dropped) client abandoned in the ring
        # always mismatches and is discarded below, never accepted as
        # THIS call's predictions
        corr = _CORR.pack(self._req._produced + 1)
        deadline = time.monotonic() + timeout_s
        while not self._req.try_push(corr + payload):
            self._await_bell(deadline, "request ring full")
        self._ring_bell()
        while True:
            got = self._rsp.try_pop()
            if got is None:
                self._await_bell(deadline, "awaiting the response")
                continue
            if got[:_CORR.size] == corr:
                return bytes(got[_CORR.size:])
            # a stale response to a call an earlier deadline abandoned
            log.debug("ring client: dropped stale response")

    def _ring_bell(self) -> None:
        try:
            self._bell.sendall(b"!")
        except OSError as exc:
            raise RingPeerDead(f"doorbell send failed: {exc}") from exc

    def _await_bell(self, deadline: float, why: str) -> None:
        """Bounded wait for the peer's token — select, never spin; EOF
        and reset are the peer-death signal."""
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RingTimeout(f"ring call timed out ({why})")
        try:
            ready, _, _ = select.select([self._bell], [], [],
                                        min(remaining, 0.25))
        except (OSError, ValueError) as exc:
            raise RingPeerDead(f"doorbell lost: {exc}") from exc
        if ready:
            try:
                token = self._bell.recv(4096)
            except OSError as exc:
                raise RingPeerDead(f"doorbell reset: {exc}") from exc
            if not token:
                raise RingPeerDead("ring peer closed the doorbell")

    def close(self) -> None:
        if self._bell is not None:
            try:
                self._bell.close()
            except OSError:
                pass
        self._req.close()
        if self._rsp is not None:
            self._rsp.close()
