"""Fleet dispatch policy — PURE FUNCTIONS over immutable worker views.

The router's three decisions (which worker takes the next microbatch,
whether a deadline is feasible at the door, how lost work re-enters the
queue) are load-bearing claims about the fleet's behavior under load
and failure, so they live here as pure functions of explicit inputs —
unit-testable with no subprocesses, no sockets, no clocks
(tests/test_fleet.py). The router (fleet/router.py) owns the mutable
state and calls these at each decision point.

**Least-loaded = earliest predicted completion.** A worker's predicted
completion for a NEW batch is ``(inflight_batches + 1) * ewma_batch_s``
— queue-depth-times-service-time, the classic M/M/1-ish estimate. It
deliberately folds BOTH signals the ISSUE names: in-flight depth (how
much is queued there) and recent latency (how fast this worker drains).
A uniformly fast fleet degenerates to join-the-shortest-queue; a
straggler (hot device, noisy neighbor) organically receives less work
without any explicit weight knob.

**Deadline feasibility at the door.** With per-request deadlines on,
a request whose deadline even the BEST worker's predicted completion
cannot meet is shed at submit — failing in microseconds instead of
occupying a pending slot for milliseconds and failing anyway. This is
an estimate, not a guarantee: an admitted request can still expire in
the queue (the router resolves it with the same DeadlineExceeded).

**Requeue ordering.** Requests carry a monotone submission sequence
number. Work recovered from a lost worker re-enters AT THE FRONT of
the pending queue in submission order: a requeued request is by
construction older than everything still pending (batches are taken
in prefix order), so sorting the recovered set by sequence and
prepending restores the global submission order exactly — the
invariant tests/test_fleet.py pins across multi-loss interleavings.
"""

from __future__ import annotations

import dataclasses
import math

# Conservative prior for a worker that has never reported a batch
# latency (fresh member): pessimistic enough that the first few
# dispatches spread across fresh workers rather than pile on one.
DEFAULT_BATCH_S = 0.05


@dataclasses.dataclass(frozen=True)
class WorkerView:
    """One worker as the policy sees it — an immutable snapshot the
    router builds under its lock and hands to the pure functions."""

    worker_id: str
    healthy: bool = True
    # Microbatches dispatched to this worker and not yet completed.
    inflight_batches: int = 0
    # Requests inside those batches (tie-break refinement only).
    inflight_requests: int = 0
    # EWMA of this worker's recent per-batch wall latency (seconds).
    ewma_batch_s: float = DEFAULT_BATCH_S
    # Outstanding-batch slots (FleetConfig.worker_slots): at capacity
    # the worker is skipped even if it predicts earliest completion —
    # stacking a queue behind one worker defeats the fleet.
    slots: int = 2


def predicted_completion_s(w: WorkerView) -> float:
    """Seconds until a new batch handed to `w` would complete: its
    backlog plus the new batch, each at its recent service time."""
    return (w.inflight_batches + 1) * max(w.ewma_batch_s, 1e-6)


def choose_worker(workers, exclude=frozenset()) -> WorkerView | None:
    """The healthy, non-saturated worker with the earliest predicted
    completion; ties break on fewer in-flight requests then worker_id
    (total order — dispatch is deterministic given the same views).
    None when every healthy worker is at its slot capacity (the router
    waits for a completion) or no worker is healthy (the router waits
    for membership to recover, or drains on close).

    ``exclude`` is the retry-exclusion set (the rollout's excluded-slot
    pattern applied to dispatch): a batch recovered from a lost worker
    carries that worker's id, so a FLAPPING worker — lost on transport,
    re-admitted by the next probe — cannot eat the same request twice.
    The caller falls back to an exclusion-free choice when exclusion
    leaves nobody (one surviving-but-flapping worker still beats
    failing the request outright)."""
    eligible = [w for w in workers
                if w.healthy and w.inflight_batches < w.slots
                and w.worker_id not in exclude]
    if not eligible:
        return None
    return min(eligible, key=lambda w: (predicted_completion_s(w),
                                        w.inflight_requests, w.worker_id))


def choose_hedge_worker(workers, exclude=frozenset()) -> WorkerView | None:
    """The second-opinion worker for a hedged re-dispatch: healthy, not
    the primary (``exclude``), earliest predicted completion. A hedge
    may use ONE slot past the worker's cap (`slots + 1`): hedges exist
    to cut tail latency, and refusing every hedge whenever the fleet is
    busy — exactly when stragglers appear — would disable the mechanism
    at the moment it pays; the +1 bound still prevents hedge pile-up."""
    eligible = [w for w in workers
                if w.healthy and w.worker_id not in exclude
                and w.inflight_batches < w.slots + 1]
    if not eligible:
        return None
    return min(eligible, key=lambda w: (predicted_completion_s(w),
                                        w.inflight_requests, w.worker_id))


# Adaptive hedging needs a latency distribution before it can pick a
# quantile; below this many completed batches the threshold is +inf
# (hedge nothing) rather than a guess off two samples.
HEDGE_MIN_SAMPLES = 20


def hedge_threshold_s(fixed_ms: float, quantile: float,
                      recent_batch_s) -> float:
    """Seconds a dispatched batch may run before the router hedges it.

    ``fixed_ms`` > 0 wins (an explicit --hedge_quantile_ms operator
    override); else ``quantile`` in (0, 1) adapts the threshold to the
    observed per-batch round-trip distribution (``recent_batch_s``, a
    recency window of completed-batch wall times): hedge whatever runs
    past the rolling q-quantile. Returns +inf (never hedge) when
    neither is configured or the sample set is still too small —
    hedging must not fire off noise."""
    if fixed_ms > 0:
        return fixed_ms / 1e3
    if not 0.0 < quantile < 1.0:
        return math.inf
    samples = sorted(recent_batch_s)
    if len(samples) < HEDGE_MIN_SAMPLES:
        return math.inf
    pos = quantile * (len(samples) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(samples) - 1)
    return samples[lo] + (samples[hi] - samples[lo]) * (pos - lo)


def deadline_infeasible(workers, now: float, deadline_abs: float) -> bool:
    """True when NO healthy worker's predicted completion meets the
    deadline — the door-shed test. Saturated-but-healthy workers still
    count (their backlog is in the prediction); an empty healthy set is
    infeasible by definition (nobody could ever serve it)."""
    candidates = [w for w in workers if w.healthy]
    if not candidates:
        return True
    return now + min(predicted_completion_s(w)
                     for w in candidates) > deadline_abs


def merge_requeue(pending, recovered, seq=lambda r: r.seq):
    """Work recovered from a lost worker, merged back into the pending
    queue in GLOBAL SUBMISSION ORDER: every request carries a monotone
    submission seq, so one sort restores exactly the order callers
    submitted in. Recovered requests predate everything still pending
    (batches dispatch in prefix order), so they land in front; and two
    workers lost back-to-back interleave their recovered batches
    correctly — an earlier-dispatched batch recovered SECOND still
    re-enters ahead of a later-dispatched one recovered first (a
    naive prepend would let the younger batch cut the line). Returns a
    new list; both inputs untouched (pure)."""
    return sorted([*recovered, *pending], key=seq)


def probe_transition(healthy: bool, consecutive_failures: int,
                     probe_ok: bool, lost_after: int
                     ) -> tuple[bool, int, str | None]:
    """Membership state machine for ONE probe result, as a pure
    function: (healthy', consecutive_failures', event) where event is
    "lost" | "recovered" | None. A healthy member is excluded after
    `lost_after` CONSECUTIVE probe failures (one dropped poll must not
    flap a live worker); an excluded member is re-admitted on the
    first successful probe (it answered its readiness probe — by the
    PR-4 contract that means warm and admitting)."""
    if probe_ok:
        return True, 0, (None if healthy else "recovered")
    failures = consecutive_failures + 1
    if healthy and failures >= lost_after:
        return False, failures, "lost"
    return healthy and failures < lost_after, failures, None
