"""HTTP transport between the fleet router and its serve workers.

The engineered parts of the fleet are the policy (fleet/policy.py) and
the router's recovery machinery (fleet/router.py); the wire is
deliberately boring — stdlib HTTP on 127.0.0.1, JSON microbatches — so
there is nothing to install, nothing to configure, and nothing that can
hold a connection's state hostage (every dispatch is one independent
POST the router can time out and retry elsewhere). One worker = one
``WorkerServer`` wrapping the PR-4-hardened engine+queue stack:

- ``GET  /healthz`` — the shared readiness probe (serve/health.py) plus
  worker identity and warm-start evidence (compiles / deserialized /
  arena_warm), which is how fleet_bench proves workers started warm
  without scraping their telemetry;
- ``POST /predict`` — one microbatch ``{"entries": [...], "ts_buckets":
  [...]}`` in, per-request rows out: ``{"pred": <float>}`` or
  ``{"error": "<serve/errors.py class>", "message": ...}``. The handler
  submits each request to the worker's own MicrobatchQueue and waits,
  so EVERY PR-4 invariant (admission control, quarantine, watchdog,
  NaN guard) applies per worker unchanged; typed failures travel by
  CLASS NAME and are re-raised as the same types router-side.

Failure mapping (the contract fleet/router.py relies on):

- transport-level failure — connection refused/reset, timeout, non-200
  — means THE WORKER is unusable (``WorkerTransportError``): the
  router marks it lost and requeues the batch to survivors;
- a 200 with per-request ``error`` rows means the WORKER is fine and
  those REQUESTS failed: ``QueueClosed`` rows (a draining worker) are
  retryable elsewhere, everything else is the request's own typed
  outcome and propagates to the caller.

The ``fleet.worker`` fault-injection site fires per handled microbatch
(pertgnn_tpu/testing/faults.py): ``error`` fails the call at transport
level, ``wedge`` stalls it into the router's dispatch timeout, and
``kill`` enacts ``os._exit(137)`` — the deterministic worker-death
drill behind the chaos scenario in benchmarks/fleet_bench.py.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pertgnn_tpu.lens.request import LensRequest, LensResult
from pertgnn_tpu.serve import errors as serve_errors
from pertgnn_tpu.serve.health import probe_payload
from pertgnn_tpu.testing import faults

log = logging.getLogger(__name__)


def pred_to_wire(pred):
    """A prediction as it rides a result row: a float (single-tau) or a
    list of floats (a multi-quantile vector). JSON float round-trips
    are exact in Python, so the fleet's bit-identity contract survives
    the wire for vectors exactly as it always has for scalars."""
    import numpy as np

    if np.ndim(pred) == 0:
        return float(pred)
    return [float(x) for x in np.asarray(pred)]


def result_from_row(row: dict):
    """Rehydrate one OK result row into what a single-process caller's
    Future would have resolved to: a float, a (T,) float32 vector, or a
    LensResult carrying attribution rows — the fleet front door's
    contract matches the queue's by construction."""
    import numpy as np

    pred = row["pred"]
    val = (np.asarray(pred, np.float32) if isinstance(pred, list)
           else float(pred))
    if "attr" in row:
        return LensResult(pred=val,
                          attribution=tuple(dict(r)
                                            for r in row["attr"]))
    return val


class WorkerTransportError(RuntimeError):
    """The worker call failed at TRANSPORT level (refused, reset, timed
    out, non-200): the router cannot tell whether the worker is dead,
    wedged, or gone — it marks the worker lost and requeues the batch
    to the survivors. Request-level failures never raise this; they
    ride the 200 response as typed per-request rows."""


def error_from_row(row: dict) -> Exception:
    """Rehydrate a per-request error row into the SAME typed exception
    the worker's queue raised, so a fleet caller handles shed/deadline/
    quarantine identically to a single-process caller. Unknown names
    (version skew) degrade to ServeError, never to a silent drop."""
    cls = getattr(serve_errors, str(row.get("error", "")), None)
    if cls is None or not (isinstance(cls, type)
                           and issubclass(cls, serve_errors.ServeError)):
        cls = serve_errors.ServeError
    return cls(row.get("message", "worker-reported failure"))


class WorkerServer:
    """One serve worker's wire surface over its engine + queue."""

    def __init__(self, engine, queue, port: int = 0, extra_fn=None):
        self._engine = engine
        self._queue = queue
        self._extra_fn = extra_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                ready, body = probe_payload(
                    outer._engine, outer._queue,
                    outer._extra_fn() if outer._extra_fn else None)
                self._reply(200 if ready else 503, body)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    results = outer._predict(req["entries"],
                                             req["ts_buckets"],
                                             req.get("trace"),
                                             req.get("slo"),
                                             req.get("dg"),
                                             req.get("lens"))
                except faults.InjectedFault as exc:
                    # the armed chaos plan asked for a transport-level
                    # failure: the router must see this worker as lost
                    log.warning("worker: injected transport failure: %s",
                                exc)
                    self._reply(500, {"error": "InjectedFault",
                                      "message": str(exc)})
                    return
                except Exception as exc:
                    # an unexpected handler bug must not strand the
                    # router's futures: answer 500 (router requeues)
                    log.exception("worker: request handler failed")
                    self._reply(500, {"error": type(exc).__name__,
                                      "message": str(exc)})
                    return
                self._reply(200, {"results": results})

            def _reply(self, status: int, body: dict):
                payload = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):  # the router polls; don't spam
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="fleet-worker")
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def _predict(self, entries, ts_buckets, trace: list | None = None,
                 slo: list | None = None,
                 dg: list | None = None,
                 lens: list | None = None) -> list[dict]:
        """Submit one router microbatch to the local queue and wait —
        per-request rows in request order, every row present (a
        submitted Future ALWAYS resolves; a rejected submit IS the
        row's outcome). ``trace`` is the router's per-request trace
        propagation: None, or one ``{"tid", "psid"}``/null per request
        — the worker's stage spans parent under the router's transport
        span (``psid``), so graftscope can join the two processes'
        JSONL files into one request tree. ``slo``/``dg`` are the
        per-request SLO class names and brownout-downgrade flags
        (fleet/shield.py), and ``lens`` the per-request lens variant
        dicts (pertgnn_tpu/lens/: attribution k + what-if edits) —
        all omitted entirely for all-default traffic."""
        plan = faults.active()
        if plan is not None:
            verdict = plan.fire("fleet.worker", entry_ids=entries)
            if verdict == "kill":
                # the worker-death drill: indistinguishable from
                # SIGKILL to the router (connection dies mid-call)
                log.error("fault injection: fleet.worker kill — exiting")
                os._exit(137)
        if trace is None or len(trace) != len(entries):
            trace = [None] * len(entries)
        if slo is None or len(slo) != len(entries):
            slo = [None] * len(entries)
        if dg is None or len(dg) != len(entries):
            dg = [False] * len(entries)
        if lens is None or len(lens) != len(entries):
            lens = [None] * len(entries)
        futures = []
        for eid, tsb, t, s, d, ln in zip(entries, ts_buckets, trace,
                                         slo, dg, lens):
            ctx = (self._engine.bus.adopt_trace(t["tid"], t["psid"])
                   if isinstance(t, dict) else None)
            try:
                futures.append(self._queue.submit(
                    int(eid), int(tsb), trace=ctx, slo=s,
                    downgrade=bool(d), lens=LensRequest.from_wire(ln)))
            except serve_errors.ServeError as exc:
                futures.append(exc)  # admission outcome, row below
        rows: list[dict] = []
        for fut in futures:
            if isinstance(fut, Exception):
                rows.append({"error": type(fut).__name__,
                             "message": str(fut)})
                continue
            try:
                res = fut.result()
                if isinstance(res, LensResult):
                    rows.append({"pred": pred_to_wire(res.pred),
                                 "attr": list(res.attribution)})
                else:
                    rows.append({"pred": pred_to_wire(res)})
            except Exception as exc:  # lint: allow-silent-except — the row IS the record; the router rehydrates it
                rows.append({"error": type(exc).__name__,
                             "message": str(exc)})
        return rows

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# -- router-side client ---------------------------------------------------

def post_predict(base_url: str, entries, ts_buckets,
                 timeout_s: float, trace: list | None = None,
                 slo: list | None = None,
                 dg: list | None = None,
                 lens: list | None = None) -> list[dict]:
    """One microbatch dispatch; returns per-request rows. Raises
    WorkerTransportError on ANY transport-level failure (the lost-worker
    signature). ``trace`` propagates per-request trace contexts (one
    ``{"tid", "psid"}`` or None per request); omitted entirely when no
    request in the batch is head-sampled, so untraced traffic pays zero
    wire bytes. ``slo`` (per-request class names), ``dg`` (brownout
    downgrade flags), and ``lens`` (per-request lens variant dicts —
    LensRequest.to_wire) follow the same omit-when-default rule."""
    payload = {"entries": [int(e) for e in entries],
               "ts_buckets": [int(t) for t in ts_buckets]}
    if trace is not None and any(t is not None for t in trace):
        payload["trace"] = trace
    if slo is not None and any(s is not None for s in slo):
        payload["slo"] = slo
    if dg is not None and any(dg):
        payload["dg"] = [bool(d) for d in dg]
    if lens is not None and any(ln is not None for ln in lens):
        payload["lens"] = lens
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{base_url}/predict", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            payload = json.loads(resp.read())
    except Exception as exc:
        # urllib raises HTTPError on non-200 and URLError/socket
        # timeouts on dead transports — all the same verdict here
        raise WorkerTransportError(
            f"worker {base_url} dispatch failed: "
            f"{type(exc).__name__}: {exc}") from exc
    results = payload.get("results")
    if not isinstance(results, list) or len(results) != len(entries):
        got = len(results) if isinstance(results, list) else "no"
        raise WorkerTransportError(
            f"worker {base_url} answered {got} rows for a "
            f"{len(entries)}-request batch")
    return results


def get_probe(base_url: str, timeout_s: float) -> tuple[int, dict]:
    """(status, body) of one readiness probe. Raises
    WorkerTransportError when nothing answers (a 503 ANSWERS — a
    draining worker is reachable-but-not-ready, which membership
    treats differently from gone)."""
    try:
        with urllib.request.urlopen(f"{base_url}/healthz",
                                    timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read())
        except ValueError:
            body = {}
        return exc.code, body
    except Exception as exc:
        raise WorkerTransportError(
            f"worker {base_url} probe failed: "
            f"{type(exc).__name__}: {exc}") from exc
