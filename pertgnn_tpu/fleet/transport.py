"""HTTP transport between the fleet router and its serve workers.

The engineered parts of the fleet are the policy (fleet/policy.py) and
the router's recovery machinery (fleet/router.py); the wire is
deliberately boring — stdlib HTTP on 127.0.0.1, JSON microbatches — so
there is nothing to install, nothing to configure, and nothing that can
hold a connection's state hostage (every dispatch is one independent
POST the router can time out and retry elsewhere). One worker = one
``WorkerServer`` wrapping the PR-4-hardened engine+queue stack:

- ``GET  /healthz`` — the shared readiness probe (serve/health.py) plus
  worker identity and warm-start evidence (compiles / deserialized /
  arena_warm), which is how fleet_bench proves workers started warm
  without scraping their telemetry;
- ``POST /predict`` — one microbatch ``{"entries": [...], "ts_buckets":
  [...]}`` in, per-request rows out: ``{"pred": <float>}`` or
  ``{"error": "<serve/errors.py class>", "message": ...}``. The handler
  submits each request to the worker's own MicrobatchQueue and waits,
  so EVERY PR-4 invariant (admission control, quarantine, watchdog,
  NaN guard) applies per worker unchanged; typed failures travel by
  CLASS NAME and are re-raised as the same types router-side.

Failure mapping (the contract fleet/router.py relies on):

- transport-level failure — connection refused/reset, timeout, non-200
  — means THE WORKER is unusable (``WorkerTransportError``): the
  router marks it lost and requeues the batch to survivors;
- a 200 with per-request ``error`` rows means the WORKER is fine and
  those REQUESTS failed: ``QueueClosed`` rows (a draining worker) are
  retryable elsewhere, everything else is the request's own typed
  outcome and propagates to the caller.

The ``fleet.worker`` fault-injection site fires per handled microbatch
(pertgnn_tpu/testing/faults.py): ``error`` fails the call at transport
level, ``wedge`` stalls it into the router's dispatch timeout, and
``kill`` enacts ``os._exit(137)`` — the deterministic worker-death
drill behind the chaos scenario in benchmarks/fleet_bench.py.

Since ISSUE 16 the boring wire is the DEFAULT, not the ceiling: the
graftwire data plane (fleet/wire.py + fleet/shmring.py, selected via
``FleetConfig.transport``) layers a versioned binary frame codec and a
same-host shared-memory ring transport over the SAME contract —
:class:`FleetTransport` below negotiates per worker at probe time and
degrades loudly to this file's JSON wire whenever the capability is
missing, so every failure map above survives verbatim on every wire.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pertgnn_tpu.fleet import shmring, wire
from pertgnn_tpu.lens.request import LensRequest, LensResult
from pertgnn_tpu.serve import errors as serve_errors
from pertgnn_tpu.serve.health import probe_payload
from pertgnn_tpu.testing import faults

log = logging.getLogger(__name__)


def pred_to_wire(pred):
    """A prediction as it rides a result row: a float (single-tau) or a
    list of floats (a multi-quantile vector). JSON float round-trips
    are exact in Python, so the fleet's bit-identity contract survives
    the wire for vectors exactly as it always has for scalars."""
    import numpy as np

    if np.ndim(pred) == 0:
        return float(pred)
    return [float(x) for x in np.asarray(pred)]


def result_from_row(row: dict):
    """Rehydrate one OK result row into what a single-process caller's
    Future would have resolved to: a float, a (T,) float32 vector, or a
    LensResult carrying attribution rows — the fleet front door's
    contract matches the queue's by construction."""
    import numpy as np

    pred = row["pred"]
    val = (np.asarray(pred, np.float32) if isinstance(pred, list)
           else float(pred))
    if "attr" in row:
        return LensResult(pred=val,
                          attribution=tuple(dict(r)
                                            for r in row["attr"]))
    return val


class WorkerTransportError(RuntimeError):
    """The worker call failed at TRANSPORT level (refused, reset, timed
    out, non-200): the router cannot tell whether the worker is dead,
    wedged, or gone — it marks the worker lost and requeues the batch
    to the survivors. Request-level failures never raise this; they
    ride the 200 response as typed per-request rows."""


def error_from_row(row: dict) -> Exception:
    """Rehydrate a per-request error row into the SAME typed exception
    the worker's queue raised, so a fleet caller handles shed/deadline/
    quarantine identically to a single-process caller. Unknown names
    (version skew) degrade to ServeError, never to a silent drop."""
    cls = getattr(serve_errors, str(row.get("error", "")), None)
    if cls is None or not (isinstance(cls, type)
                           and issubclass(cls, serve_errors.ServeError)):
        cls = serve_errors.ServeError
    return cls(row.get("message", "worker-reported failure"))


class WorkerServer:
    """One serve worker's wire surface over its engine + queue. Speaks
    BOTH HTTP wires on /predict (JSON and the graftwire binary frame,
    selected per request by Content-Type — capability, not
    configuration, so a mixed fleet never hard-fails) and, when
    constructed with ``transport="shm"``, additionally services a
    shared-memory ring pair (fleet/shmring.py) advertised in the probe
    body for the router to attach at negotiation time."""

    def __init__(self, engine, queue, port: int = 0, extra_fn=None,
                 transport: str = "json", shm_ring_slots: int = 8,
                 shm_slot_bytes: int = 65536):
        self._engine = engine
        self._queue = queue
        self._extra_fn = extra_fn
        # closed-server latch: shutdown() stops NEW connections, but a
        # keep-alive handler thread already parked on an open pooled
        # connection (FleetTransport) would keep serving this worker's
        # CLOSED queue forever — the in-process twin of a drained
        # subprocess whose sockets the OS would have torn down.  The
        # latch makes such a thread answer 503 and drop its connection,
        # so the router sees the standard lost-worker signature
        # (WorkerTransportError -> reconnect) instead of a split-brain.
        self._closing = False
        self._ring = None
        if transport == "shm":
            self._ring = shmring.RingServer(self._handle_frame,
                                            shm_ring_slots,
                                            shm_slot_bytes)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: the router's pooled per-worker
            # connections (FleetTransport) reuse one TCP stream; 1.0
            # would close after every reply and the A/B against
            # binary/shm would be measuring TCP handshakes
            protocol_version = "HTTP/1.1"
            # Nagle + delayed ACK on a keep-alive stream turns every
            # reply into a ~40ms stall; replies must leave NOW
            disable_nagle_algorithm = True

            def do_GET(self):
                if outer._closing:
                    self.close_connection = True
                    self._reply(503, {"error": "WorkerClosing",
                                      "message": "worker shut down"})
                    return
                ready, body = probe_payload(
                    outer._engine, outer._queue,
                    outer._extra_fn() if outer._extra_fn else None)
                # transport negotiation rides the existing probe: the
                # wire version always, the ring advert when one exists
                body["wire_version"] = wire.WIRE_VERSION
                if outer._ring is not None:
                    body["shm"] = outer._ring.advertisement()
                self._reply(200 if ready else 503, body)

            def do_POST(self):
                if outer._closing:
                    self.close_connection = True
                    self._reply(503, {"error": "WorkerClosing",
                                      "message": "worker shut down"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                ctype = self.headers.get("Content-Type", "")
                binary = ctype.startswith(wire.CONTENT_TYPE)
                try:
                    req = (wire.decode_request(raw) if binary
                           else json.loads(raw))
                except (wire.WireFormatError, ValueError) as exc:
                    # typed refusal, never a crash: a skewed/corrupt
                    # frame answers 400 and the client renegotiates
                    self._reply(400, {"error": type(exc).__name__,
                                      "message": str(exc)})
                    return
                try:
                    results = outer._predict(req["entries"],
                                             req["ts_buckets"],
                                             req.get("trace"),
                                             req.get("slo"),
                                             req.get("dg"),
                                             req.get("lens"))
                except faults.InjectedFault as exc:
                    # the armed chaos plan asked for a transport-level
                    # failure: the router must see this worker as lost
                    log.warning("worker: injected transport failure: %s",
                                exc)
                    self._reply(500, {"error": "InjectedFault",
                                      "message": str(exc)})
                    return
                except Exception as exc:
                    # an unexpected handler bug must not strand the
                    # router's futures: answer 500 (router requeues)
                    log.exception("worker: request handler failed")
                    self._reply(500, {"error": type(exc).__name__,
                                      "message": str(exc)})
                    return
                if binary:
                    self._reply_raw(200, wire.encode_response(results),
                                    wire.CONTENT_TYPE)
                else:
                    self._reply(200, {"results": results})

            def _reply(self, status: int, body: dict):
                self._reply_raw(status, json.dumps(body).encode(),
                                "application/json")

            def _reply_raw(self, status: int, payload: bytes,
                           ctype: str):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):  # the router polls; don't spam
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="fleet-worker")
        self._thread.start()

    def _handle_frame(self, frame: bytes) -> bytes:
        """The ring service callback: one request frame in, one
        response/refusal frame out. Mirrors do_POST's failure map —
        a decode failure or handler bug becomes a typed refusal frame
        (the ring's 400/500), which the router-side transport raises
        as WorkerTransportError; the ``kill`` fault fires inside
        _predict exactly as it does for HTTP, so the worker-death
        drill covers this wire too."""
        try:
            req = wire.decode_request(frame)
        except wire.WireFormatError as exc:
            return wire.encode_refusal(type(exc).__name__, str(exc))
        try:
            results = self._predict(req["entries"], req["ts_buckets"],
                                    req.get("trace"), req.get("slo"),
                                    req.get("dg"), req.get("lens"))
        except faults.InjectedFault as exc:
            log.warning("worker: injected ring failure: %s", exc)
            return wire.encode_refusal("InjectedFault", str(exc))
        except Exception as exc:
            log.exception("worker: ring handler failed")
            return wire.encode_refusal(type(exc).__name__, str(exc))
        return wire.encode_response(results)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def _predict(self, entries, ts_buckets, trace: list | None = None,
                 slo: list | None = None,
                 dg: list | None = None,
                 lens: list | None = None) -> list[dict]:
        """Submit one router microbatch to the local queue and wait —
        per-request rows in request order, every row present (a
        submitted Future ALWAYS resolves; a rejected submit IS the
        row's outcome). ``trace`` is the router's per-request trace
        propagation: None, or one ``{"tid", "psid"}``/null per request
        — the worker's stage spans parent under the router's transport
        span (``psid``), so graftscope can join the two processes'
        JSONL files into one request tree. ``slo``/``dg`` are the
        per-request SLO class names and brownout-downgrade flags
        (fleet/shield.py), and ``lens`` the per-request lens variant
        dicts (pertgnn_tpu/lens/: attribution k + what-if edits) —
        all omitted entirely for all-default traffic."""
        plan = faults.active()
        if plan is not None:
            verdict = plan.fire("fleet.worker", entry_ids=entries)
            if verdict == "kill":
                # the worker-death drill: indistinguishable from
                # SIGKILL to the router (connection dies mid-call)
                log.error("fault injection: fleet.worker kill — exiting")
                os._exit(137)
        if trace is None or len(trace) != len(entries):
            trace = [None] * len(entries)
        if slo is None or len(slo) != len(entries):
            slo = [None] * len(entries)
        if dg is None or len(dg) != len(entries):
            dg = [False] * len(entries)
        if lens is None or len(lens) != len(entries):
            lens = [None] * len(entries)
        futures = []
        for eid, tsb, t, s, d, ln in zip(entries, ts_buckets, trace,
                                         slo, dg, lens):
            ctx = (self._engine.bus.adopt_trace(t["tid"], t["psid"])
                   if isinstance(t, dict) else None)
            try:
                futures.append(self._queue.submit(
                    int(eid), int(tsb), trace=ctx, slo=s,
                    downgrade=bool(d), lens=LensRequest.from_wire(ln)))
            except serve_errors.ServeError as exc:
                futures.append(exc)  # admission outcome, row below
        rows: list[dict] = []
        for fut in futures:
            if isinstance(fut, Exception):
                rows.append({"error": type(fut).__name__,
                             "message": str(fut)})
                continue
            try:
                res = fut.result()
                if isinstance(res, LensResult):
                    rows.append({"pred": pred_to_wire(res.pred),
                                 "attr": list(res.attribution)})
                else:
                    rows.append({"pred": pred_to_wire(res)})
            except Exception as exc:  # lint: allow-silent-except — the row IS the record; the router rehydrates it
                rows.append({"error": type(exc).__name__,
                             "message": str(exc)})
        return rows

    def close(self) -> None:
        self._closing = True
        if self._ring is not None:
            self._ring.close()
        self._server.shutdown()
        self._server.server_close()


# -- router-side client ---------------------------------------------------

def post_predict(base_url: str, entries, ts_buckets,
                 timeout_s: float, trace: list | None = None,
                 slo: list | None = None,
                 dg: list | None = None,
                 lens: list | None = None) -> list[dict]:
    """One microbatch dispatch; returns per-request rows. Raises
    WorkerTransportError on ANY transport-level failure (the lost-worker
    signature). ``trace`` propagates per-request trace contexts (one
    ``{"tid", "psid"}`` or None per request); omitted entirely when no
    request in the batch is head-sampled, so untraced traffic pays zero
    wire bytes. ``slo`` (per-request class names), ``dg`` (brownout
    downgrade flags), and ``lens`` (per-request lens variant dicts —
    LensRequest.to_wire) follow the same omit-when-default rule."""
    payload = {"entries": [int(e) for e in entries],
               "ts_buckets": [int(t) for t in ts_buckets]}
    if trace is not None and any(t is not None for t in trace):
        payload["trace"] = trace
    if slo is not None and any(s is not None for s in slo):
        payload["slo"] = slo
    if dg is not None and any(dg):
        payload["dg"] = [bool(d) for d in dg]
    if lens is not None and any(ln is not None for ln in lens):
        payload["lens"] = lens
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{base_url}/predict", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            payload = json.loads(resp.read())
    except Exception as exc:
        # urllib raises HTTPError on non-200 and URLError/socket
        # timeouts on dead transports — all the same verdict here
        raise WorkerTransportError(
            f"worker {base_url} dispatch failed: "
            f"{type(exc).__name__}: {exc}") from exc
    results = payload.get("results")
    if not isinstance(results, list) or len(results) != len(entries):
        got = len(results) if isinstance(results, list) else "no"
        raise WorkerTransportError(
            f"worker {base_url} answered {got} rows for a "
            f"{len(entries)}-request batch")
    return results


def get_probe(base_url: str, timeout_s: float) -> tuple[int, dict]:
    """(status, body) of one readiness probe. Raises
    WorkerTransportError when nothing answers (a 503 ANSWERS — a
    draining worker is reachable-but-not-ready, which membership
    treats differently from gone)."""
    try:
        with urllib.request.urlopen(f"{base_url}/healthz",
                                    timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read())
        except ValueError:
            body = {}
        return exc.code, body
    except Exception as exc:
        raise WorkerTransportError(
            f"worker {base_url} probe failed: "
            f"{type(exc).__name__}: {exc}") from exc


class FleetTransport:
    """The graftwire dispatch client — one per router, ``post``-
    signature-compatible with :func:`post_predict` so the router's
    sender loops and every injected test transport stay untouched.

    Mode selects the PREFERRED wire; what a given worker actually
    speaks is negotiated once per URL off its probe body and degrades
    LOUDLY (counter ``transport.fallback``), never silently:

    - ``json`` — the legacy JSON body, now over a pooled persistent
      HTTP/1.1 connection per (sender thread, worker) with
      reconnect-on-error (counter ``transport.reconnects``) instead of
      a fresh TCP handshake per POST;
    - ``binary`` — graftwire frames (fleet/wire.py) over the same
      pooled HTTP; a worker that does not advertise ``wire_version``
      falls back to json;
    - ``shm`` — frames over the worker's advertised shared-memory
      rings (fleet/shmring.py); no advert / failed attach / oversize
      frame falls back to binary HTTP (per-worker sticky or per-call,
      by cause), and any ring failure mid-flight maps to
      WorkerTransportError — the existing lost-worker path.

    Thread custody mirrors the ring's SPSC contract: connections and
    ring clients live in thread-local maps, so each router sender
    thread owns its transport endpoints exclusively; the shared
    negotiation cache is the only locked state and the lock never
    covers a blocking call (graftsync lock-order proves it). Byte
    accounting (``transport.bytes_out/bytes_in``, tagged
    ``wire=json|binary|shm``) hangs the A/B evidence on every hop."""

    def __init__(self, mode: str = "json", probe=get_probe, bus=None,
                 connect_timeout_s: float = 2.0):
        if mode not in ("json", "binary", "shm"):
            raise ValueError(f"unknown transport mode {mode!r}")
        self.mode = mode
        self._probe = probe
        self._injected_bus = bus
        self._connect_timeout_s = connect_timeout_s
        self._local = threading.local()
        self._lock = threading.Lock()
        self._neg: dict[str, dict] = {}     # url -> negotiated state
        self._gen: dict[str, int] = {}      # url -> forget() epoch
        self._last_wire: dict[str, str] = {}
        self._endpoints: list = []          # every conn/ring, for close

    @property
    def bus(self):
        if self._injected_bus is not None:
            return self._injected_bus
        from pertgnn_tpu import telemetry
        return telemetry.get_bus()

    # -- negotiation ---------------------------------------------------

    def _negotiate(self, base_url: str, timeout_s: float) -> dict:
        """The per-URL wire decision, probed once and cached until
        forget(). A probe transport failure raises — the caller's
        lost-worker verdict — and leaves nothing cached."""
        with self._lock:
            st = self._neg.get(base_url)
        if st is not None:
            return st
        status, body = self._probe(
            base_url, max(self._connect_timeout_s, min(timeout_s, 5.0)))
        st = {"wire": "json", "shm": None}
        if body.get("wire_version") == wire.WIRE_VERSION:
            st["wire"] = "binary"
            if self.mode == "shm":
                advert = body.get("shm")
                if isinstance(advert, dict):
                    st["shm"] = advert
                else:
                    self.bus.counter("transport.fallback", level=2,
                                     wire="shm", reason="no_ring")
        else:
            # version skew (or a pre-graftwire worker): binary frames
            # would be refused — degrade to the wire both sides speak
            self.bus.counter("transport.fallback", level=2,
                             wire=self.mode, reason="version")
        with self._lock:
            st = self._neg.setdefault(base_url, st)
        return st

    def wire_for(self, base_url: str) -> str:
        """The wire the LAST dispatch to this worker actually rode —
        the router stamps it on its transport spans so graftscope
        attributes the win (and the fallback)."""
        return self._last_wire.get(base_url, "json")

    def forget(self, base_url: str) -> None:
        """Membership hook: drop the URL's negotiated state so the next
        dispatch renegotiates — a respawned worker advertises fresh
        ring segment names, and a recovered one may have changed
        capabilities. The router calls this on probe lost/recovered
        transitions and on remove_worker."""
        with self._lock:
            self._neg.pop(base_url, None)
            self._gen[base_url] = self._gen.get(base_url, 0) + 1

    # -- per-thread endpoints ------------------------------------------

    def _cache(self, name: str) -> dict:
        cache = getattr(self._local, name, None)
        if cache is None:
            cache = {}
            setattr(self._local, name, cache)
        return cache

    def _ring_for(self, base_url: str, st: dict, gen: int):
        """This thread's ring client for the URL, attaching on first
        use; None = fall back to HTTP (sticky until forget())."""
        rings = self._cache("rings")
        cached = rings.get(base_url)
        if cached is not None:
            if cached[0] == gen:
                return cached[1]
            cached[1].close()       # a respawn invalidated the attach
            del rings[base_url]
        advert = st.get("shm")
        if advert is None:
            return None
        try:
            client = shmring.RingClient(advert, self._connect_timeout_s)
        except shmring.RingError as exc:
            log.warning("transport: ring attach to %s failed (%s); "
                        "falling back to HTTP", base_url, exc)
            self.bus.counter("transport.fallback", level=2, wire="shm",
                             reason="attach")
            with self._lock:
                neg = self._neg.get(base_url)
                if neg is not None:
                    neg["shm"] = None
            return None
        rings[base_url] = (gen, client)
        with self._lock:
            self._endpoints.append(client)
        return client

    def _drop_ring(self, base_url: str) -> None:
        cached = self._cache("rings").pop(base_url, None)
        if cached is not None:
            cached[1].close()

    def _conn_for(self, base_url: str,
                  timeout_s: float) -> tuple[object, bool]:
        """(connection, was_fresh) from this thread's pool."""
        conns = self._cache("conns")
        conn = conns.get(base_url)
        fresh = conn is None
        if fresh:
            parts = urllib.parse.urlsplit(base_url)
            conn = http.client.HTTPConnection(parts.hostname,
                                              parts.port,
                                              timeout=timeout_s)
            conns[base_url] = conn
            with self._lock:
                self._endpoints.append(conn)
        conn.timeout = timeout_s
        if conn.sock is None:
            try:
                conn.connect()     # eager, so NODELAY covers call #1
            except OSError:
                pass               # conn.request() surfaces it on the
                                   # handled transport-failure path
        if conn.sock is not None:
            conn.sock.settimeout(timeout_s)
            # headers and body go out in separate sends; without
            # NODELAY the second send waits out the peer's delayed ACK
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        return conn, fresh

    def _drop_conn(self, base_url: str) -> None:
        conn = self._cache("conns").pop(base_url, None)
        if conn is not None:
            conn.close()

    # -- dispatch ------------------------------------------------------

    def post(self, base_url: str, entries, ts_buckets,
             timeout_s: float, trace: list | None = None,
             slo: list | None = None,
             dg: list | None = None,
             lens: list | None = None) -> list[dict]:
        """One microbatch dispatch over the negotiated wire — the same
        contract as :func:`post_predict`: per-request rows back, or
        WorkerTransportError for anything that means the WORKER (not a
        request) failed."""
        st = (self._negotiate(base_url, timeout_s)
              if self.mode != "json" else None)
        if st is not None and self.mode == "shm":
            with self._lock:
                gen = self._gen.get(base_url, 0)
            ring = self._ring_for(base_url, st, gen)
            if ring is not None:
                frame = wire.encode_request(entries, ts_buckets,
                                            trace=trace, slo=slo,
                                            dg=dg, lens=lens)
                bus = self.bus
                try:
                    raw = ring.call(frame, timeout_s)
                    # counted only after the frame actually travelled
                    # the ring — an oversize frame falls back to HTTP
                    # and must not be double-counted across wires
                    bus.counter("transport.bytes_out", len(frame),
                                level=2, wire="shm")
                    bus.counter("transport.bytes_in", len(raw),
                                level=2, wire="shm")
                    rows = wire.decode_response(raw)
                except shmring.RingFrameTooLarge as exc:
                    # this CALL outgrew the slot; the worker is fine —
                    # ride HTTP for it and keep the ring
                    log.warning("transport: %s (worker %s); this call "
                                "falls back to HTTP", exc, base_url)
                    bus.counter("transport.fallback", level=2,
                                wire="shm", reason="oversize")
                except (shmring.RingError,
                        wire.WireFormatError) as exc:
                    # peer dead / timed out / torn slot / refused or
                    # undecodable frame: the lost-worker verdict — the
                    # router requeues and every Future still resolves
                    self._drop_ring(base_url)
                    self.forget(base_url)
                    raise WorkerTransportError(
                        f"worker {base_url} ring dispatch failed: "
                        f"{type(exc).__name__}: {exc}") from exc
                else:
                    self._check_rows(base_url, rows, len(entries))
                    self._last_wire[base_url] = "shm"
                    return rows
        binary = st is not None and st["wire"] == "binary"
        wire_used = "binary" if binary else "json"
        if binary:
            body = wire.encode_request(entries, ts_buckets, trace=trace,
                                       slo=slo, dg=dg, lens=lens)
            ctype = wire.CONTENT_TYPE
        else:
            payload = {"entries": [int(e) for e in entries],
                       "ts_buckets": [int(t) for t in ts_buckets]}
            if trace is not None and any(t is not None for t in trace):
                payload["trace"] = trace
            if slo is not None and any(s is not None for s in slo):
                payload["slo"] = slo
            if dg is not None and any(dg):
                payload["dg"] = [bool(d) for d in dg]
            if lens is not None and any(ln is not None for ln in lens):
                payload["lens"] = lens
            body = json.dumps(payload).encode()
            ctype = "application/json"
        bus = self.bus
        bus.counter("transport.bytes_out", len(body), level=2,
                    wire=wire_used)
        data = self._http_post(base_url, body, ctype, timeout_s)
        bus.counter("transport.bytes_in", len(data), level=2,
                    wire=wire_used)
        if binary:
            try:
                rows = wire.decode_response(data)
            except wire.WireFormatError as exc:
                self.forget(base_url)   # renegotiate before retrying
                raise WorkerTransportError(
                    f"worker {base_url} answered an undecodable "
                    f"frame: {exc}") from exc
        else:
            try:
                rows = json.loads(data).get("results")
            except ValueError as exc:
                raise WorkerTransportError(
                    f"worker {base_url} answered unparseable JSON: "
                    f"{exc}") from exc
        self._check_rows(base_url, rows, len(entries))
        self._last_wire[base_url] = wire_used
        return rows

    def _http_post(self, base_url: str, body: bytes, ctype: str,
                   timeout_s: float) -> bytes:
        """One pooled POST with reconnect-on-error: a REUSED keep-alive
        connection the worker closed between batches retries exactly
        once on a fresh one (counter ``transport.reconnects``; safe for
        the same reason requeue-after-loss is — predictions are
        deterministic); a FRESH connection failing is the lost-worker
        signature and raises immediately."""
        for _ in range(2):
            conn, was_fresh = self._conn_for(base_url, timeout_s)
            try:
                conn.request("POST", "/predict", body=body,
                             headers={"Content-Type": ctype})
                resp = conn.getresponse()
                data = resp.read()
            except Exception as exc:
                self._drop_conn(base_url)
                if was_fresh:
                    raise WorkerTransportError(
                        f"worker {base_url} dispatch failed: "
                        f"{type(exc).__name__}: {exc}") from exc
                self.bus.counter("transport.reconnects", level=2)
                continue
            if resp.status != 200:
                raise WorkerTransportError(
                    f"worker {base_url} answered {resp.status}: "
                    f"{data[:200]!r}")
            return data
        raise WorkerTransportError(     # pragma: no cover — loop logic
            f"worker {base_url} dispatch failed after reconnect")

    @staticmethod
    def _check_rows(base_url: str, rows, n: int) -> None:
        if not isinstance(rows, list) or len(rows) != n:
            got = len(rows) if isinstance(rows, list) else "no"
            raise WorkerTransportError(
                f"worker {base_url} answered {got} rows for a "
                f"{n}-request batch")

    def close(self) -> None:
        """Release every endpoint any thread opened. The router calls
        this AFTER joining its sender threads, so no thread-local owner
        is still dispatching."""
        with self._lock:
            endpoints, self._endpoints = self._endpoints, []
        for ep in endpoints:
            try:
                ep.close()
            except Exception:       # lint: allow-silent-except — best-effort teardown of dead sockets/segments
                pass
