"""Elastic warm spares: scale the fleet on the queue-wait signal.

The fleet's capacity story before this module was static: N workers at
launch, minus whatever dies. But the load path this PR builds
(fleet/loadgen.py) is bursty by construction — diurnal envelopes and
open-loop bursts that a fixed fleet either overprovisions for or
collapses under. This controller closes the loop the warm stores make
cheap: because a worker spawns from the shared AOT + arena stores
(zero compiles, zero ingest — PR 3/5, the same machinery PR 11's
rollout restarts ride), a SPARE is seconds away, so capacity can
follow load instead of provisioning for its peak.

Control law (deliberately boring — hysteresis, not a model):

- **signal** — ``router.queue_wait_signal_ms()``: the rolling max of
  the ``router.queue_wait`` gauge (admission→dispatch wait of each
  dispatched batch's oldest request). Queue wait is THE saturation
  signature for an open-loop arrival process: offered load above
  capacity shows up here first, before latency percentiles move.
- **scale up** — signal above ``autoscale_up_ms`` sustained for
  ``autoscale_hold_s`` (no spawning off one noisy batch), spares below
  ``autoscale_max_spares``: spawn one spare via the injected
  ``spawn_spare``, await its readiness probe, `router.add_worker` it.
  One at a time — each spawn changes the signal, so the loop
  re-observes before the next.
- **scale down** — signal below ``autoscale_down_ms`` sustained for
  ``autoscale_cooldown_s``: retire the NEWEST spare (LIFO keeps the
  membership churn at the margin) via ``router.remove_worker`` (its
  queued custody requeues, in-flight work settles) then
  ``stop_spare`` — the worker's SIGTERM drain. Base workers are never
  retired; the controller only ever shrinks what it grew.

The controller is process-agnostic the way fleet/rollout.py is: the
caller injects ``spawn_spare() -> (worker_id, url, handle, probe_body)``
and ``stop_spare(worker_id, handle)`` (subprocess spawn/SIGTERM in
cli/fleet_main.py; plain fakes in tests), and the clock is injectable,
so the hysteresis sequencing is unit-tested with no processes and no
sleeps (tests/test_shield.py).

Telemetry (docs/OBSERVABILITY.md): counters ``autoscale.spawned`` /
``autoscale.retired`` / ``autoscale.spawn_failed``, gauges
``autoscale.spares`` / ``autoscale.signal_ms``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

from pertgnn_tpu import telemetry

log = logging.getLogger(__name__)


class AutoscaleController:
    """Hysteresis autoscaler over a FleetRouter's queue-wait signal.

    ``spawn_spare(index) -> (worker_id, url, handle, probe_body)`` must
    return a READY worker (probe answered 200) — the controller adds it
    to the router only after a successful spawn, so a cold or dead
    spare never enters dispatch. A spawn that raises is counted
    (``autoscale.spawn_failed``) and retried on the next up-decision.
    ``stop_spare(worker_id, handle)`` stops a retired spare (SIGTERM
    drain; it has already left the router's membership when called)."""

    def __init__(self, router, *,
                 spawn_spare: Callable[[int], tuple[str, str, Any, dict]],
                 stop_spare: Callable[[str, Any], None],
                 max_spares: int,
                 up_ms: float, down_ms: float,
                 hold_s: float = 0.5, cooldown_s: float = 10.0,
                 poll_interval_s: float = 0.1,
                 signal_window_s: float = 2.0,
                 bus=None, clock=time.perf_counter):
        self._router = router
        self._spawn = spawn_spare
        self._stop_spare = stop_spare
        self._max_spares = int(max_spares)
        self._up_ms = up_ms
        self._down_ms = down_ms
        self._hold_s = hold_s
        self._cooldown_s = cooldown_s
        self._poll_interval_s = poll_interval_s
        self._signal_window_s = signal_window_s
        self._injected_bus = bus
        self._clock = clock
        # (worker_id, handle) of live spares, spawn order (LIFO retire)
        self._spares: list[tuple[str, Any]] = []
        self._spawned_total = 0
        self._retired_total = 0
        self._spawn_failed = 0
        # True while a spawn is mid-flight (spawn_spare blocks until
        # the spare answers its readiness probe) — how a launcher's
        # retire-wait knows a spare is still COMING vs never triggered
        self._spawning = False
        # hysteresis state: when the signal first crossed each bound
        # (None = not currently crossed)
        self._over_since: float | None = None
        self._under_since: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    @property
    def bus(self):
        return (self._injected_bus if self._injected_bus is not None
                else telemetry.get_bus())

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "AutoscaleController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-autoscale")
        self._thread.start()
        return self

    def close(self, retire_spares: bool = True) -> None:
        """Stop the control loop; optionally retire every live spare
        (the default — a bench must not leak worker processes)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        if retire_spares:
            while self._retire_one(reason="close"):
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats_dict(self) -> dict:
        with self._lock:
            return {
                "spares": [wid for wid, _h in self._spares],
                "spawned": self._spawned_total,
                "retired": self._retired_total,
                "spawn_failed": self._spawn_failed,
                "spawning": self._spawning,
                "max_spares": self._max_spares,
            }

    # -- the control loop ------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            try:
                self.step(self._clock())
            except Exception:  # lint: allow-silent-except — logged; one bad tick must not kill the loop
                log.exception("autoscale: control step failed")

    def step(self, now: float) -> str | None:
        """One control decision off the current signal. Public so tests
        drive the hysteresis with an injected clock and zero sleeps.
        Returns "up" | "down" | None (what it did)."""
        signal_ms = self._router.queue_wait_signal_ms(
            self._signal_window_s)
        self.bus.gauge("autoscale.signal_ms", round(signal_ms, 3),
                       spares=len(self._spares))
        # hysteresis bookkeeping: how long has the signal been over the
        # up bound / under the down bound, continuously
        self._over_since = (None if signal_ms < self._up_ms
                            else self._over_since
                            if self._over_since is not None else now)
        self._under_since = (None if signal_ms >= self._down_ms
                             else self._under_since
                             if self._under_since is not None else now)
        if (self._over_since is not None
                and now - self._over_since >= self._hold_s
                and len(self._spares) < self._max_spares):
            self._over_since = None  # one spawn per sustained crossing
            if self._spawn_one(signal_ms):
                return "up"
            return None
        if (self._under_since is not None
                and now - self._under_since >= self._cooldown_s
                and self._spares):
            self._under_since = None  # one retire per sustained calm
            if self._retire_one(reason="cooldown", signal_ms=signal_ms):
                return "down"
        return None

    def _spawn_one(self, signal_ms: float) -> bool:
        index = self._spawned_total
        with self._lock:
            self._spawning = True
        try:
            worker_id, url, handle, body = self._spawn(index)
        except Exception as exc:
            with self._lock:
                self._spawn_failed += 1
                self._spawning = False
            log.error("autoscale: spare spawn #%d failed: %s: %s",
                      index, type(exc).__name__, exc)
            self.bus.counter("autoscale.spawn_failed",
                            error=type(exc).__name__)
            return False
        try:
            self._router.add_worker(worker_id, url)
        except Exception as exc:
            # router closed (or membership collision) while the spare
            # was warming: the spare must not leak as an orphan process
            with self._lock:
                self._spawn_failed += 1
                self._spawning = False
            log.error("autoscale: could not add ready spare %s to the "
                      "router (%s: %s); stopping it", worker_id,
                      type(exc).__name__, exc)
            self.bus.counter("autoscale.spawn_failed",
                             error=type(exc).__name__)
            try:
                self._stop_spare(worker_id, handle)
            except Exception:  # lint: allow-silent-except — best-effort teardown of a spare that never joined
                pass
            return False
        with self._lock:
            self._spares.append((worker_id, handle))
            self._spawned_total += 1
            self._spawning = False
            n = len(self._spares)
        log.warning("autoscale: spawned warm spare %s (queue wait "
                    "%.1fms > %.1fms; %d spare(s) live; compiles=%s)",
                    worker_id, signal_ms, self._up_ms, n,
                    body.get("compiles"))
        self.bus.counter("autoscale.spawned", worker=worker_id,
                         compiles=body.get("compiles"),
                         arena_warm=body.get("arena_warm"))
        self.bus.gauge("autoscale.spares", n)
        return True

    def _retire_one(self, reason: str,
                    signal_ms: float | None = None) -> bool:
        with self._lock:
            if not self._spares:
                return False
            worker_id, handle = self._spares.pop()  # LIFO: newest first
            self._retired_total += 1
            n = len(self._spares)
        # membership first (the router requeues its queued custody and
        # stops assigning), THEN the process drain
        self._router.remove_worker(worker_id)
        try:
            self._stop_spare(worker_id, handle)
        except Exception as exc:
            log.error("autoscale: stopping retired spare %s raised "
                      "%s: %s (membership already removed)", worker_id,
                      type(exc).__name__, exc)
        log.warning("autoscale: retired spare %s (%s%s; %d spare(s) "
                    "remain)", worker_id, reason,
                    "" if signal_ms is None else
                    f", queue wait {signal_ms:.1f}ms < "
                    f"{self._down_ms:.1f}ms", n)
        self.bus.counter("autoscale.retired", worker=worker_id,
                         reason=reason)
        self.bus.gauge("autoscale.spares", n)
        return True
