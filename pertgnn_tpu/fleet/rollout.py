"""Zero-downtime blue/green fleet rollout: one worker at a time.

The last arc of the streaming loop (ROADMAP item 1): continual training
(stream/continual.py) refreshes the checkpoint; this controller rolls it
across the PR-7 fleet without dropping a request.  Per worker, in
sequence:

1. **drain** — stop the old worker through the existing SIGTERM drain
   machinery (cli/fleet_main.py worker role): admissions stop, the
   undispatched backlog is handed back as retryable QueueClosed rows,
   and the router requeues it to the survivors while its membership
   prober excludes the draining member;
2. **restart warm** — spawn the replacement on the SAME port with the
   refreshed checkpoint; the shared AOT + arena/delta stores make
   cold-to-ready seconds, which is the whole reason rolling one worker
   at a time is cheap;
3. **verify** — poll the replacement's /healthz until 200 and run the
   caller's verification over the probe body (e.g. ``checkpoint_epoch``
   equals the refreshed step: the probe carries warm-start AND version
   evidence); the router re-admits the member on its next probe;
4. **proceed or roll back** — on verified readiness, next worker; on
   timeout/verification failure, kill the replacement, respawn the OLD
   configuration, confirm IT is ready, and abort the rollout loudly
   (counter ``rollout.rollback``) — a half-new fleet serving two
   checkpoint versions indefinitely is the failure mode this exists to
   prevent (docs/RELIABILITY.md).

The controller is deliberately process-agnostic: the caller injects
``stop_worker`` / ``spawn_new`` / ``spawn_old`` callables (subprocess
SIGTERM+spawn in benchmarks/stream_bench.py and cli fleets; plain fakes
in tests/test_stream.py), so the sequencing and rollback logic is
unit-testable without a fleet.  It runs on the CALLER's thread — the
fleet keeps serving because the router and the surviving workers are
other processes/threads entirely.

Invariant (exit-code-asserted by stream_bench under live closed-loop
traffic): a rollout loses ZERO Futures — every request submitted before,
during, and after resolves to a prediction or a typed error — and p99
stays bounded, because at most one worker is ever out of membership.

Telemetry (docs/OBSERVABILITY.md): counters ``rollout.started`` /
``rollout.worker_drained`` / ``rollout.worker_ready`` /
``rollout.rollback`` / ``rollout.failed`` / ``rollout.completed``,
histogram ``rollout.worker_swap_seconds``.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable

from pertgnn_tpu import telemetry
from pertgnn_tpu.fleet.transport import WorkerTransportError, get_probe

log = logging.getLogger(__name__)


class RolloutError(RuntimeError):
    """The rollout aborted.  `rolled_back` tells the operator whether
    the failing slot was restored to the OLD checkpoint (True: the
    fleet is whole again, on mixed=no/old version) or is DOWN (False:
    the fleet is degraded by one worker — page someone)."""

    def __init__(self, message: str, *, worker_id: str,
                 rolled_back: bool):
        super().__init__(message)
        self.worker_id = worker_id
        self.rolled_back = rolled_back


@dataclasses.dataclass
class RolloutWorker:
    """One fleet slot as the controller sees it: identity, probe URL,
    and an opaque process handle the injected callables understand."""

    worker_id: str
    url: str
    handle: Any = None


class RolloutController:
    """Sequential blue/green rollout over a fixed worker set.

    `stop_worker(worker)` must stop the CURRENT process behind
    `worker.handle` and return once it exited (the drain path);
    `spawn_new(worker)` / `spawn_old(worker)` must start a replacement
    on the worker's port serving the refreshed / previous checkpoint
    and return the new handle.  `verify(body) -> str | None` inspects a
    200 probe body and returns a human-readable complaint (or None) —
    e.g. "checkpoint_epoch is 3, wanted 5"."""

    def __init__(self, workers: list[RolloutWorker], *,
                 stop_worker: Callable[[RolloutWorker], None],
                 spawn_new: Callable[[RolloutWorker], Any],
                 spawn_old: Callable[[RolloutWorker], Any],
                 verify: Callable[[dict], str | None] | None = None,
                 probe: Callable[..., tuple[int, dict]] = get_probe,
                 ready_timeout_s: float = 300.0,
                 poll_interval_s: float = 0.25,
                 bus=None, memo=None,
                 new_generation: dict | None = None):
        if not workers:
            raise ValueError("rollout needs at least one worker")
        self._workers = list(workers)
        self._stop = stop_worker
        self._spawn_new = spawn_new
        self._spawn_old = spawn_old
        self._verify = verify
        self._probe = probe
        self._ready_timeout_s = ready_timeout_s
        self._poll_interval_s = poll_interval_s
        self._injected_bus = bus
        # the prediction memo's generation flip (fleet/memo.py): the
        # old generation is retired BEFORE the first worker drains —
        # mid-rollout the fleet serves two checkpoint versions, so
        # mid-rollout the cache serves nothing — and `new_generation`
        # (checkpoint_epoch / arena_fingerprint / taus kwargs for
        # memo.set_generation) is installed only after EVERY worker
        # verified on the new checkpoint.  An aborted rollout leaves
        # the memo cold, never stale: whichever version the fleet ended
        # up on, no cached byte predates the flip
        self._memo = memo
        self._new_generation = new_generation

    @property
    def bus(self):
        return (self._injected_bus if self._injected_bus is not None
                else telemetry.get_bus())

    # -- readiness -------------------------------------------------------

    def _await_ready(self, w: RolloutWorker, *,
                     use_verify: bool = True) -> tuple[bool, str]:
        """(ready-and-verified, complaint).  Polls until a 200 whose
        body passes `verify`, or the timeout.  A 200 that FAILS
        verification keeps polling (warmup races can answer 200 before
        identity fields settle) but reports the last complaint.
        ``use_verify=False`` checks plain readiness only — the rollback
        path respawns the OLD checkpoint, which the caller's
        new-version verification would (correctly) never accept."""
        deadline = time.monotonic() + self._ready_timeout_s
        complaint = "never answered the readiness probe"
        while time.monotonic() < deadline:
            try:
                status, body = self._probe(w.url, timeout_s=2.0)
            except WorkerTransportError:
                status, body = -1, {}
            if status == 200:
                bad = (self._verify(body)
                       if (use_verify and self._verify) else None)
                if bad is None:
                    return True, ""
                complaint = f"ready but failed verification: {bad}"
            elif status >= 0:
                complaint = f"probe answered {status} (not ready)"
            time.sleep(self._poll_interval_s)
        return False, complaint

    # -- the rollout -----------------------------------------------------

    def run(self) -> dict:
        """Roll every worker; returns a summary dict.  Raises
        RolloutError on the first worker that cannot be brought up on
        the new checkpoint (after attempting rollback to the old)."""
        bus = self.bus
        bus.counter("rollout.started", workers=len(self._workers))
        if self._memo is not None:
            # atomic retirement of the old cache generation: from this
            # moment no pre-rollout prediction can be read or inserted
            # (docs/RELIABILITY.md "stale cache generation")
            self._memo.retire_generation(reason="rollout")
        swapped: list[str] = []
        for w in self._workers:
            t0 = time.perf_counter()
            log.info("rollout: draining worker %s", w.worker_id)
            self._stop(w)
            bus.counter("rollout.worker_drained", worker=w.worker_id)
            try:
                w.handle = self._spawn_new(w)
                ok, complaint = self._await_ready(w)
            except Exception as e:
                # a replacement that never spawns (exec failure, port
                # bind race) is the same failure as one that never
                # answers 200 — it must reach the SAME rollback path,
                # not escape with the slot empty and no telemetry
                log.exception("rollout: spawning the replacement for "
                              "%s failed", w.worker_id)
                ok = False
                complaint = f"spawn_new raised {type(e).__name__}: {e}"
            if not ok:
                self._rollback(w, complaint)
            dt = time.perf_counter() - t0
            bus.counter("rollout.worker_ready", worker=w.worker_id)
            bus.histogram("rollout.worker_swap_seconds", dt,
                          worker=w.worker_id)
            swapped.append(w.worker_id)
            log.info("rollout: worker %s swapped in %.1fs", w.worker_id,
                     dt)
        if self._memo is not None and self._new_generation:
            self._memo.set_generation(**self._new_generation)
        bus.counter("rollout.completed", workers=len(swapped))
        return {"swapped": swapped, "workers": len(self._workers)}

    def _rollback(self, w: RolloutWorker, complaint: str) -> None:
        """The failing slot goes back to the OLD checkpoint; the
        rollout aborts either way — loudly."""
        bus = self.bus
        log.error("rollout: worker %s failed readiness on the new "
                  "checkpoint (%s) — rolling this slot back",
                  w.worker_id, complaint)
        bus.counter("rollout.rollback", worker=w.worker_id)
        try:
            self._stop(w)
        except Exception as e:
            log.warning("rollout: stopping the failed replacement for "
                        "%s raised %s: %s (continuing to respawn)",
                        w.worker_id, type(e).__name__, e)
        w.handle = self._spawn_old(w)
        # readiness only: the old checkpoint must not be judged by the
        # NEW version's verification (it would always "fail", reporting
        # every successful rollback as a degraded fleet)
        ok, old_complaint = self._await_ready(w, use_verify=False)
        bus.counter("rollout.failed", worker=w.worker_id,
                    rolled_back=ok)
        if not ok:
            raise RolloutError(
                f"worker {w.worker_id} failed readiness on the NEW "
                f"checkpoint ({complaint}) AND its rollback to the old "
                f"checkpoint failed ({old_complaint}) — the fleet is "
                f"running degraded by one worker",
                worker_id=w.worker_id, rolled_back=False)
        raise RolloutError(
            f"worker {w.worker_id} failed readiness on the new "
            f"checkpoint ({complaint}); the slot was rolled back to the "
            f"old checkpoint and the fleet is whole, still serving the "
            f"previous version",
            worker_id=w.worker_id, rolled_back=True)
