"""Open-loop trace-replay load generation: bursts, diurnals, Zipf.

Everything that exercised the fleet before this module was CLOSED-LOOP:
fleet_bench's client threads submit, WAIT for the answer, submit again
— so the offered rate self-throttles to whatever the fleet can serve,
and queueing collapse is structurally invisible (the canonical
coordinated-omission trap). Real microservice front doors (the paper's
own Alibaba-trace domain — PAPER.md) are open-loop: arrivals come when
they come, and a fleet slower than its arrival process grows a queue.
This module replays that arrival dynamic:

- **schedule generation** (`generate_schedule`) is a PURE function of
  (spec, request population, seed) — deterministic, so a chaos run is
  reproducible arrival-for-arrival and the bench's reference
  predictions line up index-for-index. The arrival process is a
  non-homogeneous Poisson: per-millisecond-bin counts drawn at rate
  ``base_rps x diurnal(t) x burst(t)``, where ``diurnal`` is a raised
  sinusoid (amplitude ``diurnal_amp``, period ``diurnal_period_s`` —
  the day compressed to bench scale) and ``burst`` multiplies the rate
  by ``burst_factor`` during ``burst_len_s`` windows every
  ``burst_every_s`` seconds (the flash-crowd mode the autoscaler and
  the shed policy exist for).
- **skewed popularity**: each arrival draws its (entry, ts_bucket)
  request from a Zipf(``zipf_s``) law over a seeded permutation of the
  real corpus — a few hot entries dominate, the tail stays warm, which
  is exactly the regime that makes per-rung executable caches and
  hedging interesting.
- **SLO mix**: arrivals draw a class from ``slo_mix``
  (fleet/shield.py vocabulary), so admission's
  lowest-class-first shedding faces realistic mixed traffic.

**Replay** (`replay`) submits each arrival at its scheduled time and
does NOT wait — futures resolve through done-callbacks into
preallocated result slots, so a drowning fleet shows up as queue growth
and sheds, not as a politely slowed generator. The only throttle is
physics: if the submitting thread falls behind the schedule the lag is
measured and reported (``loadgen.lag_ms``), never silently absorbed.

Telemetry (docs/OBSERVABILITY.md): gauges ``loadgen.offered_rps`` (per
elapsed second: what was OFFERED, which under collapse exceeds what
was served — the open-loop signature) and ``loadgen.lag_ms``; counters
``loadgen.submitted`` / ``loadgen.shed``.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time

import numpy as np

from pertgnn_tpu import telemetry
from pertgnn_tpu.fleet import shield
from pertgnn_tpu.serve.errors import ServeError

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One open-loop load scenario. All times in seconds; the whole
    schedule is deterministic given (spec, population, seed)."""

    duration_s: float = 10.0
    # Baseline offered rate (arrivals per second) before envelopes.
    base_rps: float = 50.0
    # Burst envelope: multiply the rate by `burst_factor` during
    # windows of `burst_len_s` starting every `burst_every_s`.
    # burst_every_s <= 0 or burst_factor <= 1 = no bursts.
    burst_factor: float = 1.0
    burst_every_s: float = 0.0
    burst_len_s: float = 1.0
    # Diurnal envelope: rate x (1 + amp * sin(2*pi*t/period)) — the
    # day's load curve compressed to bench scale. amp in [0, 1).
    diurnal_amp: float = 0.0
    diurnal_period_s: float = 10.0
    # Zipf popularity exponent over the request population (> 0; ~1.1
    # matches web-trace skew). 0 = uniform.
    zipf_s: float = 1.1
    # (class name, weight) mix arrivals draw their SLO class from.
    slo_mix: tuple = ((shield.SLO_CLASSES[0], 0.1),
                      (shield.DEFAULT_CLASS, 0.3),
                      (shield.BEST_EFFORT, 0.6))
    seed: int = 0


@dataclasses.dataclass
class Schedule:
    """The materialized arrival schedule: parallel arrays, one row per
    arrival, times as offsets from replay start."""

    t: np.ndarray           # float64 seconds, non-decreasing
    entry_ids: np.ndarray   # int64
    ts_buckets: np.ndarray  # int64
    slo: np.ndarray         # int8 index into shield.SLO_CLASSES

    def __len__(self) -> int:
        return len(self.t)

    def slo_name(self, i: int) -> str:
        return shield.SLO_CLASSES[int(self.slo[i])]


def rate_at(spec: LoadSpec, t: float) -> float:
    """Offered rate (rps) at offset `t` — base x diurnal x burst."""
    rate = spec.base_rps
    if spec.diurnal_amp > 0:
        rate *= 1.0 + spec.diurnal_amp * math.sin(
            2.0 * math.pi * t / max(spec.diurnal_period_s, 1e-9))
    if spec.burst_every_s > 0 and spec.burst_factor > 1.0:
        if (t % spec.burst_every_s) < spec.burst_len_s:
            rate *= spec.burst_factor
    return max(rate, 0.0)


def _zipf_probs(n: int, s: float) -> np.ndarray:
    if n <= 0:
        raise ValueError("empty request population")
    if s <= 0:
        return np.full(n, 1.0 / n)
    p = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** s
    return p / p.sum()


def generate_schedule(spec: LoadSpec, entries, ts_buckets) -> Schedule:
    """The deterministic arrival schedule for one replay.

    ``entries`` / ``ts_buckets`` are the request POPULATION (the real
    corpus — e.g. every (entry, ts_bucket) pair of a split); arrivals
    draw rows from it under the Zipf law over a seeded rank
    permutation, so 'hot entry' is a property of the seed, not of
    corpus order. Same (spec, population) -> bit-identical schedule
    (pinned in tests/test_shield.py)."""
    entries = np.asarray(entries, np.int64)
    ts_buckets = np.asarray(ts_buckets, np.int64)
    if len(entries) != len(ts_buckets):
        raise ValueError("entries / ts_buckets length mismatch")
    rng = np.random.default_rng(spec.seed)
    # arrivals: per-1ms-bin Poisson counts at the envelope rate,
    # uniform placement within each bin (thinning-free and exact
    # enough at bench scale)
    bin_s = 1e-3
    n_bins = max(int(round(spec.duration_s / bin_s)), 1)
    t_bins = np.arange(n_bins) * bin_s
    rates = np.asarray([rate_at(spec, t) for t in t_bins])
    counts = rng.poisson(rates * bin_s)
    n = int(counts.sum())
    t = np.repeat(t_bins, counts) + rng.random(n) * bin_s
    t.sort(kind="stable")
    # popularity: Zipf over a seeded rank permutation of the population
    rank_of = rng.permutation(len(entries))
    probs = _zipf_probs(len(entries), spec.zipf_s)
    pop_idx = rank_of[rng.choice(len(entries), size=n, p=probs)]
    # SLO mix
    names = [c for c, _w in spec.slo_mix]
    for c in names:
        shield.class_priority(c)  # typo'd class fails at build time
    weights = np.asarray([w for _c, w in spec.slo_mix], np.float64)
    if weights.sum() <= 0:
        raise ValueError("slo_mix weights must sum > 0")
    slo_of_mix = rng.choice(len(names), size=n, p=weights / weights.sum())
    slo = np.asarray([shield.class_priority(names[i])
                      for i in slo_of_mix], np.int8)
    return Schedule(t=t, entry_ids=entries[pop_idx],
                    ts_buckets=ts_buckets[pop_idx], slo=slo)


@dataclasses.dataclass
class ReplayResult:
    """Per-arrival outcomes of one open-loop replay — index-aligned
    with the schedule, so the bench's reference predictions compare
    row-for-row. Every scheduled arrival lands in exactly one bucket:
    a prediction (``preds[i]`` finite), or a typed error name
    (``errors[i]``) — a row with neither is a LOST FUTURE, the thing
    benchmarks/tail_bench.py exit-code-asserts never happens."""

    # float32, NaN where no prediction.  Shape (n,) for a single-tau
    # head, (n, T) under a multi-quantile head (replay's vector_width)
    # — per-tau columns, same order as the checkpoint's taus
    preds: np.ndarray
    errors: list                 # per-row typed error name or None
    latency_ms: np.ndarray       # submit -> resolution, NaN where shed
    lag_ms: np.ndarray           # actual submit - scheduled time
    offered: int = 0
    submitted: int = 0
    unresolved: int = 0          # futures still pending at wait timeout

    def served_mask(self) -> np.ndarray:
        """(n,) bool — rows that resolved to a prediction.  Row-wise
        over the tau columns in vector mode (a served quantile vector
        is all-finite by construction; a NaN-struck row is a finding
        the engine's own non-finite guard would have typed)."""
        finite = np.isfinite(self.preds)
        return finite.all(axis=1) if finite.ndim == 2 else finite

    def lost_futures(self) -> int:
        """Rows with neither a prediction nor a typed error — must be
        zero (the ALWAYS-resolves contract, measured end to end)."""
        served = self.served_mask()
        return int(sum(1 for p, e in zip(served, self.errors)
                       if not p and e is None))

    def error_counts(self) -> dict:
        out: dict[str, int] = {}
        for e in self.errors:
            if e is not None:
                out[e] = out.get(e, 0) + 1
        return out

    def latency_summary_by_class(self, schedule: Schedule) -> dict:
        """Served-latency percentiles per SLO class (the bench's
        bounded-p99-for-the-top-class gate reads this)."""
        out: dict[str, dict] = {}
        for ci, cname in enumerate(shield.SLO_CLASSES):
            mask = (schedule.slo == ci) & np.isfinite(self.latency_ms)
            lat = np.sort(self.latency_ms[mask])
            if len(lat) == 0:
                out[cname] = {"count": 0}
                continue
            out[cname] = {
                "count": int(len(lat)),
                "p50_ms": float(np.percentile(lat, 50)),
                "p99_ms": float(np.percentile(lat, 99)),
                "p99_9_ms": float(np.percentile(lat, 99.9)),
                "max_ms": float(lat[-1]),
            }
        return out


def replay(submit, schedule: Schedule, *, bus=None,
           wait_timeout_s: float = 300.0,
           vector_width: int = 0) -> ReplayResult:
    """Drive one open-loop replay against a router-shaped front door.

    ``submit(entry_id, ts_bucket, slo=<class name>) -> Future`` is the
    FleetRouter/MicrobatchQueue contract: it may raise a typed
    ServeError at admission (recorded as that arrival's outcome) and
    its Future always resolves. The caller's thread is the injector:
    it sleeps to each arrival's scheduled time, submits, attaches a
    done-callback, and moves on — it NEVER waits on a result
    mid-schedule (open loop). After the last arrival it waits out the
    in-flight tail (bounded by `wait_timeout_s`; stragglers are
    counted `unresolved`, and an unresolved future is a finding).

    ``vector_width`` is the checkpoint's quantile-head width: > 1
    preallocates (n, T) result slots so multi-quantile fleets replay
    without truncation (the per-tau columns land in the stats JSON);
    <= 1 keeps the historical scalar slots."""
    bus = bus if bus is not None else telemetry.get_bus()
    n = len(schedule)
    preds = np.full((n, vector_width) if vector_width > 1 else n,
                    np.nan, np.float32)
    errors: list = [None] * n
    latency_ms = np.full(n, np.nan, np.float64)
    lag_ms = np.zeros(n, np.float64)
    outstanding = [0]
    count_lock = threading.Lock()
    submitted = 0

    def on_done(i: int, t_submit: float, fut) -> None:
        t_now = time.perf_counter()
        try:
            exc = fut.exception()
            if exc is None:
                # plain traffic only (no lens variants): the result is
                # a scalar, or a (T,)-vector filling this row's per-tau
                # columns when the replay was sized with vector_width
                preds[i] = fut.result()
                latency_ms[i] = (t_now - t_submit) * 1e3
            else:
                errors[i] = type(exc).__name__
        except BaseException:  # lint: allow-silent-except — recorded as an outcome
            errors[i] = "ResultStorageError"
        finally:
            # the drain wait counts on EVERY callback decrementing —
            # a storage surprise must not hang the replay
            with count_lock:
                outstanding[0] -= 1

    t0 = time.perf_counter()
    next_second = 1.0
    offered_in_second = 0
    for i in range(n):
        t_sched = float(schedule.t[i])
        delay = t_sched - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        now_rel = time.perf_counter() - t0
        lag_ms[i] = max(now_rel - t_sched, 0.0) * 1e3
        offered_in_second += 1
        if now_rel >= next_second:
            bus.gauge("loadgen.offered_rps", offered_in_second,
                      second=int(next_second))
            next_second += 1.0
            offered_in_second = 0
        t_submit = time.perf_counter()
        try:
            fut = submit(int(schedule.entry_ids[i]),
                         int(schedule.ts_buckets[i]),
                         slo=schedule.slo_name(i))
        except ServeError as exc:
            # an admission reject IS this arrival's outcome (shed at
            # the door — open loop means we record it and keep going)
            errors[i] = type(exc).__name__
            bus.counter("loadgen.shed", level=2,
                        error=type(exc).__name__)
            continue
        submitted += 1
        with count_lock:
            outstanding[0] += 1
        fut.add_done_callback(
            lambda f, i=i, ts=t_submit: on_done(i, ts, f))
    bus.counter("loadgen.submitted", submitted)
    # wait out the in-flight tail (bounded): poll the outstanding
    # count — callbacks resolve on other threads
    deadline = time.monotonic() + wait_timeout_s
    while time.monotonic() < deadline:
        with count_lock:
            left = outstanding[0]
        if left == 0:
            break
        time.sleep(0.02)
    with count_lock:
        unresolved = outstanding[0]
    if unresolved:
        log.error("loadgen: %d future(s) unresolved after %.0fs tail "
                  "wait — a lost-future finding", unresolved,
                  wait_timeout_s)
    bus.gauge("loadgen.lag_ms", float(lag_ms.max()) if n else 0.0,
              mean=float(lag_ms.mean()) if n else 0.0)
    return ReplayResult(preds=preds, errors=errors,
                        latency_ms=latency_ms, lag_ms=lag_ms,
                        offered=n, submitted=submitted,
                        unresolved=unresolved)
