"""graftwire codec — the fleet's versioned binary wire format.

The JSON transport (fleet/transport.py) spends the hot path in
``json.dumps``/``json.loads`` and has to ARGUE float bit-identity
through decimal round-trip; this codec makes both structural. One
frame is a fixed little-endian header followed by tagged
length-prefixed sections (docs/GUIDE.md §14 renders the byte-layout
table):

    frame   := magic "GW" | version u8 | kind u8 | frame_len u32
               | section*
    section := tag u8 | len u32 | payload[len]

Request frames (kind 1) mirror the JSON body's omit-when-default
contract exactly — entries/ts_buckets as packed i64 arrays, ``dg`` as
a bitmask, and the rare metadata sections (``trace``/``slo``/``lens``)
as UTF-8 JSON so their nested dict shapes stay in lockstep with the
legacy wire. Response frames (kind 2) carry scalar predictions as raw
IEEE-754 f64 and vector predictions as contiguous raw f32 (or f64 when
an element would not survive the narrowing) row blocks — bit-identity
across transports is a property of the LAYOUT, not of a printer.
Error rows travel as the same ``{"error", "message"}`` pairs
``error_from_row`` rehydrates, so the typed-outcome contract is
transport-invariant. A refusal frame (kind 3) is how a worker answers
a frame it cannot decode: typed, loud, never a crash.

Decoding NEVER throws anything but :class:`WireFormatError` (or its
:class:`WireRefusal` subclass) at a malformed, truncated, or
version-skewed frame — the transport maps that to its existing
lost-worker/fallback machinery. No pickle anywhere: every byte on
this wire is ints, floats, and UTF-8 JSON.
"""

from __future__ import annotations

import json
import struct

import numpy as np

WIRE_VERSION = 1
# the Content-Type that negotiates the binary wire over HTTP
CONTENT_TYPE = "application/x-pertgnn-wire"

_MAGIC = b"GW"
_HDR = struct.Struct("<2sBBI")          # magic, version, kind, frame_len
_SEC = struct.Struct("<BI")             # tag, len
_U32 = struct.Struct("<I")

KIND_REQUEST = 1
KIND_RESPONSE = 2
KIND_REFUSAL = 3

# request sections
_TAG_ENTRIES = 0x01                      # u32 count + count * i64
_TAG_TS = 0x02                           # u32 count + count * i64
_TAG_TRACE = 0x03                        # UTF-8 JSON (list of dict|null)
_TAG_SLO = 0x04                          # UTF-8 JSON (list of str|null)
_TAG_DG = 0x05                           # u32 count + LSB-first bitmask
_TAG_LENS = 0x06                         # UTF-8 JSON (list of dict|null)
# response sections
_TAG_ROWKIND = 0x10                      # u32 count + count * u8
_TAG_SCALARS = 0x11                      # raw f64 per scalar row
_TAG_VECTORS = 0x12                      # per vector: u8 width, u32 T, raw
_TAG_ERRORS = 0x13                       # UTF-8 JSON ([{error, message}])
_TAG_ATTR = 0x14                         # UTF-8 JSON ([[row, rows], ...])
_TAG_CACHE = 0x15                        # u32 count + LSB-first bitmask
# refusal section
_TAG_REFUSAL = 0x20                      # UTF-8 JSON ({error, message})

_ROW_SCALAR = 0
_ROW_VECTOR = 1
_ROW_ERROR = 2


class WireFormatError(RuntimeError):
    """The frame cannot be decoded — truncated, corrupt, wrong magic,
    unknown section, or a version this build does not speak. The
    transport converts this into its fallback/lost-worker machinery;
    it must never surface as a crash."""


class WireRefusal(WireFormatError):
    """The PEER decoded our frame and refused it (a kind-3 frame):
    typically version skew on the worker side. Carries the peer's own
    error name + message."""


def _section(tag: int, payload: bytes) -> bytes:
    return _SEC.pack(tag, len(payload)) + payload


def _frame(kind: int, sections: list[bytes]) -> bytes:
    body = b"".join(sections)
    return _HDR.pack(_MAGIC, WIRE_VERSION, kind,
                     _HDR.size + len(body)) + body


def _pack_i64s(values) -> bytes:
    vals = [int(v) for v in values]
    return _U32.pack(len(vals)) + struct.pack(f"<{len(vals)}q", *vals)


def _unpack_i64s(buf: bytes, what: str) -> list[int]:
    if len(buf) < 4:
        raise WireFormatError(f"{what}: truncated count")
    (n,) = _U32.unpack_from(buf)
    if len(buf) != 4 + 8 * n:
        raise WireFormatError(
            f"{what}: {len(buf) - 4} payload bytes for {n} i64s")
    return list(struct.unpack_from(f"<{n}q", buf, 4))


def _pack_json(obj) -> bytes:
    return json.dumps(obj).encode()


def _unpack_json(buf: bytes, what: str):
    try:
        return json.loads(buf.decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise WireFormatError(f"{what}: bad JSON section: {exc}") from exc


def _split_sections(buf: bytes, expect_kind: int) -> dict[int, bytes]:
    """Header-validate one frame and return {tag: payload}. The ONLY
    raise is WireFormatError (WireRefusal for a peer's kind-3)."""
    if len(buf) < _HDR.size:
        raise WireFormatError(f"frame truncated at {len(buf)} bytes "
                              f"(header is {_HDR.size})")
    magic, version, kind, frame_len = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireFormatError(f"bad magic {magic!r} (not a graftwire "
                              f"frame)")
    if version != WIRE_VERSION:
        raise WireFormatError(f"wire version skew: frame v{version}, "
                              f"this build speaks v{WIRE_VERSION}")
    if frame_len != len(buf):
        raise WireFormatError(f"frame length {frame_len} != "
                              f"{len(buf)} bytes on the wire "
                              f"(truncated or concatenated)")
    sections: dict[int, bytes] = {}
    off = _HDR.size
    while off < len(buf):
        if off + _SEC.size > len(buf):
            raise WireFormatError("section header truncated")
        tag, n = _SEC.unpack_from(buf, off)
        off += _SEC.size
        if off + n > len(buf):
            raise WireFormatError(f"section 0x{tag:02x} truncated: "
                                  f"{n} declared, "
                                  f"{len(buf) - off} remain")
        if tag in sections:
            raise WireFormatError(f"duplicate section 0x{tag:02x}")
        sections[tag] = bytes(buf[off:off + n])
        off += n
    if kind == KIND_REFUSAL and expect_kind != KIND_REFUSAL:
        info = _unpack_json(sections.get(_TAG_REFUSAL, b"{}"),
                            "refusal")
        raise WireRefusal(f"peer refused the frame: "
                          f"{info.get('error', 'WireFormatError')}: "
                          f"{info.get('message', '(no message)')}")
    if kind != expect_kind:
        raise WireFormatError(f"frame kind {kind}, expected "
                              f"{expect_kind}")
    return sections


# -- request frames -------------------------------------------------------

def encode_request(entries, ts_buckets, trace: list | None = None,
                   slo: list | None = None,
                   dg: list | None = None,
                   lens: list | None = None) -> bytes:
    """One microbatch request frame — the same omit-when-default rules
    as ``post_predict``'s JSON body, so all-plain traffic is two packed
    int arrays and nothing else."""
    sections = [_section(_TAG_ENTRIES, _pack_i64s(entries)),
                _section(_TAG_TS, _pack_i64s(ts_buckets))]
    if trace is not None and any(t is not None for t in trace):
        sections.append(_section(_TAG_TRACE, _pack_json(trace)))
    if slo is not None and any(s is not None for s in slo):
        sections.append(_section(_TAG_SLO, _pack_json(slo)))
    if dg is not None and any(dg):
        bits = bytearray((len(dg) + 7) // 8)
        for i, d in enumerate(dg):
            if d:
                bits[i // 8] |= 1 << (i % 8)
        sections.append(_section(
            _TAG_DG, _U32.pack(len(dg)) + bytes(bits)))
    if lens is not None and any(ln is not None for ln in lens):
        sections.append(_section(_TAG_LENS, _pack_json(lens)))
    return _frame(KIND_REQUEST, sections)


def decode_request(buf: bytes) -> dict:
    """A request frame back into the JSON body's dict shape —
    ``WorkerServer._predict`` consumes either wire without knowing
    which one carried the batch."""
    sections = _split_sections(buf, KIND_REQUEST)
    if _TAG_ENTRIES not in sections or _TAG_TS not in sections:
        raise WireFormatError("request frame missing entries/ts "
                              "sections")
    req = {"entries": _unpack_i64s(sections[_TAG_ENTRIES], "entries"),
           "ts_buckets": _unpack_i64s(sections[_TAG_TS], "ts_buckets")}
    if _TAG_TRACE in sections:
        req["trace"] = _unpack_json(sections[_TAG_TRACE], "trace")
    if _TAG_SLO in sections:
        req["slo"] = _unpack_json(sections[_TAG_SLO], "slo")
    if _TAG_DG in sections:
        raw = sections[_TAG_DG]
        if len(raw) < 4:
            raise WireFormatError("dg: truncated count")
        (n,) = _U32.unpack_from(raw)
        bits = raw[4:]
        if len(bits) != (n + 7) // 8:
            raise WireFormatError(f"dg: {len(bits)} mask bytes for "
                                  f"{n} flags")
        req["dg"] = [bool(bits[i // 8] >> (i % 8) & 1)
                     for i in range(n)]
    if _TAG_LENS in sections:
        req["lens"] = _unpack_json(sections[_TAG_LENS], "lens")
    return req


# -- response frames ------------------------------------------------------

def _f32_exact(arr64: np.ndarray) -> bool:
    """Whether every element survives f64 -> f32 -> f64 bit-exactly —
    true for anything that was ever a float32 (pred_to_wire's vectors),
    in which case the narrow row block loses nothing. One vectorized
    round trip, not a per-float pack (the response encode hot path);
    out-of-f32-range values overflow to inf and compare unequal, NaNs
    compare unequal — both take the wide block."""
    with np.errstate(over="ignore"):
        return bool((arr64.astype(np.float32).astype(np.float64)
                     == arr64).all())


def encode_response(rows: list[dict]) -> bytes:
    """Per-request result rows as one frame: a rowkind byte per row,
    then the scalar block (raw f64), the vector blocks (raw f32 where
    exact, f64 otherwise), the error rows, and the lens attribution
    payloads, each in row order."""
    kinds = bytearray()
    scalar_vals: list[float] = []
    vectors = bytearray()
    errors: list[dict] = []
    attr: list[list] = []
    nvec = 0
    for i, row in enumerate(rows):
        if "error" in row:
            kinds.append(_ROW_ERROR)
            errors.append({"error": str(row.get("error", "")),
                           "message": str(row.get("message", ""))})
            continue
        pred = row["pred"]
        if isinstance(pred, list):
            kinds.append(_ROW_VECTOR)
            nvec += 1
            arr = np.asarray(pred, np.float64)
            width = 4 if _f32_exact(arr) else 8
            vectors += struct.pack("<BI", width, len(arr))
            vectors += (arr.astype("<f4") if width == 4
                        else arr.astype("<f8")).tobytes()
        else:
            kinds.append(_ROW_SCALAR)
            scalar_vals.append(float(pred))
        if "attr" in row:
            attr.append([i, list(row["attr"])])
    sections = [_section(_TAG_ROWKIND,
                         _U32.pack(len(rows)) + bytes(kinds))]
    if scalar_vals:
        sections.append(_section(
            _TAG_SCALARS,
            np.asarray(scalar_vals, "<f8").tobytes()))
    if nvec:
        sections.append(_section(_TAG_VECTORS,
                                 _U32.pack(nvec) + bytes(vectors)))
    if errors:
        sections.append(_section(_TAG_ERRORS, _pack_json(errors)))
    if attr:
        sections.append(_section(_TAG_ATTR, _pack_json(attr)))
    # cache_hit flags as a dg-style bitmask, omitted when no row was
    # served from the prediction memo (fleet/memo.py) — all-miss (and
    # all pre-memo) traffic pays zero extra wire bytes
    if any(row.get("cache_hit") for row in rows):
        bits = bytearray((len(rows) + 7) // 8)
        for i, row in enumerate(rows):
            if row.get("cache_hit"):
                bits[i // 8] |= 1 << (i % 8)
        sections.append(_section(
            _TAG_CACHE, _U32.pack(len(rows)) + bytes(bits)))
    return _frame(KIND_RESPONSE, sections)


def decode_response(buf: bytes) -> list[dict]:
    """A response frame back into the JSON wire's row dicts —
    ``result_from_row``/``error_from_row`` rehydrate them identically,
    and ``decode_response(encode_response(rows)) == rows`` holds with
    struct-level float equality (tests/test_wire.py pins it)."""
    sections = _split_sections(buf, KIND_RESPONSE)
    if _TAG_ROWKIND not in sections:
        raise WireFormatError("response frame missing rowkind section")
    raw = sections[_TAG_ROWKIND]
    if len(raw) < 4:
        raise WireFormatError("rowkind: truncated count")
    (n,) = _U32.unpack_from(raw)
    kinds = raw[4:]
    if len(kinds) != n:
        raise WireFormatError(f"rowkind: {len(kinds)} bytes for "
                              f"{n} rows")
    scalars_raw = sections.get(_TAG_SCALARS, b"")
    n_scalar = sum(1 for k in kinds if k == _ROW_SCALAR)
    if len(scalars_raw) != 8 * n_scalar:
        raise WireFormatError(f"scalars: {len(scalars_raw)} bytes for "
                              f"{n_scalar} scalar rows")
    scalars = np.frombuffer(scalars_raw, "<f8").tolist()
    errors = (_unpack_json(sections[_TAG_ERRORS], "errors")
              if _TAG_ERRORS in sections else [])
    vec_buf = sections.get(_TAG_VECTORS, b"")
    n_vector = sum(1 for k in kinds if k == _ROW_VECTOR)
    if vec_buf:
        if len(vec_buf) < 4:
            raise WireFormatError("vectors: truncated count")
        (nvec,) = _U32.unpack_from(vec_buf)
    else:
        nvec = 0
    if nvec != n_vector:
        raise WireFormatError(f"vectors: section declares {nvec} "
                              f"blocks for {n_vector} vector rows")
    vec_off = 4 if vec_buf else 0
    rows: list[dict] = []
    s_i = e_i = 0
    for k in kinds:
        if k == _ROW_SCALAR:
            rows.append({"pred": scalars[s_i]})
            s_i += 1
        elif k == _ROW_VECTOR:
            if vec_off + 5 > len(vec_buf):
                raise WireFormatError("vectors: truncated block header")
            width, t = struct.unpack_from("<BI", vec_buf, vec_off)
            vec_off += 5
            if width not in (4, 8) or vec_off + width * t > len(vec_buf):
                raise WireFormatError(f"vectors: bad block "
                                      f"(width {width}, T {t})")
            block = np.frombuffer(vec_buf, "<f4" if width == 4
                                  else "<f8", count=t, offset=vec_off)
            rows.append({"pred": block.astype(np.float64).tolist()})
            vec_off += width * t
        elif k == _ROW_ERROR:
            if e_i >= len(errors) or not isinstance(errors[e_i], dict):
                raise WireFormatError("errors: fewer error payloads "
                                      "than error rows")
            rows.append({"error": errors[e_i].get("error", ""),
                         "message": errors[e_i].get("message", "")})
            e_i += 1
        else:
            raise WireFormatError(f"unknown rowkind {k}")
    if vec_off != len(vec_buf):
        raise WireFormatError(f"vectors: {len(vec_buf) - vec_off} "
                              f"trailing bytes after the last block")
    for item in (_unpack_json(sections[_TAG_ATTR], "attr")
                 if _TAG_ATTR in sections else []):
        if (not isinstance(item, list) or len(item) != 2
                or not isinstance(item[0], int)
                or not 0 <= item[0] < len(rows)
                or "pred" not in rows[item[0]]):
            raise WireFormatError("attr: row reference out of range")
        rows[item[0]]["attr"] = item[1]
    if _TAG_CACHE in sections:
        raw = sections[_TAG_CACHE]
        if len(raw) < 4:
            raise WireFormatError("cache_hit: truncated count")
        (nc,) = _U32.unpack_from(raw)
        if nc != len(rows):
            raise WireFormatError(f"cache_hit: flag count {nc} for "
                                  f"{len(rows)} rows")
        bits = raw[4:]
        if len(bits) != (nc + 7) // 8:
            raise WireFormatError(f"cache_hit: {len(bits)} mask bytes "
                                  f"for {nc} flags")
        for i in range(nc):
            if bits[i // 8] >> (i % 8) & 1:
                rows[i]["cache_hit"] = True
    return rows


# -- refusal frames -------------------------------------------------------

def encode_refusal(error: str, message: str) -> bytes:
    """A typed decode refusal — what a worker answers when it cannot
    decode a frame (version skew, corruption). The client's decoder
    raises it as :class:`WireRefusal`, which the transport maps to the
    lost-worker path, never a crash."""
    return _frame(KIND_REFUSAL, [_section(
        _TAG_REFUSAL, _pack_json({"error": error, "message": message}))])
