"""Batch counterfactual search: the fleet's first self-driving workload.

The lens answers "what if this call were not there / ran elsewhere?"
one query at a time (lens/whatif.py); this service asks the question
SYSTEMATICALLY: beam search over the drop/substitute edit neighborhood
of a hot entry's topology, minimizing the predicted tail latency — the
highest-tau quantile column under a multi-quantile head (the predicted
p99 when 0.99 is among the taus), the scalar prediction otherwise.

It deliberately owns no machinery: every candidate rides the router's
ordinary ``submit(entry, ts_bucket, lens=LensRequest(edits=...))``
front door, so hedging, shedding, tracing, and the prediction memo
(fleet/memo.py) all apply unchanged.  Three structural properties make
the search cheap:

- **zero fresh compiles, provably**: edits never grow a graph and the
  ladder rungs key on shape (lens/whatif.py module docstring), so no
  candidate can trigger a compile — benchmarks/cache_bench.py
  exit-code-asserts ``compiles == 0`` across a whole search.
- **canonical dedup**: candidates are deduplicated by their canonical
  edit key (lens/canon.py) before submission — the same key the memo
  uses — so the engine evaluates each distinct counterfactual at most
  once and the memo's misses are bounded by the unique-canonical
  count.
- **typed refusals prune, never crash**: a candidate the edit algebra
  refuses (WhatIfRefused — e.g. dropping a pattern's last node) is
  counted and discarded like any other dead branch.

Budget discipline (docs/RELIABILITY.md "search budget exhaustion"):
``budget`` caps total submissions.  A budget too small to evaluate the
baseline plus one candidate raises the typed
:class:`SearchBudgetExhausted` — there is no argmin to report.  A
budget that runs out mid-exploration truncates the search and flags
the result ``budget_exhausted=True``: the reported best is the argmin
of what was ACTUALLY evaluated, never silently presented as the argmin
of the full neighborhood (counter ``search.budget_exhausted`` either
way).

Telemetry (docs/OBSERVABILITY.md): counters ``search.requests`` /
``search.refused`` / ``search.errors`` / ``search.budget_exhausted``,
gauges ``search.rounds`` / ``search.best_objective``.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from pertgnn_tpu import telemetry
from pertgnn_tpu.lens.canon import canonical_edits, canonical_lens_key
from pertgnn_tpu.lens.request import LensRequest, LensResult
from pertgnn_tpu.serve.errors import ServeError, WhatIfRefused
from pertgnn_tpu.lens.whatif import MAX_EDITS

log = logging.getLogger(__name__)


class SearchBudgetExhausted(RuntimeError):
    """The submission budget cannot cover even the baseline plus one
    candidate — the search has no evaluated neighborhood to take an
    argmin over, so it refuses loudly instead of reporting the
    unedited topology as a 'finding'."""


@dataclasses.dataclass(frozen=True)
class SearchSpec:
    """One search problem: the hot request plus the exploration knobs.
    ``num_nodes`` / ``num_edges`` are the entry's BASE topology sizes
    (the launcher reads them off the dataset's mixtures — the router
    itself holds no mixtures); candidate indices beyond what an edited
    graph still has are refused by the worker and pruned, not
    special-cased here."""

    entry_id: int
    ts_bucket: int
    num_nodes: int
    num_edges: int
    # beam search shape
    beam_width: int = 4
    max_depth: int = 2
    # total submission cap, baseline included
    budget: int = 96
    # ops explored; drop_edge shrinks the graph, sub_node re-routes a
    # stage (drop_node is deliberately absent from the default: its
    # incident-edge removal makes later edge indices mixture-dependent,
    # which buys little beyond drop_edge at much worse dedup)
    ops: tuple = ("drop_edge", "sub_node")
    # substitute candidates for sub_node (e.g. the entry's own ms ids)
    sub_ms_ids: tuple = ()
    # branching caps, so a big topology cannot explode a round
    max_drop_candidates: int = 16
    max_sub_nodes: int = 4
    # SLO class the candidates ride under (best-effort by default: the
    # search is background traffic and should shed first)
    slo: str | None = None
    timeout_s: float = 60.0


@dataclasses.dataclass
class SearchResult:
    """The argmin over everything evaluated, with its audit trail."""

    baseline: float
    best_objective: float
    best_edits: tuple
    # every evaluated candidate: (edits, objective), evaluation order
    evaluated: list
    requests: int = 0
    refused: int = 0
    errors: int = 0
    rounds: int = 0
    budget_exhausted: bool = False

    @property
    def improvement(self) -> float:
        return self.baseline - self.best_objective

    def to_dict(self) -> dict:
        return {
            "baseline": self.baseline,
            "best_objective": self.best_objective,
            "best_edits": [dict(e) for e in self.best_edits],
            "improvement": self.improvement,
            "evaluated": len(self.evaluated),
            "requests": self.requests,
            "refused": self.refused,
            "errors": self.errors,
            "rounds": self.rounds,
            "budget_exhausted": self.budget_exhausted,
        }


def objective_of(pred) -> float:
    """The scalar the search minimizes: the LAST tau's prediction of a
    quantile vector (taus are sorted ascending — config.py — so the
    last column is the tail), the prediction itself otherwise."""
    if isinstance(pred, LensResult):
        pred = pred.pred
    arr = np.asarray(pred, np.float64)
    return float(arr.reshape(-1)[-1])


class CounterfactualSearch:
    """Beam search over an entry's edit neighborhood through a
    router-shaped ``submit`` front door."""

    def __init__(self, submit, spec: SearchSpec, bus=None):
        self._submit = submit
        self._spec = spec
        self._injected_bus = bus

    @property
    def bus(self):
        if self._injected_bus is not None:
            return self._injected_bus
        return telemetry.get_bus()

    # -- candidate generation -------------------------------------------

    def _neighbors(self, edits: tuple) -> list[tuple]:
        """Single-op extensions of one beam state, deterministic order.
        Edge indices are enumerated against the state's REMAINING edge
        count (each drop_edge shrinks it by exactly one), so within
        this op vocabulary no candidate is trivially out of range."""
        s = self._spec
        if len(edits) >= min(s.max_depth, MAX_EDITS):
            return []
        out: list[tuple] = []
        if "drop_edge" in s.ops:
            remaining = s.num_edges - sum(
                1 for e in edits if e["op"] == "drop_edge")
            for j in range(min(remaining, s.max_drop_candidates)):
                out.append(edits + ({"op": "drop_edge", "edge": j},))
        if "sub_node" in s.ops and s.sub_ms_ids:
            for i in range(min(s.num_nodes, s.max_sub_nodes)):
                for m in s.sub_ms_ids:
                    out.append(edits + (
                        {"op": "sub_node", "node": int(i),
                         "ms_id": int(m)},))
        return out

    # -- evaluation ------------------------------------------------------

    def _evaluate(self, batch: list[tuple], counts: dict) -> list:
        """Submit one round's candidates as a BATCH (the router
        coalesces them into microbatches) and collect objectives."""
        s = self._spec
        flights = []
        for edits in batch:
            lens = (LensRequest(edits=edits) if edits else None)
            try:
                fut = self._submit(s.entry_id, s.ts_bucket, slo=s.slo,
                                   lens=lens)
            except ServeError as exc:
                counts["errors"] += 1
                log.debug("search: candidate rejected at admission: %s",
                          exc)
                continue
            counts["requests"] += 1
            flights.append((edits, fut))
        scored = []
        for edits, fut in flights:
            try:
                scored.append((edits, objective_of(
                    fut.result(timeout=s.timeout_s))))
            except WhatIfRefused:
                counts["refused"] += 1
            except Exception as exc:
                counts["errors"] += 1
                log.debug("search: candidate failed: %s: %s",
                          type(exc).__name__, exc)
        return scored

    def run(self) -> SearchResult:
        """The full beam search; raises SearchBudgetExhausted only when
        the budget cannot buy a single comparison."""
        s = self._spec
        bus = self.bus
        if s.budget < 2:
            bus.counter("search.budget_exhausted",
                        entry_id=s.entry_id, evaluated=0)
            raise SearchBudgetExhausted(
                f"budget {s.budget} cannot cover the baseline plus one "
                f"candidate for entry {s.entry_id}")
        counts = {"requests": 0, "refused": 0, "errors": 0}
        base = self._evaluate([()], counts)
        if not base:
            raise ServeError(
                f"counterfactual search: the baseline request for "
                f"entry {s.entry_id} did not serve — nothing to "
                f"compare against")
        baseline = base[0][1]
        evaluated: list = [base[0]]
        seen = {canonical_lens_key(LensRequest(edits=()).to_wire())}
        best_edits, best_obj = (), baseline
        frontier: list[tuple] = [()]
        rounds = 0
        exhausted = False
        for _depth in range(s.max_depth):
            batch: list[tuple] = []
            for edits in frontier:
                for cand in self._neighbors(edits):
                    key = canonical_lens_key(
                        LensRequest(edits=cand).to_wire())
                    if key in seen:
                        continue
                    seen.add(key)
                    batch.append(cand)
            if not batch:
                break
            room = s.budget - counts["requests"]
            if room <= 0:
                exhausted = True
                break
            if len(batch) > room:
                batch = batch[:room]
                exhausted = True
            rounds += 1
            scored = self._evaluate(batch, counts)
            bus.counter("search.requests", len(batch),
                        entry_id=s.entry_id, depth=rounds)
            evaluated.extend(scored)
            scored.sort(key=lambda x: x[1])
            for edits, obj in scored[:1]:
                if obj < best_obj:
                    best_edits, best_obj = edits, obj
            frontier = [e for e, _o in scored[:s.beam_width]]
        if counts["refused"]:
            bus.counter("search.refused", counts["refused"],
                        entry_id=s.entry_id)
        if counts["errors"]:
            bus.counter("search.errors", counts["errors"],
                        entry_id=s.entry_id)
        if exhausted:
            bus.counter("search.budget_exhausted",
                        entry_id=s.entry_id,
                        evaluated=len(evaluated))
        bus.gauge("search.rounds", rounds, entry_id=s.entry_id)
        bus.gauge("search.best_objective", best_obj,
                  entry_id=s.entry_id, baseline=baseline)
        return SearchResult(
            baseline=baseline, best_objective=best_obj,
            best_edits=canonical_edits(best_edits),
            evaluated=evaluated, requests=counts["requests"],
            refused=counts["refused"], errors=counts["errors"],
            rounds=rounds, budget_exhausted=exhausted)
