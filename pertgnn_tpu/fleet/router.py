"""The fleet front door: one router, N warm serve workers.

``FleetRouter`` owns the client-facing request queue (the same
submit-a-Future contract as serve/queue.MicrobatchQueue — a submitted
Future ALWAYS resolves, to a prediction or a typed serve error) and
dispatches capacity-respecting microbatches over the HTTP transport
(fleet/transport.py) to whichever worker the pure policy
(fleet/policy.py) predicts will finish first. The design lesson is the
one DGL and PyTorch-Direct teach for single-process GNN systems —
treat the data/dispatch path as a first-class concurrent subsystem,
not a loop around the model — applied one level up, across processes.

Threads (all daemon, all owned by the router):

- **dispatcher** — coalesces pending requests under the router flush
  deadline into microbatches (submission-order prefix, same capacity
  discipline as the single-process queue), picks a worker via
  ``policy.choose_worker`` (excluding workers a retried batch already
  failed on — the rollout's excluded-slot pattern, so a FLAPPING worker
  cannot eat the same request twice), and hands the batch to that
  worker's sender. Blocks — never drops — when every healthy worker is
  at its slot capacity. Also drives the BROWNOUT state machine
  (fleet/shield.py): past the pending-occupancy threshold, best-effort
  requests are marked for rung DOWNGRADE before anyone is shed.
- **one sender per worker** — performs the blocking HTTP dispatch and
  settles futures. A transport-level failure is the lost-worker
  signature: the batch (plus anything still queued for that worker)
  REQUEUES to the front of the pending queue in submission order
  (``policy.merge_requeue``) and the worker leaves the membership.
- **prober** — polls each worker's /healthz on a fixed cadence and
  drives membership through ``policy.probe_transition``: consecutive
  probe failures exclude, the first success re-admits. Recovery is
  symmetric with loss — a re-admitted worker starts taking traffic on
  the next dispatch decision.
- **hedger** (when hedging is configured) — scans in-flight batches;
  one still running past the hedge threshold (``hedge_quantile_ms``
  fixed, or the rolling ``hedge_quantile`` of recent batch round
  trips) is RE-DISPATCHED to a second worker. First answer wins and
  settles the futures; the loser is ignored (predictions are
  deterministic, so hedging is bit-safe — benchmarks/tail_bench.py
  exit-code-asserts hedge winners stay bit-identical to the
  reference). Counters ``router.hedge_fired`` / ``router.hedge_won``;
  the transport trace spans tag ``hedged`` / ``hedge_won`` /
  ``outcome="hedge_lost"`` so graftscope shows what hedging bought.

SLO classes (fleet/shield.py) ride each request: at a full pending set
admission sheds LOWEST-CLASS-FIRST — a higher-class arrival evicts the
newest queued request of the lowest class present (its Future resolves
with the typed ``Shed``; never a lost Future), otherwise the arrival
itself is shed. Counter ``router.shed_by_class`` (tags slo, mode).

Deadline awareness happens at three points: AT THE DOOR (a request no
worker's predicted completion could meet is shed immediately with
DeadlineExceeded — counter ``router.shed_infeasible``), IN THE QUEUE
(an undispatched request expires at its deadline), and implicitly in
dispatch (least-loaded = earliest predicted completion).

Requeue safety: requests carry a bounded requeue budget
(FleetConfig.max_requeues) so a fleet of dying workers degrades to
typed failures, not an infinite requeue loop; and because every worker
serves the same checkpoint through the same padding-invariant engine,
a requeued request's prediction is bit-identical wherever it lands —
benchmarks/fleet_bench.py exit-code-asserts exactly that under a
mid-traffic SIGKILL.

Elastic membership: ``add_worker`` / ``remove_worker`` grow and shrink
the fleet live (counters ``router.worker_added`` /
``router.worker_removed``) — what the autoscale controller
(fleet/autoscale.py) drives off ``queue_wait_signal_ms()``, the rolling
window over the ``router.queue_wait`` gauge.

Telemetry (docs/OBSERVABILITY.md): counters ``router.dispatch`` /
``router.requeue`` / ``router.worker_lost`` / ``router.worker_recovered``
/ ``router.worker_added`` / ``router.worker_removed`` / ``router.shed``
/ ``router.shed_by_class`` / ``router.shed_infeasible`` /
``router.deadline_exceeded`` / ``router.hedge_fired`` /
``router.hedge_won`` / ``router.brownout``, gauges ``router.members`` /
``router.queue_wait`` (admission->dispatch wait — the autoscale
signal), histograms ``router.batch_ms`` / ``router.request_total_ms``.

Distributed tracing: the router is the fleet's trace FRONT DOOR —
submit head-samples a TraceContext per request (bus.start_trace), the
dispatch path emits ``trace.router_queue`` / ``trace.transport`` /
``trace.complete`` stage spans under a ``trace.request`` root, and the
transport propagates sampled contexts so worker-side stage spans parent
under the router's transport span (telemetry/tracing.py,
tools/graftscope — docs/OBSERVABILITY.md "Distributed request
tracing").
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import math
import queue as stdlib_queue
import threading
import time
from concurrent.futures import Future

from pertgnn_tpu import telemetry
from pertgnn_tpu.config import FleetConfig
from pertgnn_tpu.fleet import policy, shield
from pertgnn_tpu.fleet.memo import PredictionMemo
from pertgnn_tpu.testing import schedules
from pertgnn_tpu.telemetry.tracing import new_span_id
from pertgnn_tpu.fleet.transport import (FleetTransport,
                                         WorkerTransportError,
                                         error_from_row, get_probe,
                                         post_predict, result_from_row)
from pertgnn_tpu.serve.errors import (DeadlineExceeded, QueueClosed,
                                      Shed)

log = logging.getLogger(__name__)

# Worker-reported per-request failures the router retries ELSEWHERE
# instead of propagating: all of them mean "this worker cannot take it
# right now", none of them is a verdict about the request itself (Shed
# is a worker-local admission verdict — another worker's queue may
# have room).
RETRYABLE_ROWS = ("QueueClosed", "QueueFull", "Shed", "EngineUnhealthy")


@dataclasses.dataclass
class _Request:
    """One admitted request in the router's custody."""

    seq: int
    entry_id: int
    ts_bucket: int
    arrival: float
    deadline_abs: float
    future: Future
    slo: str = shield.DEFAULT_CLASS
    # brownout verdict, stamped at dispatch: the worker serves this
    # request through its cheapest ladder rung (fleet/shield.py)
    downgrade: bool = False
    # lens request variants (pertgnn_tpu/lens/): the WIRE form
    # (LensRequest.to_wire dict, None for a plain request). The router
    # forwards it opaquely — validation and edit application happen at
    # the worker's own admission (the router holds no mixtures), so a
    # refused edit comes back as a typed per-request row, and BOTH legs
    # of a hedged dispatch carry the identical variant by construction.
    lens: dict | None = None
    # the prediction memo's insert permit (fleet/memo.py MemoToken),
    # stamped by the miss that admitted this request — None when the
    # memo is off, had no active generation, or the row is uncacheable
    memo_token: object = None
    requeues: int = 0
    # workers this request already FAILED on (transport loss): the
    # retry excludes them so a flapping worker cannot eat the same
    # request twice (the rollout's excluded-slot pattern)
    excluded: tuple = ()
    # distributed tracing (telemetry/tracing.py): the head-sampled
    # TraceContext (None = untraced) and the submit stamp on the
    # CLOCK_MONOTONIC clock graftscope aligns across processes
    trace: object = None
    tm_submit: float = 0.0
    # start of the CURRENT queue residency (== tm_submit until a
    # requeue resets it) — each dispatch attempt gets its own
    # trace.router_queue span instead of overlapping the first
    tm_queue_start: float = 0.0


class _Flight:
    """One dispatched microbatch's shared custody between its primary
    sender and (at most one) hedge sender. All fields are guarded by
    the router lock; ``settled`` is the first-answer-wins latch —
    whichever leg flips it owns the batch's futures, the other leg's
    answer (or failure) is ignored. ``legs`` counts in-flight legs so
    loss handling knows when NOBODY owns the batch anymore (only then
    does it requeue)."""

    __slots__ = ("batch", "primary_id", "hedge_id", "t_dispatch",
                 "settled", "legs")

    def __init__(self, batch: list[_Request], primary_id: str,
                 t_dispatch: float):
        self.batch = batch
        self.primary_id = primary_id
        self.hedge_id: str | None = None
        self.t_dispatch = t_dispatch
        self.settled = False
        self.legs = 1


class _Worker:
    """Mutable router-side state for one fleet member (guarded by the
    router lock; snapshotted into an immutable policy.WorkerView at
    each decision point)."""

    def __init__(self, worker_id: str, base_url: str, slots: int):
        self.worker_id = worker_id
        self.base_url = base_url
        self.slots = slots
        self.healthy = True
        self.inflight_batches = 0
        self.inflight_requests = 0
        self.ewma_batch_s = policy.DEFAULT_BATCH_S
        self.ewma_seen = False
        self.probe_failures = 0
        self.dispatches = 0
        self.lost_count = 0
        # assigned-but-not-yet-sent flights; the sender thread blocks
        # on this queue (None = shut down)
        self.sender_q: stdlib_queue.SimpleQueue = stdlib_queue.SimpleQueue()

    def view(self) -> policy.WorkerView:
        return policy.WorkerView(
            worker_id=self.worker_id, healthy=self.healthy,
            inflight_batches=self.inflight_batches,
            inflight_requests=self.inflight_requests,
            ewma_batch_s=self.ewma_batch_s, slots=self.slots)


class FleetRouter:
    """Deadline-aware least-loaded dispatch over N serve workers.

    ``workers`` maps worker_id -> base_url (e.g. "http://127.0.0.1:8101");
    ``request_size`` is entry_id -> (nodes, edges) (the launcher passes
    the dataset's mixture sizes — the same capacity accounting the
    single-process queue uses); ``capacity`` is the per-microbatch
    (max_graphs, max_nodes, max_edges) ceiling, normally the workers'
    top ladder rung. ``transport_post`` / ``transport_probe`` are the
    wire functions, injectable so the hedging race and the retry
    exclusion are unit-testable with no sockets (tests/test_shield.py)."""

    def __init__(self, workers: dict[str, str], request_size,
                 capacity: tuple[int, int, int],
                 cfg: FleetConfig | None = None, bus=None,
                 transport_post=None, transport_probe=get_probe,
                 memo: PredictionMemo | None = None):
        self._cfg = cfg = cfg or FleetConfig()
        self._injected_bus = bus
        # the read-mostly path (fleet/memo.py): an injected memo wins;
        # else cfg.memo_capacity_bytes > 0 builds one.  It serves
        # nothing until the launcher installs a generation
        # (memo.set_generation) — the router never invents one, because
        # only the launcher knows the checkpoint epoch and arena
        # fingerprint the cached bits depend on
        if memo is None and cfg.memo_capacity_bytes > 0:
            memo = PredictionMemo(cfg.memo_capacity_bytes, bus=bus)
        self.memo = memo
        # the data plane: None (the default) builds the graftwire
        # FleetTransport for cfg.transport — json mode reproduces the
        # legacy wire bytes over pooled connections; tests that inject
        # a transport_post callable (the historical post_predict
        # signature) bypass it entirely and nothing changes for them
        self._transport = None
        if transport_post is None:
            self._transport = FleetTransport(mode=cfg.transport,
                                             probe=transport_probe,
                                             bus=bus)
            transport_post = self._transport.post
        self._post = transport_post
        self._probe = transport_probe
        self._request_size = request_size
        self._max_graphs, self._max_nodes, self._max_edges = capacity
        self._flush_s = cfg.router_flush_deadline_ms / 1e3
        self._deadline_s = cfg.request_deadline_ms / 1e3
        self._timeout_s = cfg.dispatch_timeout_s
        self._max_requeues = cfg.max_requeues
        self._workers = {wid: _Worker(wid, url, cfg.worker_slots)
                         for wid, url in sorted(workers.items())}
        if not self._workers:
            raise ValueError("FleetRouter needs at least one worker")
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._pending: list[_Request] = []
        self._seq = 0
        self._closed = False
        self._stop_probe = threading.Event()
        # in-flight microbatches (hedging scans these); legs accounting
        # is the close-drain condition, robust to removed workers
        self._flights: set[_Flight] = set()
        self._inflight_legs = 0
        # recent completed-batch round trips (adaptive hedge threshold)
        self._batch_s_recent: collections.deque = collections.deque(
            maxlen=256)
        # recent (t, queue_wait_ms) — the autoscale signal window
        self._qwait_recent: collections.deque = collections.deque(
            maxlen=512)
        # brownout state (fleet/shield.py)
        self._brownout = False
        self._brownout_since = 0.0
        # counters mirrored to the bus (router.* names)
        self.dispatched_batches = 0
        self.dispatched_requests = 0
        self.requeues = 0
        self.worker_lost = 0
        self.worker_recovered = 0
        self.worker_added = 0
        self.worker_removed = 0
        self.shed = 0
        self.shed_infeasible = 0
        self.deadline_exceeded = 0
        self.hedge_fired = 0
        self.hedge_won = 0
        self.served = 0
        self.memo_hits = 0
        self.failed = 0
        self.shed_by_class: collections.Counter = collections.Counter()
        self._senders = [
            threading.Thread(target=self._sender_loop, args=(w,),
                             daemon=True, name=f"router-send-{wid}")
            for wid, w in self._workers.items()]
        for t in self._senders:
            t.start()
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True,
                                            name="router-dispatch")
        self._dispatcher.start()
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True, name="router-probe")
        self._prober.start()
        self._hedger = None
        if cfg.hedge_quantile_ms > 0 or 0.0 < cfg.hedge_quantile < 1.0:
            self._hedger = threading.Thread(target=self._hedge_loop,
                                            daemon=True,
                                            name="router-hedge")
            self._hedger.start()
        self.bus.gauge("router.members", len(self._workers),
                       total=len(self._workers))

    # -- client side -----------------------------------------------------

    @property
    def bus(self):
        if self._injected_bus is not None:
            return self._injected_bus
        return telemetry.get_bus()

    def submit(self, entry_id: int, ts_bucket: int,
               slo: str | None = None, lens=None) -> Future:
        """Enqueue one request; the Future resolves to its prediction
        or a typed serve error. Raises QueueClosed / Shed /
        DeadlineExceeded (door shed) at admission. ``slo`` is the
        request's SLO class (fleet/shield.py; default "standard") — at
        a full pending set admission sheds lowest-class-first.

        ``lens`` (a pertgnn_tpu/lens LensRequest, or None) rides the
        transport body to the worker, whose own admission validates it
        — a refused what-if edit or a cold attribution ladder comes
        back as the same typed error a single-process caller would see
        (WhatIfRefused / LensDisabled, not retryable). Lens futures
        resolve to a LensResult / (T,)-vector exactly like the queue's
        (transport.result_from_row)."""
        eid = int(entry_id)
        lens_wire = None
        if lens is not None:
            lens_wire = (lens.to_wire() if hasattr(lens, "to_wire")
                         else dict(lens))
        slo_cls = shield.DEFAULT_CLASS if slo is None else slo
        shield.class_priority(slo_cls)  # unknown class fails the caller
        # size it NOW so an unknown entry fails the caller, not the
        # dispatcher (same placement as the single-process queue)
        self._request_size(eid)
        # the read-mostly path: a memo hit resolves the Future right
        # here — no admission, no queue, no wire, no engine.  The key
        # is slo-independent by construction (predictions do not depend
        # on the request's class, only shedding does), and the decoded
        # row rides the same result_from_row rehydration a wire answer
        # would, so hits are bit-identical to the uncached path
        memo_token = None
        if self.memo is not None:
            row, memo_token, nbytes = self.memo.lookup(
                eid, int(ts_bucket), lens_wire)
            if row is not None:
                fut = Future()
                fut.set_result(result_from_row(row))
                with self._lock:
                    self.served += 1
                    self.memo_hits += 1
                # the wire bytes a hit never moved (the stored frame is
                # exactly what the binary transport would have carried)
                self.bus.counter("transport.cache_bytes_saved", nbytes,
                                 level=2)
                return fut
        fut = Future()
        # head-sampling decision at the fleet's front door, BEFORE the
        # lock (dice roll + urandom must not serialize admission); a
        # rejected submit discards the context unemitted — no orphans
        ctx = self.bus.start_trace()
        tm_submit = time.monotonic() if ctx is not None else 0.0
        counter = reject = None
        lowest_queued = slo_cls
        evicted: _Request | None = None
        with self._wake:
            if self._closed:
                reject = QueueClosed("FleetRouter is closed")
            elif len(self._pending) >= self._cfg.max_pending:
                pending_classes = [r.slo for r in self._pending]
                victim_i = shield.shed_victim_index(pending_classes,
                                                    slo_cls)
                if victim_i is None:
                    self.shed += 1
                    self.shed_by_class[slo_cls] += 1
                    counter = "router.shed"
                    # the lowest-priority class occupying the queue at
                    # the moment of rejection: the end-to-end evidence
                    # that lowest-class-first held (a critical reject
                    # is legitimate ONLY when the queue held nothing
                    # lower — tail_bench gates on this tag)
                    lowest_queued = max(
                        pending_classes, key=shield.class_priority,
                        default=slo_cls)
                    reject = Shed(
                        f"router pending set is at "
                        f"max_pending={self._cfg.max_pending}; "
                        f"{slo_cls} request shed", slo=slo_cls)
                else:
                    # lowest-class-first: evict the newest queued
                    # request of the lowest class present to admit
                    # this higher-class arrival (resolved below,
                    # OUTSIDE the lock)
                    evicted = self._pending.pop(victim_i)
                    self.shed += 1
                    self.shed_by_class[evicted.slo] += 1
                    self._admit_locked(eid, ts_bucket, fut, ctx,
                                       tm_submit, slo_cls,
                                       lens=lens_wire,
                                       memo_token=memo_token)
            else:
                now = time.perf_counter()
                deadline = (now + self._deadline_s
                            if self._deadline_s > 0 else math.inf)
                if self._deadline_s > 0 and policy.deadline_infeasible(
                        [w.view() for w in self._workers.values()],
                        now, deadline):
                    self.shed_infeasible += 1
                    counter = "router.shed_infeasible"
                    reject = DeadlineExceeded(
                        f"shed at the door: no worker's predicted "
                        f"completion meets the "
                        f"{self._cfg.request_deadline_ms:g}ms deadline")
                else:
                    self._admit_locked(eid, ts_bucket, fut, ctx,
                                       tm_submit, slo_cls,
                                       deadline=deadline, now=now,
                                       lens=lens_wire,
                                       memo_token=memo_token)
        if evicted is not None:
            self.bus.counter("router.shed", entry_id=evicted.entry_id)
            self.bus.counter("router.shed_by_class", slo=evicted.slo,
                             mode="evict", entry_id=evicted.entry_id)
            self._resolve_error(evicted, Shed(
                f"evicted at admission: a {slo_cls} arrival outranked "
                f"this queued {evicted.slo} request at "
                f"max_pending={self._cfg.max_pending}",
                slo=evicted.slo))
        if reject is not None:
            # bus emission outside the lock — the shed fast path fires
            # exactly when everything contends for this lock
            if counter is not None:
                self.bus.counter(counter, entry_id=eid)
            if isinstance(reject, Shed):
                self.bus.counter("router.shed_by_class", slo=slo_cls,
                                 mode="reject", entry_id=eid,
                                 lowest_queued=lowest_queued)
            raise reject
        return fut

    def _admit_locked(self, eid: int, ts_bucket: int, fut: Future, ctx,
                      tm_submit: float, slo_cls: str,
                      deadline: float | None = None,
                      now: float | None = None,
                      lens: dict | None = None,
                      memo_token=None) -> None:
        if now is None:
            now = time.perf_counter()
        if deadline is None:
            deadline = (now + self._deadline_s
                        if self._deadline_s > 0 else math.inf)
        self._pending.append(_Request(
            seq=self._seq, entry_id=eid, ts_bucket=int(ts_bucket),
            arrival=now, deadline_abs=deadline, future=fut, slo=slo_cls,
            lens=lens, memo_token=memo_token, trace=ctx,
            tm_submit=tm_submit, tm_queue_start=tm_submit))
        self._seq += 1
        self._wake.notify_all()

    def predict(self, entry_id: int, ts_bucket: int,
                timeout: float | None = None,
                slo: str | None = None) -> float:
        """Blocking convenience (same shape as MicrobatchQueue.predict)."""
        return float(self.submit(entry_id, ts_bucket,
                                 slo=slo).result(timeout))

    def queue_wait_signal_ms(self, window_s: float = 2.0) -> float:
        """Max ``router.queue_wait`` over the last `window_s` seconds —
        THE autoscale signal (fleet/autoscale.py): how long the oldest
        request of recent batches sat between admission and dispatch.
        0.0 when nothing dispatched recently (an idle fleet is a calm
        fleet)."""
        cutoff = time.perf_counter() - window_s
        with self._lock:
            while self._qwait_recent and self._qwait_recent[0][0] < cutoff:
                self._qwait_recent.popleft()
            return max((ms for _t, ms in self._qwait_recent),
                       default=0.0)

    def stats_dict(self) -> dict:
        with self._lock:
            return {
                "workers": {
                    w.worker_id: {
                        "healthy": w.healthy,
                        "dispatches": w.dispatches,
                        "inflight_batches": w.inflight_batches,
                        "ewma_batch_ms": round(w.ewma_batch_s * 1e3, 3),
                        "lost_count": w.lost_count,
                    } for w in self._workers.values()},
                "members": sum(w.healthy
                               for w in self._workers.values()),
                "dispatched_batches": self.dispatched_batches,
                "dispatched_requests": self.dispatched_requests,
                "requeues": self.requeues,
                "worker_lost": self.worker_lost,
                "worker_recovered": self.worker_recovered,
                "worker_added": self.worker_added,
                "worker_removed": self.worker_removed,
                "shed": self.shed,
                "shed_by_class": dict(self.shed_by_class),
                "shed_infeasible": self.shed_infeasible,
                "deadline_exceeded": self.deadline_exceeded,
                "hedge_fired": self.hedge_fired,
                "hedge_won": self.hedge_won,
                "brownout_active": self._brownout,
                "served": self.served,
                "memo_hits": self.memo_hits,
                "failed": self.failed,
                "pending": len(self._pending),
            }

    # -- elastic membership (fleet/autoscale.py drives these) ------------

    def add_worker(self, worker_id: str, base_url: str) -> None:
        """Grow the fleet live: the new member takes traffic on the
        next dispatch decision. The caller is responsible for the
        worker being READY (probe 200) — the autoscale controller
        verifies readiness before adding, so a cold spare never eats
        traffic it cannot serve."""
        with self._wake:
            if self._closed:
                raise QueueClosed("FleetRouter is closed")
            if worker_id in self._workers:
                raise ValueError(f"worker {worker_id!r} already a member")
            w = _Worker(worker_id, base_url, self._cfg.worker_slots)
            self._workers[worker_id] = w
            t = threading.Thread(target=self._sender_loop, args=(w,),
                                 daemon=True,
                                 name=f"router-send-{worker_id}")
            self._senders.append(t)
            self.worker_added += 1
            members = sum(x.healthy for x in self._workers.values())
            self._wake.notify_all()
        t.start()
        log.info("router: worker %s added (%d members)", worker_id,
                 members)
        self.bus.counter("router.worker_added", worker=worker_id)
        self.bus.gauge("router.members", members,
                       total=len(self._workers))

    def remove_worker(self, worker_id: str) -> None:
        """Shrink the fleet live (the autoscale retire path): the
        member stops receiving new batches NOW, its queued-but-unsent
        custody moves back to the pending queue (no requeue-budget
        charge — retirement is not the request's fault), in-flight
        legs settle normally through their sender, and the sender
        thread exits. Idempotent for unknown ids."""
        recovered: list[_Request] = []
        with self._wake:
            w = self._workers.pop(worker_id, None)
            if w is None:
                return
            self.worker_removed += 1
            while True:
                try:
                    queued = w.sender_q.get_nowait()
                except stdlib_queue.Empty:
                    break
                if queued is None:
                    continue  # close() raced; sentinel re-sent below
                self._release_leg_locked(w, queued)
                if queued.settled:
                    continue
                if queued.legs == 0:
                    queued.settled = True
                    recovered.extend(queued.batch)
            w.sender_q.put(None)
            if recovered:
                self._pending[:] = policy.merge_requeue(self._pending,
                                                        recovered)
            members = sum(x.healthy for x in self._workers.values())
            self._wake.notify_all()
        log.info("router: worker %s removed (%d members, %d request(s) "
                 "moved back)", worker_id, members, len(recovered))
        if self._transport is not None:
            self._transport.forget(w.base_url)
        self.bus.counter("router.worker_removed", worker=worker_id)
        if recovered:
            self.bus.counter("router.requeue", len(recovered),
                             worker=worker_id, reason="worker_retired")
        self.bus.gauge("router.members", members,
                       total=len(self._workers))

    def close(self) -> None:
        """Stop admissions, dispatch everything already admitted (the
        dispatcher exits only once the pending set AND every in-flight
        leg have settled), then stop the threads. Any future the
        drain could not place (e.g. the whole fleet died) resolves
        with QueueClosed — never a hang. Idempotent."""
        with self._wake:
            if self._closed:
                self._wake.notify_all()
            self._closed = True
            self._wake.notify_all()
        self._dispatcher.join()
        self._stop_probe.set()
        for w in self._workers.values():
            w.sender_q.put(None)
        for t in self._senders:
            t.join(timeout=self._timeout_s + 10.0)
        self._prober.join(timeout=5.0)
        if self._hedger is not None:
            self._hedger.join(timeout=5.0)
        # backstop for the ALWAYS-resolves invariant: nothing should be
        # left, but a future must never outlive the router unresolved
        with self._lock:
            leftovers = self._pending[:]
            self._pending.clear()
        for r in leftovers:
            self._resolve_error(r, QueueClosed(
                "router closed before this request could be dispatched "
                "(no live worker took it)"))
        if self._transport is not None:
            # after the sender joins above: no thread still owns a
            # pooled connection or an attached ring
            self._transport.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- dispatcher ------------------------------------------------------

    def _total_inflight_locked(self) -> int:
        return self._inflight_legs

    def _full_locked(self) -> bool:
        g = n = e = 0
        for r in self._pending:
            dn, de = self._request_size(r.entry_id)
            if (g + 1 > self._max_graphs or n + dn > self._max_nodes
                    or e + de > self._max_edges):
                return True
            g, n, e = g + 1, n + dn, e + de
        return False

    def _take_batch_locked(self) -> list[_Request]:
        g = n = e = 0
        take = 0
        for r in self._pending:
            dn, de = self._request_size(r.entry_id)
            if take and (g + 1 > self._max_graphs
                         or n + dn > self._max_nodes
                         or e + de > self._max_edges):
                break
            g, n, e = g + 1, n + dn, e + de
            take += 1
        batch = self._pending[:take]
        del self._pending[:take]
        return batch

    def _pop_expired_locked(self, now: float) -> list[_Request]:
        if self._deadline_s <= 0:
            return []
        expired = [r for r in self._pending if r.deadline_abs <= now]
        if expired:
            self._pending[:] = [r for r in self._pending
                                if r.deadline_abs > now]
        return expired

    def _fail_expired(self, expired: list[_Request]) -> None:
        if not expired:
            return
        # one lock round-trip per sweep (stats_dict snapshots this
        # counter); resolution + bus emission stay outside the lock
        with self._lock:
            self.deadline_exceeded += len(expired)
        for r in expired:
            self.bus.counter("router.deadline_exceeded",
                             entry_id=r.entry_id)
            self._resolve_error(r, DeadlineExceeded(
                f"request for entry {r.entry_id} waited past its "
                f"{self._cfg.request_deadline_ms:g}ms deadline without "
                f"being dispatched"))

    def _brownout_tick_locked(self, now: float) -> str | None:
        """One brownout state-machine step off the current pending
        occupancy (fleet/shield.py). Returns the transition event for
        the caller to emit OUTSIDE the lock."""
        cfg = self._cfg
        if cfg.brownout_enter_ratio <= 0:
            return None
        occupancy = len(self._pending) / max(cfg.max_pending, 1)
        active, event = shield.brownout_transition(
            self._brownout, occupancy, now, self._brownout_since,
            enter_ratio=cfg.brownout_enter_ratio,
            exit_ratio=shield.resolve_exit_ratio(
                cfg.brownout_enter_ratio, cfg.brownout_exit_ratio))
        if event is not None:
            self._brownout = active
            self._brownout_since = now
        return event

    def _dispatch_loop(self) -> None:
        while True:
            expired: list[_Request] = []
            batch: list[_Request] = []
            brownout_event = None
            with self._wake:
                while not self._pending and not (
                        self._closed
                        and self._total_inflight_locked() == 0):
                    self._wake.wait(timeout=1.0)
                if not self._pending:
                    if self._closed and self._total_inflight_locked() == 0:
                        break
                    continue
                # coalesce under the flush deadline (anchored at the
                # oldest pending arrival, same as the serve queue)
                while self._pending and not self._closed:
                    now = time.perf_counter()
                    expired += self._pop_expired_locked(now)
                    if expired:
                        break
                    if not self._pending or self._full_locked():
                        break
                    t_flush = self._pending[0].arrival + self._flush_s
                    if now >= t_flush:
                        break
                    t_wake = min([t_flush]
                                 + [r.deadline_abs for r in self._pending
                                    if r.deadline_abs < math.inf])
                    self._wake.wait(timeout=max(t_wake - now, 0.0))
                now = time.perf_counter()
                expired += self._pop_expired_locked(now)
                brownout_event = self._brownout_tick_locked(now)
                if self._pending and (
                        self._closed or self._full_locked()
                        or now >= self._pending[0].arrival + self._flush_s):
                    batch = self._take_batch_locked()
                    # the brownout verdict, stamped UNCONDITIONALLY at
                    # dispatch (freshest pressure picture): best-effort
                    # requests ride the wire with dg=True under
                    # brownout — and a requeued request stamped during
                    # a PAST brownout is un-stamped here once the mode
                    # exits, so a stale verdict never outlives the
                    # pressure that justified it
                    for r in batch:
                        r.downgrade = (self._brownout
                                       and r.slo == shield.BEST_EFFORT)
            if brownout_event is not None:
                log.warning("router: brownout %s (pending occupancy "
                            "crossed the configured threshold — "
                            "best-effort traffic %s rung-downgraded)",
                            brownout_event,
                            "now" if brownout_event == "enter"
                            else "no longer")
                self.bus.counter("router.brownout",
                                 event=brownout_event)
            self._fail_expired(expired)
            if batch:
                self._assign(batch)
        log.debug("router dispatcher drained and exited")

    def _assign(self, batch: list[_Request]) -> None:
        """Place one microbatch on the least-loaded worker; blocks while
        every healthy worker is slot-saturated (senders notify on
        completion). Requests can still expire while waiting — a
        deadline is a dispatch deadline. Workers a retried request
        already failed on are EXCLUDED from the choice (falling back to
        ignoring exclusions only when they leave nobody — one
        surviving-but-flapping worker still beats failing the
        request)."""
        target: _Worker | None = None
        flight: _Flight | None = None
        while True:
            expired: list[_Request] = []
            fleet_dead = False
            with self._wake:
                now = time.perf_counter()
                if self._deadline_s > 0:
                    expired = [r for r in batch if r.deadline_abs <= now]
                    batch = [r for r in batch if r.deadline_abs > now]
                if batch:
                    views = [w.view() for w in self._workers.values()]
                    exclude = frozenset().union(
                        *[frozenset(r.excluded) for r in batch])
                    view = policy.choose_worker(views, exclude)
                    if view is None and exclude:
                        view = policy.choose_worker(views)
                    if view is not None:
                        target = self._workers[view.worker_id]
                        flight = _Flight(batch, target.worker_id, now)
                        self._flights.add(flight)
                        self._inflight_legs += 1
                        target.inflight_batches += 1
                        target.inflight_requests += len(batch)
                        target.dispatches += 1
                        self.dispatched_batches += 1
                        self.dispatched_requests += len(batch)
                        self._qwait_recent.append(
                            (now, (now - batch[0].arrival) * 1e3))
                    elif (self._closed and not any(
                            w.healthy for w in self._workers.values())):
                        # close-drain with a fully dead fleet: there is
                        # nobody left to take this work, ever (futures
                        # resolve OUTSIDE the lock — a done-callback
                        # must not deadlock on re-entry)
                        fleet_dead = True
                    else:
                        self._wake.wait(timeout=0.05)
            self._fail_expired(expired)
            if fleet_dead:
                self._fail_batch(batch, QueueClosed(
                    "router draining with no live workers"))
                return
            if not batch:
                return
            if target is not None:
                now = time.perf_counter()
                # the queue-wait gauge ROADMAP item 3's autoscale
                # threshold reads: admission -> dispatch of the oldest
                # request in this batch, at BASIC level (one write per
                # BATCH — an autoscaler must not need trace verbosity)
                self.bus.gauge("router.queue_wait",
                               (now - batch[0].arrival) * 1e3,
                               worker=target.worker_id,
                               graphs=len(batch))
                self.bus.counter("router.dispatch", level=2,
                                 worker=target.worker_id,
                                 graphs=len(batch))
                # per-request router-queue stage spans, emitted BEFORE
                # the sender takes ownership (a buffered context must
                # never be appended to after its finish flushes it)
                tm_now = time.monotonic()
                for r in batch:
                    if r.trace is not None:
                        self.bus.trace_span(
                            "trace.router_queue", r.trace,
                            r.tm_queue_start, tm_now,
                            worker=target.worker_id,
                            attempt=r.requeues)
                # interleaving hook (testing/schedules.py): the gap a
                # concurrent remove_worker can land in — the window
                # the membership re-check below exists for;
                # tests/test_schedules.py drives both orders
                schedules.sync_point("fleet.assign.handoff")
                with self._wake:
                    # the handoff must be atomic with membership:
                    # remove_worker drains the sender queue and sends
                    # the exit sentinel under this lock — a flight put
                    # AFTER the sentinel would never be consumed (its
                    # futures never resolve, close() hangs on the leg
                    # count). If the worker retired in the gap, undo
                    # the leg accounting and re-choose.
                    handed = self._workers.get(target.worker_id) is target
                    if handed:
                        target.sender_q.put(flight)
                    else:
                        self._release_leg_locked(target, flight)
                        target.dispatches -= 1
                        self.dispatched_batches -= 1
                        self.dispatched_requests -= len(batch)
                schedules.sync_point("fleet.assign.handoff_done")
                if handed:
                    return
                target = flight = None

    # -- hedging ---------------------------------------------------------

    def _hedge_loop(self) -> None:
        """Scan in-flight batches; re-dispatch stragglers past the
        hedge threshold to a second worker. First answer wins (the
        ``_Flight.settled`` latch); the loser is ignored."""
        cfg = self._cfg
        while not self._stop_probe.wait(0.02):
            fired: list[tuple[_Worker, _Flight, float]] = []
            with self._wake:
                thr = policy.hedge_threshold_s(cfg.hedge_quantile_ms,
                                               cfg.hedge_quantile,
                                               self._batch_s_recent)
                if thr == math.inf:
                    continue
                now = time.perf_counter()
                views = [w.view() for w in self._workers.values()]
                for flight in list(self._flights):
                    if flight.settled or flight.hedge_id is not None:
                        continue
                    age = now - flight.t_dispatch
                    if age < thr:
                        continue
                    # exclude the primary AND every worker this batch
                    # already failed on — hedging to a re-admitted
                    # flapping worker would re-open exactly the hole
                    # the retry exclusion closes (and a flight is
                    # never hedged twice, so a dead hedge leg leaves
                    # the straggler unprotected)
                    view = policy.choose_hedge_worker(
                        views, exclude={flight.primary_id}.union(
                            *[frozenset(r.excluded)
                              for r in flight.batch]))
                    if view is None:
                        continue
                    hw = self._workers[view.worker_id]
                    flight.hedge_id = hw.worker_id
                    flight.legs += 1
                    self._inflight_legs += 1
                    hw.inflight_batches += 1
                    hw.inflight_requests += len(flight.batch)
                    hw.dispatches += 1
                    self.hedge_fired += 1
                    hw.sender_q.put(flight)
                    fired.append((hw, flight, age))
                    # the accounting above staled the snapshot —
                    # re-take it so a second straggler this tick sees
                    # the hedge load it just added (never over-hedge
                    # one worker off a stale picture)
                    views = [w.view() for w in self._workers.values()]
            for hw, flight, age in fired:
                log.warning("router: hedged a %d-request batch to %s "
                            "after %.1fms (primary %s straggling past "
                            "the %.1fms threshold)", len(flight.batch),
                            hw.worker_id, age * 1e3, flight.primary_id,
                            thr * 1e3)
                self.bus.counter("router.hedge_fired",
                                 worker=hw.worker_id,
                                 primary=flight.primary_id,
                                 graphs=len(flight.batch),
                                 threshold_ms=round(thr * 1e3, 3))

    # -- senders ---------------------------------------------------------

    def _release_leg_locked(self, w: _Worker, flight: _Flight) -> None:
        """Account one leg of `flight` leaving worker `w`'s custody
        (answered, failed, drained, or skipped). Caller holds the
        lock."""
        w.inflight_batches -= 1
        w.inflight_requests -= len(flight.batch)
        flight.legs -= 1
        self._inflight_legs -= 1
        if flight.legs == 0:
            self._flights.discard(flight)

    def _sender_loop(self, w: _Worker) -> None:
        while True:
            flight = w.sender_q.get()
            if flight is None:
                return
            role = ("hedge" if flight.primary_id != w.worker_id
                    else "primary")
            with self._wake:
                skip = flight.settled
                if skip:
                    # the other leg already won while this hedge sat in
                    # the sender queue: nothing to send, nothing to tag
                    self._release_leg_locked(w, flight)
                    self._wake.notify_all()
            if skip:
                continue
            batch = flight.batch
            # transport span ids are pre-allocated so the worker can
            # parent its stage spans under them (the propagation);
            # the span itself is emitted after the round trip settles
            sids = [new_span_id() if r.trace is not None else None
                    for r in batch]
            trace_meta = [
                {"tid": r.trace.trace_id, "psid": sid}
                if r.trace is not None and r.trace.sampled else None
                for r, sid in zip(batch, sids)]
            slo_meta = [r.slo if r.slo != shield.DEFAULT_CLASS else None
                        for r in batch]
            dg_meta = [r.downgrade for r in batch]
            # lens variants ride every leg identically (the hedge leg
            # rebuilds this list from the same _Request objects), so a
            # hedged what-if/attribution answer is bit-identical to the
            # primary's regardless of which leg wins. The kwarg itself
            # follows the omit-when-default rule one level up too: an
            # all-plain batch never passes it, so pre-lens injected
            # transports (tests) keep working unchanged.
            lens_meta = [r.lens for r in batch]
            lens_kw = ({"lens": lens_meta}
                       if any(ln is not None for ln in lens_meta) else {})
            t0 = time.perf_counter()
            tm0 = time.monotonic()
            try:
                rows = self._post(
                    w.base_url, [r.entry_id for r in batch],
                    [r.ts_bucket for r in batch], self._timeout_s,
                    trace=trace_meta, slo=slo_meta, dg=dg_meta,
                    **lens_kw)
            except WorkerTransportError as exc:
                self._on_leg_failed(w, flight, role, exc, tm0, sids)
                continue
            self._on_leg_done(w, flight, role, rows,
                              time.perf_counter() - t0, tm0,
                              time.monotonic(), sids)

    def _on_leg_done(self, w: _Worker, flight: _Flight, role: str,
                     rows: list[dict], dt: float, tm0: float,
                     tm1: float, sids: list) -> None:
        """One leg answered. The first answer WINS the flight and
        settles the batch's futures; a loser only updates the worker's
        latency estimate and tags its trace spans ``hedge_lost``."""
        batch = flight.batch
        alpha = self._cfg.latency_ewma_alpha
        with self._wake:
            won = not flight.settled
            if won:
                flight.settled = True
                if role == "hedge":
                    self.hedge_won += 1
            self._release_leg_locked(w, flight)
            w.ewma_batch_s = (dt if not w.ewma_seen else
                              alpha * dt + (1 - alpha) * w.ewma_batch_s)
            w.ewma_seen = True
            self._batch_s_recent.append(dt)
            self._wake.notify_all()
        self.bus.histogram("router.batch_ms", dt * 1e3, level=2,
                           worker=w.worker_id, graphs=len(batch))
        hedged = flight.hedge_id is not None
        # which wire THIS leg actually travelled (json/binary/shm) — the
        # transport records it per endpoint after every post, so hedge
        # legs to a differently-negotiated worker tag truthfully
        wire_used = (self._transport.wire_for(w.base_url)
                     if self._transport is not None else "json")
        if not won:
            # the losing leg of a hedge race: futures are already
            # resolved (bit-identical predictions make the race safe);
            # tag the spans so graftscope shows what hedging bought
            for r, sid in zip(batch, sids):
                if r.trace is not None:
                    self.bus.trace_span("trace.transport", r.trace,
                                        tm0, tm1, span_id=sid,
                                        worker=w.worker_id,
                                        outcome="hedge_lost", role=role,
                                        wire=wire_used)
            return
        if won and role == "hedge":
            self.bus.counter("router.hedge_won", worker=w.worker_id,
                             primary=flight.primary_id,
                             graphs=len(batch))
        retry: list[_Request] = []
        give_up: list[tuple[_Request, Exception]] = []
        tm_requeue = time.monotonic()
        # retry triage BEFORE the republish: requeues/tm_queue_start
        # are winner-custody state (the settled latch above makes this
        # leg the batch's sole owner; the loser never touches requests)
        for r, row in zip(batch, rows):
            if row.get("error") in RETRYABLE_ROWS:
                r.requeues += 1
                if r.requeues > self._max_requeues:
                    give_up.append((r, error_from_row(row)))
                else:
                    r.tm_queue_start = tm_requeue
                    retry.append(r)
        retry_set = {id(r) for r in retry}
        # transport stage spans: every attempt gets one, tagged with
        # its verdict — a retried request's trace shows BOTH legs.
        # Emitted BEFORE merge_requeue republishes the retries: a
        # TraceContext's buffer is single-owner/no-lock, and the
        # moment a retry is back in the pending queue another thread
        # may emit on (or finish) its context
        for r, row, sid in zip(batch, rows, sids):
            if r.trace is None:
                continue
            outcome = ("retry" if id(r) in retry_set
                       else "ok" if "pred" in row else "error")
            tags = {"worker": w.worker_id, "outcome": outcome,
                    "wire": wire_used}
            if hedged:
                tags["hedged"] = True
                tags["hedge_won"] = role == "hedge"
            self.bus.trace_span("trace.transport", r.trace, tm0, tm1,
                                span_id=sid, **tags)
        if retry:
            with self._wake:
                self.requeues += len(retry)
                self._pending[:] = policy.merge_requeue(self._pending,
                                                        retry)
                self._wake.notify_all()
            self.bus.counter("router.requeue", len(retry),
                             worker=w.worker_id, reason="worker_busy")
        t_done = time.perf_counter()
        n_served = 0
        for r, row in zip(batch, rows):
            if id(r) in retry_set:
                continue
            if "pred" in row:
                n_served += 1
                self.bus.histogram("router.request_total_ms",
                                   (t_done - r.arrival) * 1e3, level=2)
                # populate the memo under winner custody only (the
                # settled latch above): the losing hedge leg never
                # inserts, and a stale token (a rollout flipped the
                # generation while this flight was in the air) is
                # refused inside insert — never stored
                if self.memo is not None and r.memo_token is not None:
                    self.memo.insert(r.memo_token, row)
                r.future.set_result(result_from_row(row))
                if r.trace is not None:
                    tm_settle = time.monotonic()
                    self.bus.trace_span("trace.complete", r.trace, tm1,
                                        tm_settle)
                    self.bus.finish_trace("trace.request", r.trace,
                                          r.tm_submit, tm_settle,
                                          outcome="ok",
                                          entry_id=r.entry_id,
                                          **({"hedge_won":
                                              role == "hedge"}
                                             if hedged else {}))
            else:
                self._resolve_error(r, error_from_row(row))
        if n_served:
            with self._lock:
                self.served += n_served
        for r, exc in give_up:
            self._resolve_error(r, exc)

    def _on_leg_failed(self, w: _Worker, flight: _Flight, role: str,
                       exc: WorkerTransportError, tm0: float,
                       sids: list) -> None:
        """Transport-level failure of one leg: exclude the worker NOW
        and move its entire unsettled custody — this flight (only if no
        other leg still owns it) plus anything still queued for the
        worker — back into the pending queue in submission order, each
        request remembering the failed worker so the retry EXCLUDES it.
        Requests over their requeue budget fail with the transport
        error instead of looping forever."""
        tm1 = time.monotonic()
        wire_used = (self._transport.wire_for(w.base_url)
                     if self._transport is not None else "json")
        for r, sid in zip(flight.batch, sids):
            if r.trace is not None:
                self.bus.trace_span("trace.transport", r.trace, tm0,
                                    tm1, span_id=sid,
                                    worker=w.worker_id, outcome="lost",
                                    role=role, wire=wire_used)
        recovered: list[_Request] = []
        give_up: list[_Request] = []
        with self._wake:
            was_healthy = w.healthy
            w.healthy = False
            w.probe_failures = 0
            w.lost_count += 1
            self._release_leg_locked(w, flight)
            if not flight.settled and flight.legs == 0:
                # nobody else owns this batch anymore — requeue it
                flight.settled = True
                recovered.extend(flight.batch)
            while True:
                try:
                    queued = w.sender_q.get_nowait()
                except stdlib_queue.Empty:
                    break
                if queued is None:
                    # close() raced the loss; put the sentinel back so
                    # this sender still terminates
                    w.sender_q.put(None)
                    break
                self._release_leg_locked(w, queued)
                if not queued.settled and queued.legs == 0:
                    queued.settled = True
                    recovered.extend(queued.batch)
            keep: list[_Request] = []
            tm_requeue = time.monotonic()
            for r in recovered:
                r.requeues += 1
                # remember the failure so the retry excludes this
                # worker even if a probe re-admits it first (the
                # flapping-worker hole this satellite closes)
                if w.worker_id not in r.excluded:
                    r.excluded = (*r.excluded, w.worker_id)
                if r.requeues > self._max_requeues:
                    give_up.append(r)
                else:
                    r.tm_queue_start = tm_requeue
                    keep.append(r)
            if keep:
                self.requeues += len(keep)
                self._pending[:] = policy.merge_requeue(self._pending,
                                                        keep)
            self.worker_lost += 1
            members = sum(x.healthy for x in self._workers.values())
            self._wake.notify_all()
        if self._transport is not None:
            self._transport.forget(w.base_url)
        log.error("router: worker %s lost (%s); requeued %d request(s), "
                  "%d member(s) remain", w.worker_id, exc, len(keep),
                  members)
        self.bus.counter("router.worker_lost", worker=w.worker_id,
                         was_healthy=was_healthy)
        if keep:
            self.bus.counter("router.requeue", len(keep),
                             worker=w.worker_id, reason="worker_lost")
        self.bus.gauge("router.members", members,
                       total=len(self._workers))
        for r in give_up:
            self._resolve_error(r, WorkerTransportError(
                f"request for entry {r.entry_id} exceeded its requeue "
                f"budget ({self._max_requeues}); last worker failure: "
                f"{exc}"))

    # -- membership ------------------------------------------------------

    def _probe_loop(self) -> None:
        interval = max(self._cfg.health_poll_interval_s, 0.05)
        timeout = max(1.0, interval)
        while not self._stop_probe.wait(interval):
            for w in list(self._workers.values()):
                try:
                    status, _body = self._probe(w.base_url, timeout)
                    ok = status == 200
                except WorkerTransportError:
                    ok = False
                self._apply_probe(w, ok)

    def _apply_probe(self, w: _Worker, ok: bool) -> None:
        with self._wake:
            if w.worker_id not in self._workers:
                return  # removed while this poll was in flight
            healthy, fails, event = policy.probe_transition(
                w.healthy, w.probe_failures, ok,
                self._cfg.probe_lost_after)
            w.healthy, w.probe_failures = healthy, fails
            if event == "lost":
                w.lost_count += 1
                self.worker_lost += 1
            elif event == "recovered":
                self.worker_recovered += 1
            members = sum(x.healthy for x in self._workers.values())
            if event is not None:
                self._wake.notify_all()
        if event is None:
            return
        if self._transport is not None:
            # a lost/recovered transition invalidates the negotiated
            # wire: the replacement process on the same port may speak
            # a different protocol (version skew during rolling
            # restarts), so re-probe before the next post
            self._transport.forget(w.base_url)
        log.warning("router: worker %s %s via probe (%d/%d members)",
                    w.worker_id, event, members, len(self._workers))
        # literal names, not f"router.worker_{event}": the telemetry
        # contract is greppable (graftlint telemetry-drift) — a dynamic
        # name is invisible to the docs check and to anyone auditing
        # docs/OBSERVABILITY.md against the source
        counter = ("router.worker_lost" if event == "lost"
                   else "router.worker_recovered")
        self.bus.counter(counter, worker=w.worker_id, via="probe")
        self.bus.gauge("router.members", members,
                       total=len(self._workers))

    # -- shared ----------------------------------------------------------

    def _resolve_error(self, r: _Request, exc: Exception) -> None:
        """Settle one request with a typed failure. ALWAYS called
        without the router lock held (senders, dispatcher, close) —
        Future done-callbacks run inline and may re-enter submit."""
        if not r.future.done():
            with self._lock:
                self.failed += 1
            r.future.set_exception(exc)
            if r.trace is not None:
                self.bus.finish_trace("trace.request", r.trace,
                                      r.tm_submit, time.monotonic(),
                                      outcome="error",
                                      error=type(exc).__name__,
                                      entry_id=r.entry_id)

    def _fail_batch(self, batch: list[_Request], exc: Exception) -> None:
        for r in batch:
            self._resolve_error(r, exc)
