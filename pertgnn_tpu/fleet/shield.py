"""SLO-class admission, lowest-class-first shedding, and brownout.

The fleet's overload story before this module was class-blind: at
``max_pending`` the NEWEST arrival was shed, whoever it was — so a
burst of best-effort batch traffic could starve the interactive
requests the SLO actually protects. This module makes priority a
first-class admission input, as three PURE decision functions (the
fleet/policy.py discipline — unit-testable with no queues, threads, or
clocks); `serve/queue.py` and `fleet/router.py` own the mutable state
and call these at their admission and dispatch points.

**SLO classes.** Three classes, priority by position — `critical` (the
p99.9-gated interactive tier), `standard` (the default; pre-SLO callers
land here), `best_effort` (batch/backfill traffic, first to brown out
and first to shed). The class rides each request: `submit(..., slo=)`
at both front doors, and per-request over the fleet transport body.

**Lowest-class-first shedding** (`shed_victim_index`). At a full
pending set the arrival and the queue compete BY CLASS: if some queued
request has strictly lower priority than the arrival, the NEWEST such
lowest-class request is evicted (its Future resolves with a typed
``Shed`` — never a lost Future) and the arrival is admitted; otherwise
the arrival itself is shed. Newest-victim-first preserves the oldest
work (it has waited longest and is closest to dispatch); the invariant
benchmarks/tail_bench.py exit-code-asserts is that no `critical`
request is ever shed while `best_effort` traffic was being admitted.

**Brownout** (`brownout_transition`). Between "healthy" and "shedding"
there is a cheaper lever: degrade best-effort service quality before
refusing anyone. Under brownout the router marks best-effort requests
for DOWNGRADE and the worker serves them through the CHEAPEST ladder
rung (serve/engine.py `max_rung` — small-shape executables, a fraction
of the top rung's padded compute) instead of coalescing them into
full-size batches. The mode is a hysteresis state machine over pending
occupancy: enter at `enter_ratio`, exit below `exit_ratio` after
`min_dwell_s` (no flapping on a noisy boundary).
"""

from __future__ import annotations

# Priority by position: index 0 is the highest class, shed last.
SLO_CLASSES = ("critical", "standard", "best_effort")

# What pre-SLO callers get: the middle of the ladder, so a class-aware
# deployment can both protect traffic above it and sacrifice traffic
# below it without touching legacy callers.
DEFAULT_CLASS = "standard"

BEST_EFFORT = "best_effort"


def class_priority(slo: str) -> int:
    """Priority rank of one class (0 = highest). Raises on unknown
    names — a typo'd class must fail the caller at submit, not silently
    ride at some default priority."""
    try:
        return SLO_CLASSES.index(slo)
    except ValueError:
        raise ValueError(f"unknown SLO class {slo!r} "
                         f"(choose from {SLO_CLASSES})") from None


def shed_victim_index(pending_classes, incoming: str) -> int | None:
    """Which queued request to evict so `incoming` can be admitted to a
    FULL pending set — or None when the incoming request itself is the
    one to shed.

    ``pending_classes`` is the queued requests' class names in
    submission order. The victim is the NEWEST (last-submitted) request
    of the lowest-priority class present, and only when that class is
    STRICTLY lower-priority than the incoming one: equal classes never
    evict each other (FIFO within a class — an arrival cannot bump its
    own peers), and a lower-class arrival never evicts anyone."""
    inc = class_priority(incoming)
    victim_i = None
    victim_pri = inc
    for i, cls in enumerate(pending_classes):
        pri = class_priority(cls)
        if pri > victim_pri or (victim_i is not None and pri == victim_pri):
            # strictly lower class than anything seen (or another, NEWER
            # member of the current victim class): the newest of the
            # lowest class wins the eviction
            victim_i, victim_pri = i, pri
    return victim_i


def brownout_transition(active: bool, occupancy: float, now: float,
                        last_change: float, *, enter_ratio: float,
                        exit_ratio: float, min_dwell_s: float = 0.5
                        ) -> tuple[bool, str | None]:
    """Hysteresis state machine for the brownout mode, as a pure
    function of one pressure observation: (active', event) where event
    is "enter" | "exit" | None.

    ``occupancy`` is pending/max_pending at the front door (the same
    pressure signal admission sheds on — brownout is the rung BELOW
    shedding, so it keys on the same scale). ``enter_ratio`` <= 0
    disables the mode entirely. Exit requires occupancy below
    ``exit_ratio`` AND ``min_dwell_s`` since the last transition, so a
    queue oscillating on the boundary cannot flap the downgrade."""
    if enter_ratio <= 0:
        return False, ("exit" if active else None)
    if not active:
        if occupancy >= enter_ratio:
            return True, "enter"
        return False, None
    if occupancy < exit_ratio and now - last_change >= min_dwell_s:
        return False, "exit"
    return True, None


def resolve_exit_ratio(enter_ratio: float, exit_ratio: float) -> float:
    """The effective brownout exit threshold: an explicit
    ``exit_ratio`` > 0 wins; otherwise half the enter ratio (a
    hysteresis gap wide enough that entering never implies
    immediately exiting)."""
    return exit_ratio if exit_ratio > 0 else enter_ratio / 2.0
