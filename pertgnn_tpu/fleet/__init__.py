"""Replicated serve fleet: front-door router + N warm serve workers.

PRs 1-6 made ONE serve process fast (bucketed AOT engine), observable
(telemetry bus), crash-tolerant (typed failures, watchdog, drain), and
instantly warm (AOT executable store + arena store). This package
scales that process out without weakening any of it:

- ``policy``    — the dispatch brain as PURE FUNCTIONS: least-loaded =
  earliest predicted completion, deadline feasibility at the door,
  submission-order requeue merging, probe-driven membership
  transitions (unit-tested with no subprocesses);
- ``transport`` — the boring wire: stdlib HTTP on 127.0.0.1, JSON
  microbatches, typed errors by class name, plus the worker-side
  server wrapping a full PR-4-hardened engine+queue stack;
- ``router``    — the front door: owns the client-facing request
  queue, coalesces microbatches, dispatches to the
  predicted-earliest-completion worker (excluding workers a retry
  already failed on), HEDGES stragglers to a second worker
  (first answer wins — bit-safe), requeues a lost worker's custody to
  the survivors, and drives membership from /healthz — growable live
  via add_worker/remove_worker;
- ``shield``    — SLO classes, lowest-class-first shedding, and the
  brownout hysteresis, as pure decision functions;
- ``loadgen``   — open-loop trace-replay load generation: burst and
  diurnal envelopes, Zipf popularity, SLO mix, deterministic per seed;
- ``autoscale`` — elastic warm spares off the router's queue-wait
  signal (spawn from the shared AOT/arena stores, retire on cooldown).

``cli/fleet_main.py`` is the launcher (spawns N workers warm from the
shared --compile_cache_dir/--arena_cache_dir, then routes a request
stream); ``benchmarks/fleet_bench.py`` exit-code-asserts scaling,
warm start, and the SIGKILL-a-worker chaos invariants.
"""

from pertgnn_tpu.fleet.autoscale import AutoscaleController
from pertgnn_tpu.fleet.policy import (WorkerView, choose_hedge_worker,
                                      choose_worker,
                                      deadline_infeasible,
                                      hedge_threshold_s, merge_requeue,
                                      predicted_completion_s,
                                      probe_transition)
from pertgnn_tpu.fleet.router import FleetRouter
from pertgnn_tpu.fleet.shield import (DEFAULT_CLASS, SLO_CLASSES,
                                      brownout_transition,
                                      class_priority, shed_victim_index)
from pertgnn_tpu.fleet.transport import (WorkerServer,
                                         WorkerTransportError, get_probe,
                                         post_predict)

__all__ = ["FleetRouter", "WorkerServer", "WorkerTransportError",
           "WorkerView", "AutoscaleController", "choose_worker",
           "choose_hedge_worker", "deadline_infeasible",
           "hedge_threshold_s", "merge_requeue",
           "predicted_completion_s", "probe_transition", "get_probe",
           "post_predict", "SLO_CLASSES", "DEFAULT_CLASS",
           "class_priority", "shed_victim_index",
           "brownout_transition"]
