"""Console-script launcher for graftaudit (docs/LINTS.md).

Same pattern as graftlint_cli.py: graftaudit traces the programs of a
SOURCE TREE, so it only makes sense where one exists — an editable
(in-repo) install, where this package sits inside the repo checkout
and `tools/graftaudit/` is its sibling. The launcher lives inside
`pertgnn_tpu` so the wheel never ships a generic top-level `tools`
package (namespace squatting), while the `graftaudit` entry point
still works in the install mode where the tool is usable — and fails
with a clear message, not a ModuleNotFoundError, everywhere else.
"""

from __future__ import annotations

import os
import sys


def main(argv: list[str] | None = None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo, "tools", "graftaudit")):
        print(
            "graftaudit: no tools/graftaudit next to this package — the "
            "auditor traces a repo working tree's programs, which only "
            "an editable (in-repo) install has. From a checkout, run "
            "`python -m tools.graftaudit` (docs/LINTS.md).",
            file=sys.stderr)
        return 2
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.graftaudit.cli import main as graftaudit_main

    return graftaudit_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
