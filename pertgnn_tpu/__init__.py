"""pertgnn_tpu — a TPU-native framework for microservice latency prediction.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
handasontam/PERT-GNN-KDD23 (mounted read-only at /root/reference): predicting
end-to-end latency of microservice requests (Alibaba 2021 cluster trace) with a
graph-transformer over per-entry mixtures of call-graph topologies
(span graphs and activity-on-node PERT DAGs).

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

- ``ingest/``   — L0-L2: raw span CSV cleaning, entry detection, filters,
                  runtime-pattern factorization, resource feature table.
                  Pure pandas/numpy, host-side.
- ``graphs/``   — trace → span-graph and PERT-graph construction (numpy).
- ``batching/`` — offline mixture collation into flat arrays + fixed-shape
                  packed batches (jraph-style) with validity masks.
- ``ops/``      — XLA segment ops (segment softmax, masked pooling) and the
                  Pallas fused edge-attention kernel.
- ``models/``   — flax modules: graph-transformer layers, masked BatchNorm,
                  the PertGNN regression model.
- ``train/``    — jit'd optax train loop, pinball loss, masked metrics,
                  orbax checkpointing.
- ``parallel/`` — device mesh, shard_map data parallelism, tensor-parallel
                  sharding rules, edge-sharded segment ops for giant graphs.
- ``native/``   — C++ fast paths for host-side hot loops (ctypes bindings,
                  numpy fallback).
- ``utils/``    — profiling, logging.
- ``cli/``      — preprocess / train entry points.
"""

__version__ = "0.1.0"
