"""Deterministic interleaving harness — the dynamic twin of graftsync.

graftsync (tools/graftsync) proves thread-protocol properties
statically; this module drives the interleavings the proofs cannot
reach: a :class:`ScriptedScheduler` turns named SYNC POINTS into a
totally ordered script, so a race is explored in BOTH orders on
purpose instead of once per lucky chaos draw (the generalization of
the PR-13 hedge race test, which hand-built the same idea from two
Events).

Production code exposes a sync point the same way it exposes a fault
hook (pertgnn_tpu/testing/faults.py): one module-global read —

    from pertgnn_tpu.testing import schedules
    ...
    schedules.sync_point("fleet.assign.handoff")

With no scheduler installed the call is a None check and costs
nothing. Under a test, ``install(ScriptedScheduler([...]))`` makes
every listed point BLOCK until it is the next unconsumed entry of the
script; points not (or no longer) in the script pass through freely,
so the same instrumented code runs under any script — including the
empty one.

Deadlock safety: a point that cannot become the head within
``timeout_s`` marks the scheduler BROKEN and raises
:class:`ScheduleTimeout` in every blocked thread — a test failure,
never a hung suite (the tier-1 watchdog in tests/conftest.py is the
backstop of last resort).

Current production sync points:

- ``fleet.assign.handoff`` — fleet/router.py ``_assign``, after the
  worker is chosen and the flight accounted, before the
  membership-atomic sender handoff (the ``remove_worker`` race
  window);
- ``fleet.assign.handoff_done`` — same site, after the flight was
  handed to (or released from) the chosen sender.

tests/test_schedules.py drives the three nastiest races in both
orders through this harness (hedge-settle vs. primary-answer,
autoscale ``remove_worker`` vs. in-flight dispatch, drain vs. queue
close) and pins bit-identical, exactly-once resolution under every
explored order.
"""

from __future__ import annotations

import threading
import time

__all__ = ["ScheduleTimeout", "ScriptedScheduler", "sync_point",
           "install", "uninstall", "active"]


class ScheduleTimeout(RuntimeError):
    """A scripted point could not be reached/consumed in time — the
    schedule deadlocked (or the script names a point the code never
    hits). Every thread blocked on the scheduler gets this."""


class ScriptedScheduler:
    """A totally ordered script over named sync points.

    ``script`` is the exact order in which the listed points may
    proceed; each entry is consumed once. ``point(name)``:

    - name not in the remaining script → passes through immediately
      (recorded in :attr:`passed` for debugging, not in
      :attr:`trace`);
    - name is the head → consumes it, notifies everyone, proceeds;
    - name appears later → blocks until everything before it has been
      consumed (or ``timeout_s`` passes → broken + ScheduleTimeout
      everywhere).

    Use as a context manager to install/uninstall around a test;
    :meth:`finished` tells whether the whole script was consumed.
    """

    def __init__(self, script: list[str], timeout_s: float = 10.0):
        self.script = list(script)
        self.timeout_s = float(timeout_s)
        self.trace: list[str] = []     # consumed points, in order
        self.passed: list[str] = []    # unscripted pass-throughs
        self._pos = 0
        self._cv = threading.Condition()
        self._broken: str | None = None

    # -- the point --------------------------------------------------------

    def point(self, name: str) -> None:
        with self._cv:
            if self._broken is not None:
                raise ScheduleTimeout(self._broken)
            if name not in self.script[self._pos:]:
                self.passed.append(name)
                return
            deadline = time.monotonic() + self.timeout_s
            while (self._broken is None
                   and (self._pos >= len(self.script)
                        or self.script[self._pos] != name)):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(
                        timeout=min(remaining, self.timeout_s)):
                    if remaining <= 0:
                        self._broken = (
                            f"sync point {name!r} waited "
                            f"{self.timeout_s:g}s for script head "
                            f"{self.script[self._pos:][:3]!r} — the "
                            f"schedule deadlocked")
                        self._cv.notify_all()
                        raise ScheduleTimeout(self._broken)
            if self._broken is not None:
                raise ScheduleTimeout(self._broken)
            self._pos += 1
            self.trace.append(name)
            self._cv.notify_all()

    # -- bookkeeping ------------------------------------------------------

    def finished(self) -> bool:
        with self._cv:
            return self._pos >= len(self.script)

    def abort(self, reason: str = "aborted by the test") -> None:
        """Wake every blocked thread with ScheduleTimeout — cleanup
        path for a test that already failed for another reason."""
        with self._cv:
            self._broken = reason
            self._cv.notify_all()

    def __enter__(self) -> "ScriptedScheduler":
        install(self)
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        uninstall()
        if exc_type is not None:
            self.abort(f"test raised {exc_type.__name__}")
        return False


# -- module-global hook ----------------------------------------------------

_ACTIVE: ScriptedScheduler | None = None


def active() -> ScriptedScheduler | None:
    return _ACTIVE


def install(scheduler: ScriptedScheduler) -> None:
    global _ACTIVE
    _ACTIVE = scheduler


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def sync_point(name: str) -> None:
    """The production hook: free when no scheduler is installed."""
    s = _ACTIVE
    if s is not None:
        s.point(name)
