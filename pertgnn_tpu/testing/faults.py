"""Deterministic, seedable fault injection (the chaos half of serving).

The serving path claims invariants under failure — no innocent request
loses its prediction, no future hangs forever, watchdog trips recover —
and claims need falsifiable tests, not hope. A ``FaultPlan`` is a list
of ``FaultSpec``s armed at named hook sites in production code:

===================  =====================================================
site                 where it fires
===================  =====================================================
``serve.dispatch``   InferenceEngine.predict_microbatch, before the
                     executable runs (``error`` raises, ``wedge`` stalls
                     the dispatch, ``nan`` corrupts the batch output,
                     ``delay`` stalls it ``delay_s`` and then SUCCEEDS —
                     the straggler mode hedged dispatch defends against)
``serve.compile``    InferenceEngine._compile (``error`` fails the rung)
``checkpoint.save``  CheckpointManager.save (``corrupt`` garbles the
                     just-committed step on disk)
``fleet.worker``     the fleet worker's request handler
                     (fleet/transport.py WorkerServer), per dispatched
                     microbatch: ``error`` fails the call (the router
                     sees a transport failure), ``wedge`` stalls it
                     (the router's dispatch timeout must fire), and
                     ``kill`` is returned for the handler to enact
                     ``os._exit(137)`` — a deterministic,
                     occurrence-addressed stand-in for SIGKILLing the
                     worker mid-traffic (benchmarks/fleet_bench.py
                     also sends the real signal)
``store.write.*``    the graftvault durable-write protocol
                     (store/durable.py): ``pre_fsync`` / ``post_fsync``
                     / ``pre_rename`` / ``post_rename`` bracket the
                     file fsync and the atomic rename of every store
                     write; ``kill`` is enacted there as
                     ``os._exit(137)`` — tests/test_durable.py's crash
                     matrix arms one per (store × site) over a real
                     writer subprocess and asserts the reopened store
                     is bit-identical old-or-new state
===================  =====================================================

Faults address occurrences deterministically: ``nth=(3,)`` fires on the
3rd call at that site, ``entry_id=7`` fires whenever entry 7 is in the
dispatched microbatch (a *persistently* poisoned request — the shape
quarantine must isolate), ``p=0.3`` fires pseudo-randomly from the
plan's seeded RNG (same seed + same call sequence = same fire pattern,
pinned by tests/test_faults.py).

Arming a plan:

- in-process: ``faults.install(plan)`` (tests), uninstall with
  ``install(None)``;
- cross-process: export ``PERTGNN_FAULT_PLAN=<plan.to_json()>`` before
  spawning (how benchmarks/chaos_bench.py arms a real serve_main child).

With no plan armed a hook site is one module-global read — production
overhead is nil. This module imports nothing heavy (no jax, no numpy):
importing it from the serve hot path is free.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import threading
import time

log = logging.getLogger(__name__)

ENV_VAR = "PERTGNN_FAULT_PLAN"

KINDS = ("error", "wedge", "nan", "corrupt", "kill", "delay")


class InjectedFault(RuntimeError):
    """The exception an armed ``error`` fault raises at its hook site."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault: where, what, and which occurrences."""

    site: str
    kind: str  # error | wedge | nan | corrupt
    # 1-based occurrence numbers of `site` calls this spec fires on;
    # empty = every occurrence that passes the other filters.
    nth: tuple[int, ...] = ()
    # Only fire when this entry id is in the dispatched microbatch
    # (dispatch-site faults; None = any batch).
    entry_id: int | None = None
    # Stall duration for kind="wedge" (simulated device-transport hang).
    wedge_s: float = 0.0
    # Straggler duration for kind="delay": the call SLOWS by this much
    # but still succeeds — the slow-without-failing mode hedged dispatch
    # defends against (a wedge is meant to TRIP the watchdog; a delay
    # must stay below it and return a correct answer late).
    delay_s: float = 0.0
    # Fire probability per matching occurrence, drawn from the plan's
    # seeded RNG. 1.0 = always.
    p: float = 1.0
    message: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(want one of {KINDS})")
        object.__setattr__(self, "nth", tuple(int(n) for n in self.nth))


class FaultPlan:
    """A deterministic schedule of injected faults.

    Thread-safe: the serve path fires hooks from the queue worker and
    the dispatch watchdog thread; one lock serializes the occurrence
    counters and the seeded RNG so the fire pattern is a pure function
    of (specs, seed, call sequence)."""

    def __init__(self, specs: list[FaultSpec] | tuple[FaultSpec, ...] = (),
                 seed: int = 0):
        self.specs = tuple(specs)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._calls: dict[str, int] = {}
        # append-only record of (site, occurrence, kind) actually fired —
        # what the determinism tests compare
        self.fired: list[tuple[str, int, str]] = []

    # -- the hook --------------------------------------------------------

    def fire(self, site: str, *, entry_ids=None, sleep=time.sleep
             ) -> str | None:
        """Consume one occurrence of `site`; enact the matching fault.

        ``error`` raises InjectedFault here; ``wedge`` sleeps wedge_s
        here (the call site is mid-dispatch, so the sleep IS the stall);
        ``nan`` / ``corrupt`` / ``kill`` are returned as strings for the
        call site to enact (it owns the output buffer / the checkpoint
        files / the process — ``kill`` means ``os._exit(137)``, the
        fleet worker-death drill).
        Returns None when nothing fires. At most one spec fires per
        occurrence (first match in plan order)."""
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            spec = self._match_locked(site, n, entry_ids)
            if spec is None:
                return None
            self.fired.append((site, n, spec.kind))
        log.warning("fault injection: %s #%d -> %s%s", site, n, spec.kind,
                    f" ({spec.message})" if spec.message else "")
        if spec.kind == "error":
            raise InjectedFault(
                spec.message or f"injected {site} error (occurrence {n})")
        if spec.kind == "wedge":
            sleep(spec.wedge_s)
        elif spec.kind == "delay":
            # straggler: stall here (mid-call, same place a wedge
            # stalls) but let the call proceed to a CORRECT answer —
            # the site needs no special handling, late == injected
            sleep(spec.delay_s)
        return spec.kind

    def _match_locked(self, site, n, entry_ids) -> FaultSpec | None:
        for spec in self.specs:
            if spec.site != site:
                continue
            if spec.nth and n not in spec.nth:
                continue
            if spec.entry_id is not None:
                if entry_ids is None or not any(
                        int(e) == spec.entry_id for e in entry_ids):
                    continue
            if spec.p < 1.0 and self._rng.random() >= spec.p:
                continue
            return spec
        return None

    # -- (de)serialization: config/env injection -------------------------

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "specs": [dataclasses.asdict(s) for s in self.specs],
        })

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        raw = json.loads(text)
        specs = [FaultSpec(**{**s, "nth": tuple(s.get("nth", ()))})
                 for s in raw.get("specs", ())]
        return cls(specs, seed=raw.get("seed", 0))

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        """The plan exported in $PERTGNN_FAULT_PLAN, or None. A malformed
        value raises — a chaos run with an unparseable plan must fail
        loudly, not silently measure the happy path."""
        text = os.environ.get(ENV_VAR, "")
        return cls.from_json(text) if text else None


# -- process-wide arming ------------------------------------------------

_ACTIVE: FaultPlan | None = None
_ENV_CHECKED = False


def install(plan: FaultPlan | None) -> FaultPlan | None:
    """Arm `plan` process-wide (None disarms). Returns the previous
    plan so tests can restore it."""
    global _ACTIVE, _ENV_CHECKED
    prev = _ACTIVE
    _ACTIVE = plan
    _ENV_CHECKED = True  # explicit install wins over the env var
    return prev


def active() -> FaultPlan | None:
    """The armed plan, if any. First call also adopts a plan from
    $PERTGNN_FAULT_PLAN so spawned processes (chaos_bench children)
    inherit their faults without code changes."""
    global _ACTIVE, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        env_plan = FaultPlan.from_env()
        if env_plan is not None:
            _ACTIVE = env_plan
            log.warning("fault plan armed from $%s: %d spec(s)", ENV_VAR,
                        len(env_plan.specs))
    return _ACTIVE


# -- checkpoint corruption helper ---------------------------------------

def corrupt_checkpoint_step(directory: str, step: int) -> int:
    """Garble a committed orbax step in place (truncate every regular
    file to a byte of junk) so a later restore of that step fails — the
    on-disk half of the ``checkpoint.save``/``corrupt`` fault and the
    fixture behind CheckpointManager.maybe_restore's fallback test.
    Returns the number of files corrupted; raises if the step directory
    does not exist (corrupting nothing must not pass silently)."""
    step_dir = os.path.join(directory, str(step))
    if not os.path.isdir(step_dir):
        raise FileNotFoundError(f"no checkpoint step dir {step_dir!r}")
    count = 0
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(root, name)
            with open(path, "wb") as f:
                f.write(b"\x00")
            count += 1
    log.warning("fault injection: corrupted checkpoint step %d (%d files "
                "truncated) in %s", step, count, directory)
    return count
