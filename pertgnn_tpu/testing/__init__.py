"""Deterministic fault injection and interleaving control.

Production code consults :mod:`pertgnn_tpu.testing.faults` (what
happens) and :mod:`pertgnn_tpu.testing.schedules` (in which order) at a
handful of named hook sites — the serve dispatch, rung compiles,
checkpoint saves, the router's sender handoff. With no plan/scheduler
installed every hook is one module-global read — the subsystem costs
nothing unless a test or a chaos bench arms it.
"""

from pertgnn_tpu.testing import schedules
from pertgnn_tpu.testing.faults import (FaultPlan, FaultSpec, InjectedFault,
                                        active, install)

__all__ = ["FaultPlan", "FaultSpec", "InjectedFault", "active", "install",
           "schedules"]
