"""Deterministic fault injection for robustness validation.

Production code consults :mod:`pertgnn_tpu.testing.faults` at a handful
of named hook sites (the serve dispatch, rung compiles, checkpoint
saves). With no plan installed every hook is one module-global read —
the subsystem costs nothing unless a test or benchmarks/chaos_bench.py
arms it.
"""

from pertgnn_tpu.testing.faults import (FaultPlan, FaultSpec, InjectedFault,
                                        active, install)

__all__ = ["FaultPlan", "FaultSpec", "InjectedFault", "active", "install"]
