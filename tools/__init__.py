"""Repo tooling: the graftlint static-analysis framework lives in
tools/graftlint/; tools/check_excepts.py is its back-compat shim."""
