"""graftsync — static concurrency verification for the threaded fleet
(docs/LINTS.md): lock-order cycles and blocking-while-locked, custody
(future-lifecycle) drops, condition-variable protocol, thread
lifecycle, and timeout totality, on the graftlint driver conventions.
The dynamic twin is pertgnn_tpu/testing/schedules.py (the
deterministic interleaving harness)."""

from tools.graftsync.driver import run_passes, run_repo

__all__ = ["run_passes", "run_repo"]
