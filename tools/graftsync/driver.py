"""graftsync driver — the thread-protocol analyzer on graftlint's
conventions (docs/LINTS.md): same Context/Violation/baseline
machinery, its own pragma prefix (``# graftsync: allow-<pass>``), its
own baseline file, and the shared justification tables
(tools/graftsync/justify.py) whose liveness tier-1 pins.

Exit contract, identical to the siblings: 0 clean (or everything
baselined), 1 new violations, 2 usage/internal error.
"""

from __future__ import annotations

import os
import time

from tools.graftlint.driver import (Context, LintResult, Violation,
                                    load_baseline, split_findings,
                                    write_baseline)

__all__ = ["Context", "LintResult", "Violation", "load_baseline",
           "write_baseline", "run_passes", "run_repo",
           "DEFAULT_BASELINE", "PRAGMA_PREFIX"]

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")
PRAGMA_PREFIX = "graftsync: allow-"


def run_passes(repo: str, pass_names: list[str] | None = None,
               baseline_path: str | None = None) -> LintResult:
    """Run the named passes (default: all, registry order) over the
    repo, through graftlint's shared driver core (split_findings) with
    graftsync's pragma prefix and baseline. No --changed-only variant:
    the lock-acquisition graph and the custody analysis are whole-repo
    properties, and the full run is ~1 s."""
    from tools.graftsync.passes import get_passes

    t0 = time.perf_counter()
    ctx = Context(repo)
    ctx.graftsync_hits = {}  # rule -> {(path, key)} justification hits
    baseline = load_baseline(
        DEFAULT_BASELINE if baseline_path is None else baseline_path)
    modules = get_passes(pass_names)
    new, baselined = split_findings(ctx, modules, baseline,
                                    pragma_prefix=PRAGMA_PREFIX)
    result = LintResult(new=new, baselined=baselined,
                        elapsed_s=time.perf_counter() - t0,
                        passes=[m.RULE for m in modules])
    # stashed for the allowlist-liveness pin (tests/test_graftsync.py)
    result.justification_hits = ctx.graftsync_hits
    return result


def run_repo(repo: str) -> LintResult:
    """The full suite with the default baseline — what
    tests/test_graftsync.py and bench.py --gate call."""
    return run_passes(repo)
