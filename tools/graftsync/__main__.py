from tools.graftsync.cli import main

raise SystemExit(main())
