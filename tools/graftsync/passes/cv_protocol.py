"""graftsync pass — cv-protocol: every ``Condition.wait`` must follow
the condition-variable protocol. Bug-class provenance: the lost-wakeup
class — a ``notify`` that fires before the waiter reaches ``wait`` is
silently dropped, so a wait not guarded by a predicate-rechecking loop
hangs forever on exactly the interleaving the chaos benches rarely
draw (the planted-bug fixture in tests/test_schedules.py demonstrates
it deterministically).

Checks, per condition attribute (``self.X = threading.Condition(...)``)
or module/function-local condition:

- **wait-in-loop** — every ``<cond>.wait(...)`` call must be lexically
  inside a ``while``/``for`` loop of its function: wakeups are hints,
  not messages; the predicate must be re-checked (PEP-style
  ``while not pred: cv.wait()``).
- **wait-under-lock** — the wait must be lexically inside a ``with``
  of the condition's (aliased) lock; an unlocked wait raises
  RuntimeError at runtime, but only on the paths a test happens to
  drive.
- **reachable notify** — a condition somebody waits on must have at
  least one ``notify``/``notify_all`` in the same class (or module),
  itself under the condition's lock (a ``with``, or the manual
  ``if <lock>.acquire(blocking=False):`` idiom
  serve/queue.py ``begin_drain`` uses from its signal-handler
  context). A waited-on condition nobody notifies is a deadlock
  scheduled for later.

Exemptions: ``# graftsync: allow-cv-protocol`` on the line, or a
justified entry in tools/graftsync/justify.py CV_PROTOCOL.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain
from tools.graftsync import justify
from tools.graftsync.passes import _sync_util as su

RULE = "cv-protocol"


def _cond_of_call(m, u, call: ast.Call) -> tuple[str, str] | None:
    """(display name, canonical lock id) when `call` is a method call
    on a known condition object."""
    ch = attr_chain(call.func)
    if not ch or len(ch) < 2:
        return None
    recv = ch[:-1]
    kind = su.receiver_kind(m, u, recv)
    if kind is not None and kind[0] == "cond":
        return (".".join(recv), kind[1])
    return None


def _walk_with_context(u, m):
    """Yield (node, held lock ids, loop_depth) over the unit, with
    held/loop state reset inside nested defs (closures run later, on
    another thread, outside any loop of ours)."""

    def visit(node, held: tuple, loops: int):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not u.node:
            held, loops = (), 0
        if isinstance(node, ast.With):
            for item in node.items:
                lid = su.held_lock_id(m, u, item.context_expr)
                if lid is not None and lid not in held:
                    held = held + (lid,)
        if isinstance(node, (ast.While, ast.For)):
            loops += 1
        # the manual-acquire idiom: `if <lock>.acquire(...):` makes the
        # IF BODY a held region (begin_drain's signal-handler pattern)
        if isinstance(node, ast.If):
            for n in ast.walk(node.test):
                if isinstance(n, ast.Call):
                    fch = attr_chain(n.func) or []
                    if fch and fch[-1] == "acquire":
                        lid = None
                        if len(fch) >= 2:
                            kind = su.receiver_kind(m, u, fch[:-1])
                            if kind is not None and kind[0] in ("lock",
                                                                "cond"):
                                lid = kind[1]
                        if lid is not None:
                            # recurse the body with the lock held
                            for child in node.body:
                                yield from visit(child,
                                                 held + (lid,), loops)
                            for child in node.orelse:
                                yield from visit(child, held, loops)
                            return
        yield (node, held, loops)
        for child in ast.iter_child_nodes(node):
            yield from visit(child, held, loops)

    # the `*_locked` naming convention (graftlint lock-discipline
    # enforces the caller side): the suffix asserts every caller
    # already holds the class lock, so the method body runs locked
    held0: tuple = ()
    if getattr(u.node, "name", "").endswith("_locked") \
            and u.cls is not None:
        held0 = tuple(sorted({m.lock_id(u.cls.name, c)
                              for c in u.cls.canon.values()}))
    yield from visit(u.node, held0, 0)


def run(ctx) -> list[Violation]:
    out: list[Violation] = []

    def emit(path: str, line: int, message: str, key: str) -> None:
        if justify.lookup(ctx, RULE, path, key) is None:
            out.append(Violation(rule=RULE, path=path, line=line,
                                 message=message, key=key))

    for rel in ctx.files:
        m = su.model_for(ctx, rel)
        if m is None:
            continue
        # collect waits and notifies with context. The "reachable
        # notify under the lock" promise is the CONJUNCTION of two
        # checks: existence (notified_anywhere, below) and the
        # per-site notify-no-lock violation — an unlocked-only notify
        # satisfies existence but is flagged at its own site.
        waited: dict[tuple, tuple] = {}   # cond key -> (display, line)
        notified_anywhere: set[tuple] = set()
        for u in m.units:
            owner = u.cls.name if u.cls is not None else "<module>"
            for node, held, loops in _walk_with_context(u, m):
                if not isinstance(node, ast.Call):
                    continue
                attr = (node.func.attr
                        if isinstance(node.func, ast.Attribute) else "")
                if attr not in ("wait", "notify", "notify_all"):
                    continue
                cond = _cond_of_call(m, u, node)
                if cond is None:
                    continue
                display, lock_id = cond
                ckey = (owner if display.startswith("self.")
                        else "<module>", display.split(".")[-1])
                if attr == "wait":
                    waited.setdefault(ckey, (display, node.lineno, rel))
                    if loops == 0:
                        emit(rel, node.lineno,
                             (f"{u.qual}: `{display}.wait()` outside "
                              f"a predicate-rechecking loop — "
                              f"wakeups are hints; wrap it in "
                              f"`while not <predicate>:` (lost-"
                              f"wakeup/spurious-wakeup hazard)"),
                             f"wait-no-loop@{u.qual}")
                    if lock_id not in held:
                        emit(rel, node.lineno,
                             (f"{u.qual}: `{display}.wait()` without "
                              f"holding the condition's lock (`with "
                              f"{display}:` or its aliased lock) — "
                              f"RuntimeError at runtime"),
                             f"wait-no-lock@{u.qual}")
                else:
                    notified_anywhere.add(ckey)
                    if lock_id not in held:
                        emit(rel, node.lineno,
                             (f"{u.qual}: `{display}.{attr}()` "
                              f"without holding the condition's lock "
                              f"— RuntimeError at runtime (take "
                              f"`with {display}:` around the state "
                              f"change AND the notify)"),
                             f"notify-no-lock@{u.qual}")
        for ckey, (display, line, vrel) in waited.items():
            if ckey not in notified_anywhere:
                emit(vrel, line,
                     (f"`{display}` is waited on but NEVER notified "
                      f"in {ckey[0]} — every waiter relies on its "
                      f"timeout (or hangs); add the notify on the "
                      f"state change, or justify"),
                     f"no-notify@{ckey[0]}.{ckey[1]}")
    return out
