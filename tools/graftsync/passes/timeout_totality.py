"""graftsync pass — timeout-totality: every blocking wait on the
REQUEST PATH (serve/, fleet/) is bounded — a timeout argument — or
carries a justified allowlist entry explaining which protocol
guarantees the wakeup. Bug-class provenance: the chaos scenarios' hang
class. The ALWAYS-resolves contract (docs/RELIABILITY.md) is enforced
at the Future layer, but a raw ``queue.get()`` / ``Thread.join()`` /
``Condition.wait()`` below it waits on a PROTOCOL, not a promise — and
when the protocol's other half dies (wedged device, killed worker),
an unbounded wait turns a typed failure into an opaque 870 s tier-1
timeout.

Checked call shapes (receivers resolved same-file via the shared
model — dict ``.get`` is never confused with a queue's):

- ``<Condition>.wait()`` / ``<Event>.wait()`` with no timeout;
- ``<Thread>.join()`` with no timeout;
- ``<queue>.get()`` with no timeout (``get_nowait`` is fine);
  ``<bounded Queue>.put()`` with no timeout (``SimpleQueue.put``
  never blocks);
- ``<anything>.result()`` with NO argument — a Future wait.

An unbounded wait that is CORRECT states its wakeup guarantee in
tools/graftsync/justify.py TIMEOUT_TOTALITY (liveness-pinned: a dead
entry fails tier-1), or carries
``# graftsync: allow-timeout-totality`` on the line.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain
from tools.graftsync import justify
from tools.graftsync.passes import _sync_util as su

RULE = "timeout-totality"

SCOPE = ("pertgnn_tpu/serve/", "pertgnn_tpu/fleet/")


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    for rel in ctx.files_under(*SCOPE):
        m = su.model_for(ctx, rel)
        if m is None:
            continue
        for u in m.units:
            for node in ast.walk(u.node):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr not in ("wait", "join", "get", "put",
                                "result"):
                    continue
                recv = attr_chain(node.func.value)
                if recv is None:
                    continue
                display = ".".join(recv)
                kind = su.receiver_kind(m, u, recv)
                verdict = None
                if attr == "result":
                    if not su.has_timeout_arg(node):
                        verdict = (f"`{display}.result()` without a "
                                   f"timeout — an unbounded Future "
                                   f"wait")
                elif kind is None:
                    continue
                elif attr == "wait" and kind[0] in ("cond", "event"):
                    if not su.has_timeout_arg(node):
                        verdict = (f"`{display}.wait()` without a "
                                   f"timeout")
                elif attr == "join" and kind[0] == "thread":
                    if not su.has_timeout_arg(node):
                        verdict = (f"`{display}.join()` without a "
                                   f"timeout")
                elif attr == "get" and kind[0] == "queue":
                    # Queue.get(block, timeout): the FIRST positional
                    # is `block`, not a timeout — q.get(True) is the
                    # unbounded wait this pass exists to catch
                    if not su.has_timeout_arg(
                            node, first_arg_is_timeout=False):
                        verdict = (f"`{display}.get()` without a "
                                   f"timeout")
                elif attr == "put" and kind[0] == "queue" \
                        and kind[1] == "queue":
                    # bounded queues block on put; SimpleQueue never.
                    # put(item, block, timeout): bounded with a real
                    # (non-None) third positional / timeout= keyword,
                    # or the non-blocking block=False spellings
                    bounded = (su.queue_call_nonblocking(node, "put")
                               or (len(node.args) >= 3
                                   and not su.is_none_const(
                                       node.args[2]))
                               or any(kw.arg == "timeout"
                                      and not su.is_none_const(
                                          kw.value)
                                      for kw in node.keywords))
                    if not bounded:
                        verdict = (f"`{display}.put()` on a bounded "
                                   f"queue without a timeout")
                if verdict is None:
                    continue
                key = f"{u.qual}:{attr}@{display}"
                if justify.lookup(ctx, RULE, rel, key) is not None:
                    continue
                out.append(Violation(
                    rule=RULE, path=rel, line=node.lineno,
                    message=(f"{u.qual}: {verdict} on the request "
                             f"path — when the other half of this "
                             f"protocol dies, the wait becomes an "
                             f"opaque hang; bound it, or state the "
                             f"wakeup guarantee in "
                             f"tools/graftsync/justify.py"),
                    key=key))
    return out
