"""graftsync pass — thread-lifecycle: every started Thread is NAMED,
and every non-daemon thread has a reachable join. Bug-class
provenance: the tier-1 deadlock watchdog (tests/conftest.py) dumps all
thread stacks via faulthandler when the suite's ``timeout -k`` budget
fires — a dump full of ``Thread-7`` frames attributes nothing, and
graftscope's per-process traces face the same problem. An un-joined
non-daemon thread is worse: it silently blocks process exit (the
``_call_abandonable`` docstring documents the ThreadPoolExecutor
variant of exactly that hang).

Checks, on every ``threading.Thread(...)`` construction in scope:

- **named** — the call must carry ``name=`` (a variable is fine; the
  point is that SOMEONE chose a name).
- **daemon-or-joined** — ``daemon=True``, or the constructed thread's
  binding (a local, a ``self.<attr>``, or the elements of a
  list/list-comprehension it lands in) is ``.join()``ed somewhere in
  the same file (for thread LISTS: a ``for X in <list>:`` loop whose
  variable is joined). A thread that is neither daemonized nor joined
  outlives its owner invisibly.

Exemptions: ``# graftsync: allow-thread-lifecycle`` on the
construction line, or tools/graftsync/justify.py THREAD_LIFECYCLE.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain
from tools.graftsync import justify
from tools.graftsync.passes import _sync_util as su

RULE = "thread-lifecycle"


def _thread_calls(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            ch = attr_chain(node.func) or []
            if ch and ch[-1] == "Thread" and len(ch) <= 2:
                yield node


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _joined_names(tree) -> set[str]:
    """Every dotted name `.join()` is called on in the file."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == "join":
            recv = attr_chain(node.func.value)
            if recv:
                out.add(".".join(recv))
    return out


def _loop_vars_over(tree, container: str) -> set[str]:
    """Loop variables of ``for X in <container>:`` in the file."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.For):
            it = attr_chain(node.iter)
            if it and ".".join(it) == container \
                    and isinstance(node.target, ast.Name):
                out.add(node.target.id)
    return out


def _bindings(tree, call: ast.Call) -> list[str]:
    """Dotted names the constructed Thread may be reachable under:
    direct assignment targets, or — when the construction sits inside
    a list / list-comprehension that is itself assigned — the loop
    variables iterating that list."""
    out: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        direct = node.value is call
        via_list = False
        if isinstance(node.value, ast.ListComp) \
                and node.value.elt is call:
            via_list = True
        if isinstance(node.value, ast.List) \
                and call in node.value.elts:
            via_list = True
        # `self._x.append(Thread(...))`-style incremental list growth
        # is NOT resolved (declared limit — none in the tree today);
        # such a site would need `daemon=True` or a line pragma
        if not (direct or via_list):
            continue
        for t in node.targets:
            ch = attr_chain(t)
            if not ch:
                continue
            name = ".".join(ch)
            if direct:
                out.append(name)
            if via_list:
                out.extend(sorted(_loop_vars_over(tree, name)))
                out.append(name)
    return out


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    for rel in ctx.files:
        m = su.model_for(ctx, rel)
        if m is None:
            continue
        tree = ctx.tree(rel)
        joined = _joined_names(tree)
        for call in _thread_calls(tree):
            if _kw(call, "name") is None:
                key = f"unnamed@{call.lineno}"
                if justify.lookup(ctx, RULE, rel, key) is None:
                    out.append(Violation(
                        rule=RULE, path=rel, line=call.lineno,
                        message=("Thread constructed without "
                                 "`name=` — faulthandler dumps and "
                                 "graftscope attribution need every "
                                 "thread named (Thread-<n> "
                                 "attributes nothing)"),
                        key=key))
            daemon = _kw(call, "daemon")
            is_daemon = (isinstance(daemon, ast.Constant)
                         and daemon.value is True)
            if is_daemon:
                continue
            bindings = _bindings(tree, call)
            if any(b in joined for b in bindings):
                continue
            key = f"unjoined@{call.lineno}"
            if justify.lookup(ctx, RULE, rel, key) is None:
                out.append(Violation(
                    rule=RULE, path=rel, line=call.lineno,
                    message=("non-daemon Thread with no reachable "
                             "`.join()` in this file — it outlives "
                             "its owner and blocks process exit; "
                             "daemonize it or join it on the "
                             "close/drain path"),
                    key=key))
    return out
