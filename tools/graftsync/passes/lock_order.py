"""graftsync pass — lock-order: the whole-repo lock-acquisition graph
must be acyclic, and no blocking operation may run while a lock is
held. Bug-class provenance: PR 13's review found the router's
``_assign``→sender handoff could swallow a flight against a concurrent
``remove_worker`` — exactly the window where "what runs under which
lock, in what order" stopped being checkable by eye; every threaded
module since (autoscaler, hedger, loadgen) adds acquisition contexts.

Static model (same resolution discipline as graftlint's passes —
lexical, same-file, with the same-file call fixpoint trace-hazard
pioneered):

- **acquisition graph**: a node per lock identity (class attribute,
  module global, or function local; ``Condition(self._lock)`` aliases
  to the wrapped lock). An edge A→B exists when code acquires B while
  lexically holding A, directly (nested ``with``) or through a
  same-file callee (fixpoint over the module call graph: bare-name
  functions and ``self.<method>``). Any cycle is a potential deadlock
  and a violation naming the cycle.
- **blocking-while-locked**: inside a held-lock region, these calls
  are violations — ``time.sleep``; ``<queue>.get`` (both kinds) and
  ``<Queue>.put`` (bounded queues; ``SimpleQueue.put`` never blocks);
  ``<thread>.join``; ``<event>.wait``; a ``Condition.wait`` whose lock
  is NOT the one held (waiting on one mutex while holding another);
  ``Future.result``; ``Future.set_result`` / ``set_exception``
  (done-callbacks run inline and may re-enter the very lock held —
  the deadlock class fleet/router.py documents on ``_resolve_error``);
  unbounded ``.acquire()``; the HTTP transport
  (``post_predict`` / ``get_probe`` / ``urlopen`` / ``self._post`` /
  ``self._probe``); a blocking shared-memory ring op (``<ring>.call``
  — RingClient.call waits on the doorbell for up to the transport
  timeout; fleet/shmring.py); and bus emission (``*.bus.counter/gauge/...`` —
  the writer takes its own non-reentrant lock and does file I/O, which
  must never serialize an admission path; pertgnn_tpu/telemetry/'s own
  internals are exempt, the bus IS telemetry). A same-file callee that
  performs any of these is flagged at the locked call site.

Deliberate exceptions carry ``# graftsync: allow-lock-order`` on the
line, or a justified entry in tools/graftsync/justify.py LOCK_ORDER.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain
from tools.graftsync import justify
from tools.graftsync.passes import _sync_util as su

RULE = "lock-order"

_TRANSPORT_NAMES = {"post_predict", "get_probe", "urlopen"}
_TRANSPORT_SELF_ATTRS = {"_post", "_probe"}
_BUS_METHODS = {"counter", "gauge", "histogram", "span", "trace_span",
                "finish_trace", "start_trace"}
_RESOLVE_METHODS = {"set_result", "set_exception"}
# ring verbs that wait (try_push/try_pop are non-blocking by contract)
_RING_BLOCKING = {"call"}


def _blocking_desc(m, u, call: ast.Call, held: set,
                   in_telemetry: bool) -> str | None:
    """Why this call blocks (or re-enters), or None. `held` is the set
    of canonical lock ids lexically held at the call site."""
    ch = attr_chain(call.func) or []
    attr = (call.func.attr
            if isinstance(call.func, ast.Attribute) else "")
    if ch == ["time", "sleep"]:
        return "time.sleep"
    if ch and ch[-1] in _TRANSPORT_NAMES:
        return f"HTTP transport call `{'.'.join(ch)}`"
    if (len(ch) == 2 and ch[0] == "self"
            and ch[1] in _TRANSPORT_SELF_ATTRS):
        return f"injected transport call `self.{ch[1]}(...)`"
    if attr in _RESOLVE_METHODS:
        return (f"Future.{attr} — done-callbacks run inline and may "
                f"re-enter the lock held here")
    recv = ch[:-1] if ch else []
    kind = su.receiver_kind(m, u, recv) if recv else None
    if attr == "result" and recv:
        return f"Future.result on `{'.'.join(recv)}`"
    if attr == "join" and kind is not None and kind[0] == "thread":
        return f"Thread.join on `{'.'.join(recv)}`"
    if attr in _RING_BLOCKING and kind is not None \
            and kind[0] == "ring":
        return (f"blocking ring transport op `{'.'.join(ch)}` — "
                f"RingClient.call waits on the doorbell for the full "
                f"transport timeout")
    if attr == "wait" and kind is not None:
        if kind[0] == "event":
            return f"Event.wait on `{'.'.join(recv)}`"
        if kind[0] == "cond" and kind[1] not in held:
            return (f"Condition.wait on `{'.'.join(recv)}` while "
                    f"holding a DIFFERENT lock (wait only releases "
                    f"its own)")
    if attr in ("get", "put") and kind is not None \
            and kind[0] == "queue" \
            and not su.queue_call_nonblocking(call, attr):
        if attr == "get":
            return f"blocking queue get on `{'.'.join(recv)}`"
        if kind[1] == "queue":
            return f"bounded-queue put on `{'.'.join(recv)}`"
    if attr == "acquire" and kind is not None \
            and kind[0] in ("lock", "cond"):
        if not any(kw.arg == "blocking"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is False
                   for kw in call.keywords):
            return f"unbounded acquire on `{'.'.join(recv)}`"
    if (not in_telemetry and attr in _BUS_METHODS
            and "bus" in recv):
        return (f"bus emission `{'.'.join(ch)}` — the telemetry "
                f"writer takes its own lock and does file I/O")
    return None


class _UnitFacts:
    """Per-unit lexical facts feeding the two fixpoints."""

    __slots__ = ("acquires", "blocking", "calls_under",
                 "acquired_under", "blocking_sites")

    def __init__(self):
        self.acquires: set[str] = set()            # lock ids, anywhere
        self.blocking: list[str] = []              # descs, anywhere
        # (held lock id, call node, callee-qual list)
        self.calls_under: list = []
        # (held lock id, acquired lock id, line)
        self.acquired_under: list = []
        # (held lock id, desc, line) — direct blocking under a lock
        self.blocking_sites: list = []


def _unit_facts(m, u, in_telemetry: bool) -> _UnitFacts:
    f = _UnitFacts()

    def visit(node, held: tuple):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not u.node:
            held = ()  # a closure body executes later, unlocked
        if isinstance(node, ast.With):
            for item in node.items:
                lid = su.held_lock_id(m, u, item.context_expr)
                if lid is not None:
                    f.acquires.add(lid)
                    for h in held:
                        if h != lid:
                            f.acquired_under.append(
                                (h, lid, node.lineno))
                    if lid not in held:
                        held = held + (lid,)
        if isinstance(node, ast.Call):
            desc = _blocking_desc(m, u, node, set(held), in_telemetry)
            if desc is not None:
                f.blocking.append(desc)
                if held:
                    f.blocking_sites.append((held[-1], desc,
                                             node.lineno))
            elif held:
                callees = su.callee_units(m, u, node)
                if callees:
                    f.calls_under.append((held[-1], node,
                                          [c.qual for c in callees]))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(u.node, ())
    return f


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    edges: dict[str, set[str]] = {}      # lock id -> acquired-while-held
    edge_site: dict[tuple[str, str], tuple[str, int]] = {}
    per_file: list[tuple] = []           # (rel, m, u, facts)

    for rel in ctx.files:
        m = su.model_for(ctx, rel)
        if m is None:
            continue
        in_telemetry = rel.startswith("pertgnn_tpu/telemetry/")
        facts = {u.qual: (u, _unit_facts(m, u, in_telemetry))
                 for u in m.units}
        # the same-file call graph, computed once per unit
        call_edges: dict[str, set] = {}
        for q, (u, f) in facts.items():
            outs: set[str] = set()
            for node in ast.walk(u.node):
                if isinstance(node, ast.Call):
                    outs.update(c.qual for c in su.callee_units(m, u,
                                                                node))
            call_edges[q] = outs & set(facts)
        # fixpoints: transitive acquisitions and base blocking descs
        acq: dict[str, set] = {q: set(f.acquires)
                               for q, (u, f) in facts.items()}
        blk: dict[str, set] = {q: set(f.blocking)
                               for q, (u, f) in facts.items()}
        changed = True
        while changed:
            changed = False
            for q in facts:
                for cq in call_edges[q]:
                    if not acq[cq] <= acq[q]:
                        acq[q] |= acq[cq]
                        changed = True
                    if not blk[cq] <= blk[q]:
                        blk[q] |= blk[cq]
                        changed = True
        for q, (u, f) in facts.items():
            for h, lid, line in f.acquired_under:
                edges.setdefault(h, set()).add(lid)
                edge_site.setdefault((h, lid), (rel, line))
            for h, desc, line in f.blocking_sites:
                per_file.append((rel, u, h, desc, line))
            for h, call, callees in f.calls_under:
                for cq in callees:
                    cu, cf = facts[cq]
                    for lid in acq[cq]:
                        if lid != h:
                            edges.setdefault(h, set()).add(lid)
                            edge_site.setdefault((h, lid),
                                                 (rel, call.lineno))
                    if blk[cq]:
                        per_file.append((rel, u, h,
                                         f"call to {cq}, which "
                                         f"performs: "
                                         f"{sorted(blk[cq])[0]}",
                                         call.lineno))

    # blocking-while-locked violations
    for rel, u, held, desc, line in per_file:
        key = f"{u.qual}@{held.split('::')[-1]}"
        reason = justify.lookup(ctx, RULE, rel, key)
        if reason is not None:
            continue
        out.append(Violation(
            rule=RULE, path=rel, line=line,
            message=(f"{u.qual}: {desc} while holding "
                     f"{held.split('::')[-1]} — a blocking operation "
                     f"under a lock stalls every thread contending "
                     f"for it; move it outside the critical section "
                     f"or justify it (tools/graftsync/justify.py)"),
            key=key))

    # cycle detection over the acquisition graph
    seen_cycles: set[frozenset] = set()
    state: dict[str, int] = {}
    stack: list[str] = []

    def dfs(nid: str):
        state[nid] = 1
        stack.append(nid)
        for nxt in sorted(edges.get(nid, ())):
            if state.get(nxt, 0) == 0:
                dfs(nxt)
            elif state.get(nxt) == 1:
                cycle = stack[stack.index(nxt):] + [nxt]
                fs = frozenset(cycle)
                if fs not in seen_cycles:
                    seen_cycles.add(fs)
                    rel, line = edge_site.get((nid, nxt), ("", 0))
                    pretty = " -> ".join(c.split("::")[-1]
                                         for c in cycle)
                    out.append(Violation(
                        rule=RULE, path=rel or cycle[0].split("::")[0],
                        line=line,
                        message=(f"lock-order cycle (potential "
                                 f"deadlock): {pretty} — two threads "
                                 f"taking these locks in opposite "
                                 f"orders wedge forever; pick ONE "
                                 f"global order"),
                        key="cycle:" + "|".join(sorted(fs))))
        stack.pop()
        state[nid] = 2

    for nid in sorted(set(edges) | {x for v in edges.values()
                                    for x in v}):
        if state.get(nid, 0) == 0:
            dfs(nid)
    return out
