"""Shared concurrency model for the graftsync passes.

One parse of each in-scope file (graftlint's Context cache) is lifted
into a :class:`ModuleModel`: which class attributes and local names
hold locks / conditions / queues / events / threads, resolved lexically
the way graftlint's lock-discipline pass resolves its lock attributes.
Everything is STATIC and same-file — cross-module aliasing is a
declared limit (docs/LINTS.md), covered by the dynamic interleaving
harness (pertgnn_tpu/testing/schedules.py).

Identity conventions:

- a **lock id** is ``"<rel>::<Owner>.<attr>"`` (owner = class name, or
  ``<module>`` for module-level and function-local locks). A
  ``Condition(self._lock)`` aliases to the WRAPPED lock's id — waiting
  on the condition and holding the lock are the same mutex.
- a **unit** is one analysis scope: a top-level function or a method.
  Nested defs/lambdas are visited inside their unit with the held-lock
  state RESET (a closure executes later, on whatever thread calls it).
"""

from __future__ import annotations

import ast
import dataclasses

from tools.graftlint.passes._ast_util import attr_chain

_LOCK_TAILS = ("Lock", "RLock")
_QUEUE_TAILS = {"Queue": "queue", "LifoQueue": "queue",
                "PriorityQueue": "queue", "SimpleQueue": "simple"}
# graftwire shared-memory transport handles (fleet/shmring.py):
# RingClient.call blocks on the doorbell, so a ring is a first-class
# receiver kind for the blocking-while-locked analysis
_RING_TAILS = ("RingClient", "RingServer", "ShmRing")


def _ctor_tail(value: ast.AST) -> str | None:
    """The constructor name of ``x = <mod>.<Ctor>(...)``, else None."""
    if not isinstance(value, ast.Call):
        return None
    ch = attr_chain(value.func)
    return ch[-1] if ch else None


@dataclasses.dataclass
class ClassModel:
    name: str
    node: ast.ClassDef
    lock_attrs: set = dataclasses.field(default_factory=set)
    cond_attrs: set = dataclasses.field(default_factory=set)
    event_attrs: set = dataclasses.field(default_factory=set)
    queue_attrs: dict = dataclasses.field(default_factory=dict)
    thread_attrs: set = dataclasses.field(default_factory=set)
    ring_attrs: set = dataclasses.field(default_factory=set)
    # list-of-threads attrs (self._senders = [Thread(...) ...])
    thread_list_attrs: set = dataclasses.field(default_factory=set)
    # attr -> canonical lock attr (Condition(self._lock) -> "_lock")
    canon: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Unit:
    """One analysis scope: a module function or a method."""

    qual: str                      # "Class.method" or "func"
    node: ast.AST
    cls: ClassModel | None
    local_locks: set = dataclasses.field(default_factory=set)
    local_conds: set = dataclasses.field(default_factory=set)
    local_events: set = dataclasses.field(default_factory=set)
    local_queues: dict = dataclasses.field(default_factory=dict)
    local_threads: set = dataclasses.field(default_factory=set)
    local_thread_lists: set = dataclasses.field(default_factory=set)
    local_rings: set = dataclasses.field(default_factory=set)
    local_canon: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleModel:
    rel: str
    classes: dict = dataclasses.field(default_factory=dict)
    module_locks: set = dataclasses.field(default_factory=set)
    module_conds: set = dataclasses.field(default_factory=set)
    module_queues: dict = dataclasses.field(default_factory=dict)
    units: list = dataclasses.field(default_factory=list)
    # unions across classes, for cross-object attribute calls
    # (``w.sender_q.put`` resolves by attribute NAME, same file)
    attr_queues: dict = dataclasses.field(default_factory=dict)
    attr_threads: set = dataclasses.field(default_factory=set)
    attr_events: set = dataclasses.field(default_factory=set)
    attr_rings: set = dataclasses.field(default_factory=set)

    def lock_id(self, owner: str, attr: str) -> str:
        return f"{self.rel}::{owner}.{attr}"


def _classify_assign(node, add):
    """Dispatch one Assign/AnnAssign on its constructor tail via
    ``add(category, targets, value)``."""
    if isinstance(node, ast.Assign):
        targets, value = node.targets, node.value
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        targets, value = [node.target], node.value
    else:
        return
    tail = _ctor_tail(value)
    if tail is None:
        # list-of-threads: [Thread(...) for ...] or [Thread(...), ...]
        if isinstance(value, (ast.ListComp, ast.List)):
            elts = ([value.elt] if isinstance(value, ast.ListComp)
                    else value.elts)
            if any(isinstance(e, ast.Call)
                   and (attr_chain(e.func) or [""])[-1] == "Thread"
                   for e in elts):
                add("thread_list", targets, value)
        return
    if tail in _LOCK_TAILS:
        add("lock", targets, value)
    elif tail == "Condition":
        add("cond", targets, value)
    elif tail == "Event":
        add("event", targets, value)
    elif tail == "Thread":
        add("thread", targets, value)
    elif tail in _QUEUE_TAILS:
        add("queue:" + _QUEUE_TAILS[tail], targets, value)
    elif tail in _RING_TAILS:
        add("ring", targets, value)
    elif tail in ("create", "attach"):
        # ShmRing's alternate constructors: x = ShmRing.create(...) /
        # ShmRing.attach(...) — the tail is the classmethod name, so
        # peek one link up the chain
        ch = attr_chain(value.func) or []
        if len(ch) >= 2 and ch[-2] == "ShmRing":
            add("ring", targets, value)


def _build_class(node: ast.ClassDef) -> ClassModel:
    cm = ClassModel(name=node.name, node=node)

    def add(cat, targets, value):
        for t in targets:
            ch = attr_chain(t)
            if not (ch and len(ch) == 2 and ch[0] == "self"):
                continue
            attr = ch[1]
            if cat == "lock":
                cm.lock_attrs.add(attr)
                cm.canon.setdefault(attr, attr)
            elif cat == "cond":
                cm.lock_attrs.add(attr)
                cm.cond_attrs.add(attr)
                wrapped = None
                for arg in value.args:
                    ach = attr_chain(arg)
                    if ach and len(ach) == 2 and ach[0] == "self":
                        wrapped = ach[1]
                if wrapped is not None:
                    cm.lock_attrs.add(wrapped)
                    cm.canon.setdefault(wrapped, wrapped)
                    cm.canon[attr] = wrapped
                else:
                    cm.canon.setdefault(attr, attr)
            elif cat == "event":
                cm.event_attrs.add(attr)
            elif cat == "thread":
                cm.thread_attrs.add(attr)
            elif cat == "thread_list":
                cm.thread_list_attrs.add(attr)
            elif cat == "ring":
                cm.ring_attrs.add(attr)
            elif cat.startswith("queue:"):
                cm.queue_attrs[attr] = cat.split(":", 1)[1]

    for n in ast.walk(node):
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            _classify_assign(n, add)
    return cm


def _build_unit(qual: str, fn: ast.AST, cls: ClassModel | None) -> Unit:
    u = Unit(qual=qual, node=fn, cls=cls)

    def add(cat, targets, value):
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            name = t.id
            if cat == "lock":
                u.local_locks.add(name)
                u.local_canon.setdefault(name, name)
            elif cat == "cond":
                u.local_locks.add(name)
                u.local_conds.add(name)
                wrapped = None
                for arg in value.args:
                    if isinstance(arg, ast.Name):
                        wrapped = arg.id
                if wrapped is not None:
                    u.local_locks.add(wrapped)
                    u.local_canon.setdefault(wrapped, wrapped)
                    u.local_canon[name] = wrapped
                else:
                    u.local_canon.setdefault(name, name)
            elif cat == "event":
                u.local_events.add(name)
            elif cat == "thread":
                u.local_threads.add(name)
            elif cat == "thread_list":
                u.local_thread_lists.add(name)
            elif cat == "ring":
                u.local_rings.add(name)
            elif cat.startswith("queue:"):
                u.local_queues[name] = cat.split(":", 1)[1]

    for n in ast.walk(fn):
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            _classify_assign(n, add)
    return u


def model_for(ctx, rel: str) -> ModuleModel | None:
    """The (cached) ModuleModel for one in-scope file; None when the
    file does not parse (the driver reports that once)."""
    cache = getattr(ctx, "_graftsync_models", None)
    if cache is None:
        cache = {}
        ctx._graftsync_models = cache
    if rel in cache:
        return cache[rel]
    tree = ctx.tree(rel)
    if tree is None:
        cache[rel] = None
        return None
    m = ModuleModel(rel=rel)

    def add_mod(cat, targets, value):
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if cat == "lock":
                m.module_locks.add(t.id)
            elif cat == "cond":
                m.module_locks.add(t.id)
                m.module_conds.add(t.id)
            elif cat.startswith("queue:"):
                m.module_queues[t.id] = cat.split(":", 1)[1]

    for stmt in tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            _classify_assign(stmt, add_mod)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            m.units.append(_build_unit(stmt.name, stmt, None))
        elif isinstance(stmt, ast.ClassDef):
            cm = _build_class(stmt)
            m.classes[stmt.name] = cm
            for item in stmt.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    m.units.append(_build_unit(
                        f"{stmt.name}.{item.name}", item, cm))
    for cm in m.classes.values():
        m.attr_queues.update(cm.queue_attrs)
        m.attr_threads |= cm.thread_attrs | cm.thread_list_attrs
        m.attr_events |= cm.event_attrs
        m.attr_rings |= cm.ring_attrs
    cache[rel] = m
    return m


# -- receiver / lock resolution -------------------------------------------


def held_lock_id(m: ModuleModel, u: Unit, expr: ast.AST) -> str | None:
    """The canonical lock id a ``with <expr>`` acquires, else None."""
    ch = attr_chain(expr)
    if not ch:
        return None
    if len(ch) == 2 and ch[0] == "self" and u.cls is not None:
        if ch[1] in u.cls.lock_attrs:
            return m.lock_id(u.cls.name, u.cls.canon.get(ch[1], ch[1]))
    if len(ch) == 1:
        name = ch[0]
        if name in u.local_locks:
            return m.lock_id("<module>", u.local_canon.get(name, name))
        if name in m.module_locks:
            return m.lock_id("<module>", name)
    return None


def receiver_kind(m: ModuleModel, u: Unit,
                  recv: list[str]) -> tuple[str, str | None] | None:
    """Classify the receiver chain of an attribute call: returns
    (kind, detail) with kind in {"lock", "cond", "event", "queue",
    "thread", "ring"}; for "cond"/"lock" detail is the canonical lock
    id, for "queue" the queue kind ("queue" blocking put / "simple").
    None = unresolvable (unknown object)."""
    if not recv:
        return None
    if len(recv) == 2 and recv[0] == "self" and u.cls is not None:
        attr = recv[1]
        if attr in u.cls.cond_attrs:
            return ("cond", m.lock_id(u.cls.name,
                                      u.cls.canon.get(attr, attr)))
        if attr in u.cls.lock_attrs:
            return ("lock", m.lock_id(u.cls.name,
                                      u.cls.canon.get(attr, attr)))
        if attr in u.cls.event_attrs:
            return ("event", None)
        if attr in u.cls.queue_attrs:
            return ("queue", u.cls.queue_attrs[attr])
        if attr in (u.cls.thread_attrs | u.cls.thread_list_attrs):
            return ("thread", None)
        if attr in u.cls.ring_attrs:
            return ("ring", None)
    if len(recv) == 1:
        name = recv[0]
        if name in u.local_conds:
            return ("cond", m.lock_id("<module>",
                                      u.local_canon.get(name, name)))
        if name in u.local_locks:
            return ("lock", m.lock_id("<module>",
                                      u.local_canon.get(name, name)))
        if name in m.module_conds:
            return ("cond", m.lock_id("<module>", name))
        if name in m.module_locks:
            return ("lock", m.lock_id("<module>", name))
        if name in u.local_events:
            return ("event", None)
        if name in u.local_queues:
            return ("queue", u.local_queues[name])
        if name in m.module_queues:
            return ("queue", m.module_queues[name])
        if name in u.local_threads:
            return ("thread", None)
        if name in u.local_rings:
            return ("ring", None)
    # cross-object, same-file: resolve by ATTRIBUTE name (w.sender_q)
    tail = recv[-1]
    if len(recv) >= 2:
        if tail in m.attr_queues:
            return ("queue", m.attr_queues[tail])
        if tail in m.attr_events:
            return ("event", None)
        if tail in m.attr_threads:
            return ("thread", None)
        if tail in m.attr_rings:
            return ("ring", None)
    return None


def callee_units(m: ModuleModel, u: Unit,
                 call: ast.Call) -> list[Unit]:
    """Same-file callees of one call: a bare Name resolves to module
    functions of that name; ``self.X(...)`` to method X of the unit's
    own class."""
    out = []
    if isinstance(call.func, ast.Name):
        out = [x for x in m.units
               if x.cls is None and x.qual == call.func.id]
    else:
        ch = attr_chain(call.func)
        if (ch and len(ch) == 2 and ch[0] == "self"
                and u.cls is not None):
            out = [x for x in m.units
                   if x.cls is u.cls
                   and x.qual == f"{u.cls.name}.{ch[1]}"]
    return out


def is_none_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def has_timeout_arg(call: ast.Call,
                    first_arg_is_timeout: bool = True) -> bool:
    """Whether a blocking call is bounded. ``wait``/``join``/``result``
    take the timeout as their FIRST positional; ``Queue.get``/``put``
    take ``block`` first and the timeout SECOND (``q.get(True)`` is an
    unbounded blocking wait — pass ``first_arg_is_timeout=False`` so
    it is not mistaken for a bounded one; ``q.get(False)`` is
    non-blocking and counts as bounded). Keywords: ``timeout=`` or
    ``block=False``. An EXPLICIT literal ``None`` timeout — positional
    or keyword — is spelled-out unboundedness, not a bound."""
    if first_arg_is_timeout:
        if call.args and not is_none_const(call.args[0]):
            return True
    else:
        if len(call.args) >= 2 and not is_none_const(call.args[1]):
            return True      # (block, timeout)
        if (len(call.args) == 1
                and isinstance(call.args[0], ast.Constant)
                and call.args[0].value is False):
            return True      # block=False positionally: non-blocking
    for kw in call.keywords:
        if kw.arg == "timeout" and not is_none_const(kw.value):
            return True
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def queue_call_nonblocking(call: ast.Call, attr: str) -> bool:
    """True for the non-blocking spellings of ``Queue.get``/``put``:
    a literal ``False`` in the ``block`` position (first for get,
    second for put) or ``block=False`` — those never wait at all, so
    even the under-a-lock check must not flag them."""
    pos = 0 if attr == "get" else 1
    if (len(call.args) > pos
            and isinstance(call.args[pos], ast.Constant)
            and call.args[pos].value is False):
        return True
    return any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
               and kw.value.value is False for kw in call.keywords)
