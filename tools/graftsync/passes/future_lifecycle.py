"""graftsync pass — future-lifecycle: the static half of the
zero-lost-Futures invariant (docs/RELIABILITY.md: "a submitted Future
ALWAYS resolves"). Bug-class provenance: PR 13's ``_assign``→sender
handoff race — a path existed on which a dispatched flight was neither
handed to a sender nor released, so its futures never resolved and
``close()`` hung on the leg count. The benches assert zero lost
futures PER SCHEDULE; this pass checks every schedule at once, at the
price of a coarser property.

What it proves (and what it does not — docs/LINTS.md "Limits"):

- a **custody function** is one in serve/ or fleet/ whose parameter is
  a request-custody object — annotated ``_Request``/``_Flight``/
  ``Future`` (or a list of them), or named ``batch`` / ``flight`` /
  ``expired`` / ``recovered`` (underscore-prefixed params are
  deliberately-unused and exempt). On EVERY exit path (each ``return``
  and the fall-through), the function must have performed at least one
  **custody action**: resolving (``set_result``/``set_exception``),
  any call taking the object (or an element derived from iterating
  it) as an argument or receiver — the handoff —, mutating its
  attributes/subscripts, iterating it, or returning/referencing it in
  the return expression. An exit path on which the custody object is
  NEVER TOUCHED is a dropped-custody path: the futures inside it can
  no longer resolve. ``raise`` exits are exempt (the worker loops
  catch and fail the batch — the catch-all backstop), as is an early
  return directly guarded by emptiness (``if not batch: return``).
- a **locally created Future** (``fut = Future()``) must escape —
  be passed to a call, stored into shared state, or returned — on
  every non-``raise`` exit path. A raise before the future escaped is
  fine: no caller ever saw it.

This is intraprocedural and exactly-once is NOT proven (a path that
touches custody twice passes); the deterministic interleaving harness
(pertgnn_tpu/testing/schedules.py) is the dynamic twin that pins
exactly-once for the nastiest windows. Exemptions:
``# graftsync: allow-future-lifecycle`` on the ``def`` line, or a
justified entry in tools/graftsync/justify.py FUTURE_LIFECYCLE
(key ``<qualname>:<param>``).
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain
from tools.graftsync import justify
from tools.graftsync.passes import _sync_util as su

RULE = "future-lifecycle"

SCOPE = ("pertgnn_tpu/serve/", "pertgnn_tpu/fleet/")

_CUSTODY_NAMES = {"batch", "flight", "expired", "recovered"}
_CUSTODY_TYPES = ("_Request", "_Flight", "Future")
_NON_ACTIONS = {"len", "isinstance", "bool", "id", "type", "repr",
                "str", "print"}


def _annotation_is_custody(ann: ast.AST | None) -> bool:
    if ann is None:
        return False
    for n in ast.walk(ann):
        if isinstance(n, ast.Name) and n.id in _CUSTODY_TYPES:
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and any(t in n.value for t in _CUSTODY_TYPES):
            return True
        if isinstance(n, ast.Attribute) and n.attr in _CUSTODY_TYPES:
            return True
    return False


def _custody_params(fn: ast.AST) -> list[str]:
    out = []
    args = fn.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        if a.arg in ("self", "cls") or a.arg.startswith("_"):
            continue
        if a.arg in _CUSTODY_NAMES or _annotation_is_custody(
                a.annotation):
            out.append(a.arg)
    return out


class _Analysis:
    """Path-insensitive-per-branch custody walk: statements are
    interpreted over a SET of boolean "acted" states (one per
    still-live path); branches union, action is monotone."""

    def __init__(self, names: set[str]):
        self.tracked = set(names)   # custody name + derived elements
        self.drops: list[tuple[int, str]] = []  # (line, kind)

    # -- action detection -------------------------------------------------

    def _mentions(self, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self.tracked:
                return True
        return False

    def _is_action(self, node: ast.AST) -> bool:
        """Does this statement/expression touch the custody object in
        a consuming way?"""
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                fch = attr_chain(n.func) or []
                if fch and fch[0] in self.tracked:
                    return True  # custody.x.y(...) — receiver root
                if fch and fch[-1] in _NON_ACTIONS and len(fch) == 1:
                    continue
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    if self._mentions(a):
                        return True
            elif isinstance(n, (ast.Assign, ast.AugAssign,
                                ast.AnnAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    base = t
                    while isinstance(base, (ast.Attribute,
                                            ast.Subscript)):
                        base = base.value
                    if isinstance(base, ast.Name) \
                            and base.id in self.tracked \
                            and base is not t:
                        return True  # custody.attr = / custody[i] =
                # custody.attr read into a name DERIVES the name
                if isinstance(n, ast.Assign) and n.value is not None \
                        and self._mentions(n.value):
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.tracked.add(t.id)
                    return True
            elif isinstance(n, (ast.For, ast.comprehension)):
                it = n.iter
                if self._mentions(it):
                    for sub in ast.walk(n.target):
                        if isinstance(sub, ast.Name):
                            self.tracked.add(sub.id)
                    return True
        return False

    # -- the walk ---------------------------------------------------------

    def _guarded_empty_return(self, stmt: ast.If) -> bool:
        """``if not custody: return`` / ``if custody is None: return``
        — an exit with provably-empty custody."""
        test = stmt.test
        names_in_test = self._mentions(test)
        if not names_in_test:
            return False
        ok_shape = False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op,
                                                        ast.Not):
            ok_shape = True
        if isinstance(test, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.Eq))
                for op in test.ops):
            ok_shape = True
        if not ok_shape:
            return False
        return all(isinstance(s, (ast.Return, ast.Pass, ast.Continue))
                   or (isinstance(s, ast.Expr)
                       and isinstance(s.value, ast.Constant))
                   for s in stmt.body)

    def block(self, stmts: list, states: set[bool],
              raises_exempt: bool) -> set[bool]:
        """Interpret a statement list; returns fall-through states
        (empty set = no fall-through). Exits are checked inline."""
        for stmt in stmts:
            if not states:
                return states
            if isinstance(stmt, ast.Return):
                acted_now = states
                if stmt.value is not None and self._mentions(stmt.value):
                    acted_now = {True}
                elif stmt.value is not None and self._is_action(
                        stmt.value):
                    acted_now = {True}
                if False in acted_now:
                    self.drops.append((stmt.lineno, "return"))
                return set()
            if isinstance(stmt, ast.Raise):
                if not raises_exempt and False in states:
                    self.drops.append((stmt.lineno, "raise"))
                return set()
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return set()
            if isinstance(stmt, ast.If):
                if self._guarded_empty_return(stmt):
                    states = self.block(stmt.orelse, set(states),
                                        raises_exempt)
                    continue
                test_acts = self._is_action(stmt.test)
                entry = {True} if test_acts else set(states)
                a = self.block(stmt.body, set(entry), raises_exempt)
                b = self.block(stmt.orelse, set(entry), raises_exempt)
                states = a | b
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                acts = self._is_action(stmt)
                entry = {True} if acts else set(states)
                body = self.block(stmt.body, set(entry), raises_exempt)
                # zero-trip path keeps the entry states
                states = entry | body
                states |= self.block(stmt.orelse, set(states),
                                     raises_exempt)
                continue
            if isinstance(stmt, ast.With):
                acts = any(self._is_action(i.context_expr)
                           for i in stmt.items)
                entry = {True} if acts else set(states)
                states = self.block(stmt.body, set(entry),
                                    raises_exempt)
                continue
            if isinstance(stmt, ast.Try):
                t = self.block(stmt.body, set(states), raises_exempt)
                h = set()
                for handler in stmt.handlers:
                    h |= self.block(handler.body, set(states),
                                    raises_exempt)
                merged = t | h
                merged |= self.block(stmt.orelse, set(t or states),
                                     raises_exempt)
                if stmt.finalbody:
                    merged = self.block(stmt.finalbody,
                                        set(merged or states),
                                        raises_exempt)
                states = merged or states
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scope: analyzed separately if at all
            # plain statement: does it act?
            if self._is_action(stmt):
                states = {True}
        return states


def _check_custody(fn, param: str) -> list[tuple[int, str]]:
    a = _Analysis({param})
    final = a.block(fn.body, {False}, raises_exempt=True)
    if False in final:
        a.drops.append((getattr(fn, "lineno", 0), "fall-through"))
    return a.drops


def _check_created_future(fn, name: str,
                          create_line: int) -> list[tuple[int, str]]:
    """A ``name = Future()`` local must escape on every non-raise exit
    path REACHED AFTER the creation. Approximation: analyze the whole
    body with the future tracked; creation itself is not an action."""
    a = _Analysis({name})
    final = a.block(fn.body, {False}, raises_exempt=True)
    drops = [(ln, kind) for ln, kind in a.drops if ln > create_line]
    if False in final:
        drops.append((create_line, "fall-through"))
    return drops


def _pragma_on_def(ctx, rel: str, fn) -> bool:
    try:
        line = ctx.lines(rel)[fn.lineno - 1]
    except (OSError, IndexError):
        return False
    return "graftsync: allow-future-lifecycle" in line


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    for rel in ctx.files_under(*SCOPE):
        m = su.model_for(ctx, rel)
        if m is None:
            continue
        for u in m.units:
            fn = u.node
            if fn.name == "__init__":
                continue
            if _pragma_on_def(ctx, rel, fn):
                continue
            for param in _custody_params(fn):
                key = f"{u.qual}:{param}"
                if justify.lookup(ctx, RULE, rel, key) is not None:
                    continue
                for line, kind in _check_custody(fn, param):
                    if kind == "raise":
                        continue
                    out.append(Violation(
                        rule=RULE, path=rel, line=line,
                        message=(f"{u.qual}: exit path ({kind}) on "
                                 f"which custody parameter "
                                 f"`{param}` is never touched — its "
                                 f"futures can no longer resolve "
                                 f"(dropped custody); resolve, hand "
                                 f"off, or justify in "
                                 f"tools/graftsync/justify.py"),
                        key=key))
                    break  # one finding per (function, param)
            # locally created futures must escape
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and (attr_chain(node.value.func) or [""])[-1]
                        == "Future"):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        key = f"{u.qual}:{t.id}"
                        if justify.lookup(ctx, RULE, rel,
                                          key) is not None:
                            continue
                        drops = _check_created_future(fn, t.id,
                                                      node.lineno)
                        drops = [d for d in drops if d[1] != "raise"]
                        if drops:
                            line, kind = drops[0]
                            out.append(Violation(
                                rule=RULE, path=rel, line=line,
                                message=(
                                    f"{u.qual}: Future created at "
                                    f"line {node.lineno} "
                                    f"(`{t.id}`) can reach an exit "
                                    f"({kind}) without escaping — "
                                    f"a dropped future never "
                                    f"resolves"),
                                key=key))
    return out
