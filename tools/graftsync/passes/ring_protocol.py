"""graftsync pass — ring-protocol: the SPSC shared-memory ring's
publication discipline is a proof obligation, not a comment.

The graftwire ring (pertgnn_tpu/fleet/shmring.py) synchronizes producer
and consumer with nothing but a per-slot sequence stamp: the producer
writes the payload FIRST and publishes the stamp LAST; the consumer
reads the stamp, copies the payload, then RE-reads the stamp — a
mismatch means the copy raced an overwrite (a torn frame) and must be
discarded. Both halves are ordinary lexical code, so one refactor that
hoists the stamp write above the payload write (or drops the re-read)
silently turns every wrap-around into corrupt frames. This pass pins
the ordering statically, the same way lock-order pins the acquisition
graph.

Model: inside any one function, calls to the four protocol helpers —
``_payload_write``/``_seq_write`` (producer) and ``_seq_read``/
``_payload_read`` (consumer) — are collected in source order
(receiver-agnostic: ``self._seq_write`` and ``ring._seq_write`` both
count; the names are the contract, shmring.py documents them as such).

- **publication-last** (producer): no ``_seq_write`` may precede a
  later ``_payload_write`` in the same function. The stamp is the
  commit; payload bytes written after it are visible to a concurrent
  consumer as a committed-but-torn frame.
- **read-validate-reread** (consumer): a function that calls
  ``_payload_read`` must call ``_seq_read`` both BEFORE its first
  payload read (validate: the slot is committed) and AFTER its last
  (re-validate: the copy did not race a producer lap).

Deliberate exceptions carry a justified entry in
tools/graftsync/justify.py RING_PROTOCOL (none exist today — the
protocol has no safe variant).
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftsync import justify
from tools.graftsync.passes import _sync_util as su

RULE = "ring-protocol"

_PRODUCER = ("_payload_write", "_seq_write")
_CONSUMER = ("_seq_read", "_payload_read")
_HELPERS = set(_PRODUCER) | set(_CONSUMER)


def _protocol_calls(fn: ast.AST) -> list[tuple[str, int]]:
    """(helper name, line) for every protocol-helper call inside one
    function, in source order, closures included — a nested def that
    touches the slot participates in the same frame's lifecycle."""
    hits = []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _HELPERS):
            hits.append((node.func.attr, node.lineno,
                         node.col_offset))
    hits.sort(key=lambda h: (h[1], h[2]))
    return [(name, line) for name, line, _ in hits]


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    for rel in ctx.files:
        m = su.model_for(ctx, rel)
        if m is None:
            continue
        for u in m.units:
            calls = _protocol_calls(u.node)
            if not calls:
                continue
            # publication-last: a _seq_write with a _payload_write
            # after it commits a frame whose payload is still mutating
            pw_lines = [ln for nm, ln in calls if nm == "_payload_write"]
            if pw_lines:
                last_pw = pw_lines[-1]
                for nm, ln in calls:
                    if nm == "_seq_write" and ln < last_pw:
                        key = f"{u.qual}:publication-order"
                        if justify.lookup(ctx, RULE, rel, key) is None:
                            out.append(Violation(
                                rule=RULE, path=rel, line=ln,
                                message=(
                                    f"{u.qual}: _seq_write at line "
                                    f"{ln} precedes a _payload_write "
                                    f"at line {last_pw} — the sequence "
                                    f"stamp is the COMMIT; publishing "
                                    f"before the payload is complete "
                                    f"hands the consumer a torn "
                                    f"frame"),
                                key=key))
                        break
            # read-validate-reread: payload copies must be bracketed
            # by stamp reads, or a producer lap goes undetected
            pr_lines = [ln for nm, ln in calls if nm == "_payload_read"]
            if pr_lines:
                sr_lines = [ln for nm, ln in calls if nm == "_seq_read"]
                if not sr_lines or sr_lines[0] > pr_lines[0]:
                    key = f"{u.qual}:read-validate"
                    if justify.lookup(ctx, RULE, rel, key) is None:
                        out.append(Violation(
                            rule=RULE, path=rel, line=pr_lines[0],
                            message=(
                                f"{u.qual}: _payload_read without a "
                                f"preceding _seq_read — copying a slot "
                                f"before checking its stamp reads "
                                f"uncommitted bytes"),
                            key=key))
                if not sr_lines or sr_lines[-1] < pr_lines[-1]:
                    key = f"{u.qual}:read-revalidate"
                    if justify.lookup(ctx, RULE, rel, key) is None:
                        out.append(Violation(
                            rule=RULE, path=rel, line=pr_lines[-1],
                            message=(
                                f"{u.qual}: no _seq_read AFTER the "
                                f"last _payload_read — without the "
                                f"re-read, a producer lap during the "
                                f"copy (torn frame) is undetectable"),
                            key=key))
    return out
