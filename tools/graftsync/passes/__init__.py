"""Pass registry, graftlint's shape: order is output stability only —
the acquisition graph first (the deadlock proof), then custody, then
the protocol/lifecycle/timeout hygiene passes."""

from __future__ import annotations

from tools.graftsync.passes import (cv_protocol, future_lifecycle,
                                    lock_order, ring_protocol,
                                    thread_lifecycle, timeout_totality)

_ORDER = (lock_order, future_lifecycle, cv_protocol, thread_lifecycle,
          timeout_totality, ring_protocol)

# short aliases accepted on the CLI next to the canonical RULE names
ALIASES = {
    "locks": lock_order, "order": lock_order,
    "futures": future_lifecycle, "custody": future_lifecycle,
    "cv": cv_protocol,
    "threads": thread_lifecycle,
    "timeouts": timeout_totality, "timeout": timeout_totality,
    "ring": ring_protocol, "rings": ring_protocol,
}


def registry() -> dict[str, object]:
    return {m.RULE: m for m in _ORDER}


def get_passes(names: list[str] | None = None) -> list:
    if not names:
        return list(_ORDER)
    reg = registry()
    out = []
    for n in names:
        mod = reg.get(n) or ALIASES.get(n)
        if mod is None:
            raise KeyError(
                f"unknown pass {n!r} (choose from {sorted(reg)} "
                f"or aliases {sorted(ALIASES)})")
        if mod not in out:
            out.append(mod)
    return out
