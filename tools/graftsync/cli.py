"""graftsync CLI.

    python -m tools.graftsync [PASS ...] [options]

Options:
    --json             machine-readable result (one JSON object)
    --baseline PATH    baseline file (default tools/graftsync/
                       baseline.json when it exists)
    --no-baseline      ignore any baseline
    --write-baseline   accept today's findings into the baseline file
                       and exit 0 (reviewable: the file is in-tree)
    --root DIR         repo root (default: this file's repo)
    --list             list passes and exit

No --changed-only: the acquisition graph and the custody analysis are
whole-repo properties and the full run is ~1 s (docs/LINTS.md).

Exit codes: 0 clean (or all findings baselined), 1 new violations,
2 usage / internal error — the contract tests/test_graftsync.py
enforces in tier-1 and bench.py --gate piggybacks on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _repo_default() -> str:
    # tools/graftsync/cli.py -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    from tools.graftsync import driver
    from tools.graftsync.passes import get_passes, registry

    p = argparse.ArgumentParser(
        prog="graftsync",
        description="static concurrency verification for the threaded "
                    "fleet (docs/LINTS.md)")
    p.add_argument("passes", nargs="*",
                   help="pass names to run (default: all); "
                        f"canonical: {', '.join(registry())}")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--baseline", default=None)
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--root", default=None)
    p.add_argument("--list", action="store_true")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    if args.list:
        for name, mod in registry().items():
            doc = next(iter((mod.__doc__ or "").strip().splitlines()),
                       "")
            print(f"{name:20s} {doc}")
        return 0

    repo = os.path.abspath(args.root or _repo_default())
    if not os.path.isdir(repo):
        # a typo'd --root would otherwise discover zero files and
        # "pass" vacuously
        print(f"graftsync: root is not a directory: {repo}",
              file=sys.stderr)
        return 2
    try:
        get_passes(args.passes or None)
    except KeyError as e:
        print(f"graftsync: {e.args[0]}", file=sys.stderr)
        return 2

    baseline = ("" if args.no_baseline else args.baseline)
    if (baseline and not args.write_baseline
            and not os.path.exists(baseline)):
        # an EXPLICIT baseline path that does not exist is a usage
        # error, not an empty baseline (graftlint's CLI rationale)
        print(f"graftsync: baseline file not found: {baseline} "
              f"(--write-baseline creates one; --no-baseline ignores "
              f"baselines)", file=sys.stderr)
        return 2
    try:
        result = driver.run_passes(repo, args.passes or None,
                                   baseline_path=baseline)
    except FileNotFoundError as e:
        print(f"graftsync: {e}", file=sys.stderr)
        return 2
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        # a corrupt baseline is a USAGE error (exit 2), not "new
        # violations" (exit 1) — CI reads the exit-code contract
        print(f"graftsync: unreadable baseline file "
              f"({type(e).__name__}: {e})", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = args.baseline or driver.DEFAULT_BASELINE
        fresh = result.new + result.baselined
        ran = set(result.passes)
        if not args.passes:
            ran |= {"driver"}
        keep = [driver.Violation(rule=r, path=pth, line=0, message=k,
                                 key=k)
                for (r, pth, k) in driver.load_baseline(path)
                if r not in ran]
        driver.write_baseline(path, fresh + keep)
        print(f"graftsync: wrote {len(fresh) + len(keep)} baseline "
              f"entr(ies) to {path}"
              + (f" ({len(keep)} carried over from passes that did "
                 f"not run)" if keep else ""))
        return 0

    if args.as_json:
        print(json.dumps(result.as_dict()))
    else:
        for v in result.new:
            print(v)
        tail = (f"{len(result.new)} violation(s)"
                + (f", {len(result.baselined)} baselined"
                   if result.baselined else "")
                + f" [{', '.join(result.passes)};"
                  f" {result.elapsed_s:.2f}s]")
        print(tail, file=sys.stderr)
    return 1 if result.new else 0


if __name__ == "__main__":
    raise SystemExit(main())
