"""The shared concurrency-justification tables — ONE file where every
deliberate exemption from the thread-protocol analyzers lives with its
reason stated (ISSUE-14 satellite: single source of truth).

Two consumers:

- **graftsync passes** consult their table via :func:`lookup`, which
  also records the hit on the run's Context so tier-1 can pin LIVENESS:
  an entry that no longer suppresses a real finding fails the suite
  (tests/test_graftsync.py) — a dead exemption is a hole in the proof
  with a permission slip.
- **graftlint's lock-discipline pass** imports :data:`SINGLE_WRITER`
  (its historical ``ALLOWLIST`` — the name is re-exported there for
  back-compat), so the single-writer reasoning is not duplicated
  between the source-level and protocol-level analyzers.

Keys are stable identities (class.attr, ``qualname:what``), never line
numbers. Keep every reason CURRENT: an entry whose reason stops being
true is a data race / deadlock / lost future with a permission slip.
"""

from __future__ import annotations

# -- single-writer instance attributes (graftlint lock-discipline) --------
# (class name, attribute) -> why exactly ONE thread ever writes it.
SINGLE_WRITER: dict[tuple[str, str], str] = {
    # serve/queue.py MicrobatchQueue — worker-thread-only pipeline
    # state: written exclusively by the single `_run` worker (and by
    # close() only AFTER joining it); never read by another thread.
    ("MicrobatchQueue", "_inflight"):
        "overlapped-dispatch slot; worker-thread-only by design "
        "(documented on the attribute)",
    ("MicrobatchQueue", "_dispatcher"):
        "abandonable dispatcher handle; worker-thread-only, rebuilt "
        "by the worker after a watchdog trip",
    ("MicrobatchQueue", "_cooldown_until"):
        "fail-fast window bound; read and written by the worker only",
    ("MicrobatchQueue", "_drain_announced"):
        "drain-marker latch; worker-only, except close() which reads "
        "AND writes it only after joining the worker (single-threaded "
        "by then)",
    # fleet/autoscale.py AutoscaleController — control-thread-only
    # state: step() runs exclusively on the control thread (or a
    # test's driver thread, never both — start() is how the thread
    # comes to exist); the lock guards only the spares list /
    # totals that stats_dict() snapshots cross-thread.
    ("AutoscaleController", "_thread"):
        "written once in start() BEFORE the control thread exists; "
        "read only by close() after _stop is set",
    ("AutoscaleController", "_over_since"):
        "hysteresis bookkeeping; step() is control-thread-only by "
        "design (documented on the attribute)",
    ("AutoscaleController", "_under_since"):
        "hysteresis bookkeeping; step() is control-thread-only by "
        "design",
    # fleet/router.py FleetRouter — the prediction memo handle.
    ("FleetRouter", "memo"):
        "bound once in __init__ and never rebound; .insert()/.lookup() "
        "mutate the PredictionMemo's OWN state under the memo's OWN "
        "lock (fleet/memo.py — graftsync-verified: bus emission and "
        "wire codec work stay outside it). Calling it under the router "
        "lock would NEST router-lock -> memo-lock and put the memo's "
        "bus counters under a lock — the exact lock-order hazard "
        "graftsync forbids — so the unlocked call IS the protocol",
}

# -- timeout-totality (graftsync) -----------------------------------------
# (path, key) -> why this blocking call may wait without a timeout.
# key = "<qualname>:<verb>@<receiver>" — see passes/timeout_totality.py.
TIMEOUT_TOTALITY: dict[tuple[str, str], str] = {
    ("pertgnn_tpu/serve/queue.py",
     "MicrobatchQueue._run:wait@self._wake"):
        "idle worker awaiting work; close() sets _closed and notifies "
        "under the same lock, so the wakeup that ends the wait is "
        "guaranteed (liveness pinned by every close-path serve test)",
    ("pertgnn_tpu/serve/queue.py",
     "MicrobatchQueue.close:join@self._worker"):
        "close-drain completeness: the worker exits once the pending "
        "set is flushed; bounding this join would abandon admitted "
        "futures mid-drain — the ALWAYS-resolves contract outranks a "
        "bounded close",
    ("pertgnn_tpu/fleet/router.py",
     "FleetRouter._sender_loop:get@w.sender_q"):
        "sender awaiting work; close()/remove_worker() put the exit "
        "sentinel under the membership lock, so the queue always "
        "terminates the wait",
    ("pertgnn_tpu/fleet/router.py",
     "FleetRouter.close:join@self._dispatcher"):
        "close-drain completeness: the dispatcher exits once the "
        "pending set AND every in-flight leg settled; bounding it "
        "would abandon futures (request deadlines bound the drain "
        "in practice)",
    ("pertgnn_tpu/fleet/transport.py",
     "WorkerServer._predict:result@fut"):
        "a submitted Future ALWAYS resolves (serve/errors.py "
        "contract); the ROUTER bounds the round trip with its "
        "transport timeout, so a wedged worker is abandoned "
        "client-side, not waited on here",
    ("pertgnn_tpu/fleet/loadgen.py",
     "replay:result@fut"):
        "done-callback context: the future is already resolved when "
        "the callback runs (exception() was checked first) — "
        "result() cannot block",
}

# -- future-lifecycle (graftsync) -----------------------------------------
# (path, key) -> why an exit path without a custody action is safe.
# key = "<qualname>:<param>" — see passes/future_lifecycle.py.
FUTURE_LIFECYCLE: dict[tuple[str, str], str] = {
    ("pertgnn_tpu/serve/queue.py",
     "MicrobatchQueue._health_gate:batch"):
        "gate helper: on the True path the CALLER retains custody and "
        "dispatches; the False path fails the batch via _failfast "
        "before returning",
}

# -- lock-order (graftsync) -----------------------------------------------
# (path, key) -> why this blocking-while-locked site is deliberate.
LOCK_ORDER: dict[tuple[str, str], str] = {}

# -- cv-protocol (graftsync) ----------------------------------------------
CV_PROTOCOL: dict[tuple[str, str], str] = {}

# -- thread-lifecycle (graftsync) -----------------------------------------
THREAD_LIFECYCLE: dict[tuple[str, str], str] = {}

# -- ring-protocol (graftsync) --------------------------------------------
# Empty BY DESIGN: the SPSC publication discipline has no safe variant
# (see passes/ring_protocol.py) — an entry here would be a torn-frame
# data race with a permission slip.
RING_PROTOCOL: dict[tuple[str, str], str] = {}

TABLES: dict[str, dict[tuple[str, str], str]] = {
    "timeout-totality": TIMEOUT_TOTALITY,
    "future-lifecycle": FUTURE_LIFECYCLE,
    "lock-order": LOCK_ORDER,
    "cv-protocol": CV_PROTOCOL,
    "thread-lifecycle": THREAD_LIFECYCLE,
    "ring-protocol": RING_PROTOCOL,
}


def lookup(ctx, rule: str, path: str, key: str) -> str | None:
    """The justification for (rule, path, key), or None. A hit is
    recorded on the Context so the liveness test can require every
    entry to still be suppressing a real finding."""
    reason = TABLES.get(rule, {}).get((path, key))
    if reason is not None:
        hits = getattr(ctx, "graftsync_hits", None)
        if hits is not None:
            hits.setdefault(rule, set()).add((path, key))
    return reason
