"""graftlint: multi-pass static analysis for this repo's real bug classes.

Every review in PRs 3-7 caught a recurrence of the same few bug classes
by hand; each pass here mechanizes one of them (provenance table in
docs/LINTS.md):

- ``excepts``            silently-swallowed exceptions (PR 4's lint,
                         formerly tools/check_excepts.py — a shim there
                         preserves the old CLI and import surface)
- ``aot-key-coverage``   Config fields baked into compiled programs but
                         missing from the aot/keys.py cache-key
                         derivation (the PR-3 stale-replay bug class)
- ``trace-hazard``       host syncs / Python side effects inside
                         jitted / pjit'd / Pallas functions
- ``telemetry-drift``    counter/gauge/span names emitted by the code
                         vs docs/OBSERVABILITY.md's tables (and back)
- ``lock-discipline``    instance attributes of threaded classes in the
                         serve/fleet/prefetch paths mutated outside the
                         owning lock
- ``flag-config-drift``  config.py dataclass fields vs cli/common.py
                         flags, both directions

Run: ``python -m tools.graftlint [pass ...] [--json] [--baseline P]``.
The whole suite is a tier-1 gate (tests/test_graftlint.py) and
``bench.py --gate`` refuses captures from a tree where it fails.
"""

from __future__ import annotations

from tools.graftlint.driver import (Context, LintResult, Violation,
                                    run_passes, run_repo)

__all__ = ["Context", "LintResult", "Violation", "run_passes", "run_repo"]
