"""graftlint CLI.

    python -m tools.graftlint [PASS ...] [options]
    python -m tools.graftlint telemetry --emit-table

Options:
    --json             machine-readable result (one JSON object)
    --baseline PATH    baseline file (default tools/graftlint/
                       baseline.json when it exists)
    --no-baseline      ignore any baseline
    --write-baseline   accept today's findings into the baseline file
                       and exit 0 (reviewable: the file is in-tree)
    --root DIR         repo root (default: this file's repo)
    --changed-only     lint only files changed vs --changed-base
                       (default HEAD) plus untracked files — the
                       pre-commit fast path. FILE-scoped passes only:
                       repo-contract passes (telemetry-drift,
                       flag-config-drift, aot-key-coverage) are
                       skipped with a notice (naming one explicitly
                       together with the flag is a usage error),
                       because they compare the WHOLE tree against a
                       contract and a partial file set would fabricate
                       drift (docs/LINTS.md)
    --changed-base REF git ref to diff against (default HEAD)
    --list             list passes and exit

Exit codes: 0 clean (or all findings baselined), 1 new violations,
2 usage / internal error. The same contract tests/test_graftlint.py
enforces in tier-1 and bench.py --gate piggybacks on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _repo_default() -> str:
    # tools/graftlint/cli.py -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _changed_files(repo: str, base: str) -> list[str]:
    """Repo-relative paths changed vs `base` (tracked, staged or not)
    plus untracked files — what a pre-commit run should look at.
    Raises OSError when git cannot answer (not a checkout, bad ref)."""
    import subprocess

    out: list[str] = []
    for cmd in (["git", "diff", "--name-only", base, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=repo, capture_output=True,
                                  text=True, timeout=30)
        except subprocess.TimeoutExpired as e:
            raise OSError(f"git timed out: {e}") from e
        if proc.returncode != 0:
            raise OSError(proc.stderr.strip()
                          or f"`{' '.join(cmd)}` failed")
        out.extend(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return sorted(set(out))


def main(argv: list[str] | None = None) -> int:
    from tools.graftlint import driver
    from tools.graftlint.passes import get_passes, registry

    p = argparse.ArgumentParser(
        prog="graftlint",
        description="multi-pass static analysis for this repo's real "
                    "bug classes (docs/LINTS.md)")
    p.add_argument("passes", nargs="*",
                   help="pass names to run (default: all); "
                        f"canonical: {', '.join(registry())}")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--baseline", default=None)
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--root", default=None)
    p.add_argument("--changed-only", action="store_true")
    p.add_argument("--changed-base", default="HEAD", metavar="REF")
    p.add_argument("--list", action="store_true")
    p.add_argument("--emit-table", action="store_true",
                   help="telemetry pass only: regenerate "
                        "docs/OBSERVABILITY.md's metric tables from "
                        "source instead of checking them")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    if args.list:
        for name, mod in registry().items():
            doc = next(iter((mod.__doc__ or "").strip().splitlines()),
                       "")
            print(f"{name:20s} {doc}")
        return 0

    repo = os.path.abspath(args.root or _repo_default())
    if not os.path.isdir(repo):
        # a typo'd --root would otherwise discover zero files and
        # "pass" vacuously
        print(f"graftlint: root is not a directory: {repo}",
              file=sys.stderr)
        return 2
    try:
        get_passes(args.passes or None)
    except KeyError as e:
        print(f"graftlint: {e.args[0]}", file=sys.stderr)
        return 2

    if args.emit_table:
        if args.passes not in (["telemetry"], ["telemetry-drift"]):
            print("graftlint: --emit-table belongs to the telemetry "
                  "pass: `python -m tools.graftlint telemetry "
                  "--emit-table`", file=sys.stderr)
            return 2
        from tools.graftlint.passes import telemetry_drift

        ctx = driver.Context(repo)
        try:
            content, summary = telemetry_drift.emit_table(ctx)
        except OSError as e:
            # no docs/OBSERVABILITY.md to regenerate = usage error
            # (exit 2), not "lint findings" (exit 1)
            print(f"graftlint: cannot regenerate "
                  f"{telemetry_drift.DOC}: {e}", file=sys.stderr)
            return 2
        doc_path = ctx.abspath(telemetry_drift.DOC)
        with open(doc_path, "w", encoding="utf-8") as f:
            f.write(content)
        print(json.dumps({"emit_table": summary, "wrote": doc_path}))
        if summary["unplaced"]:
            print(f"graftlint: {len(summary['unplaced'])} new metric(s) "
                  f"had no table to land in — add a table section for "
                  f"them: {summary['unplaced']}", file=sys.stderr)
            return 1
        return 0

    baseline = ("" if args.no_baseline else args.baseline)
    if (baseline and not args.write_baseline
            and not os.path.exists(baseline)):
        # an EXPLICIT baseline path that does not exist is a usage
        # error, not an empty baseline: a typo'd path in CI would
        # silently resurface all accepted debt (and --write-baseline
        # would fork a second file while the real one goes stale)
        print(f"graftlint: baseline file not found: {baseline} "
              f"(--write-baseline creates one; --no-baseline ignores "
              f"baselines)", file=sys.stderr)
        return 2
    only_files = None
    skipped_repo_passes: list[str] = []
    if args.changed_only:
        if args.write_baseline:
            print("graftlint: --write-baseline over a --changed-only "
                  "subset would drop every other file's accepted "
                  "entries — run them separately", file=sys.stderr)
            return 2
        try:
            only_files = _changed_files(repo, args.changed_base)
        except OSError as e:
            print(f"graftlint: cannot resolve changed files ({e}) — "
                  f"is this a git checkout?", file=sys.stderr)
            return 2
        requested = get_passes(args.passes or None)
        repo_scoped = [m.RULE for m in requested
                       if getattr(m, "PASS_SCOPE", "file") == "repo"]
        if args.passes and repo_scoped:
            # an explicitly-named repo-contract pass cannot run on a
            # file subset without fabricating drift — refuse rather
            # than silently widen or silently skip what was asked for
            print(f"graftlint: {', '.join(repo_scoped)} compare(s) the "
                  f"WHOLE tree against a contract and cannot run under "
                  f"--changed-only — drop the flag for these",
                  file=sys.stderr)
            return 2
        skipped_repo_passes = repo_scoped
        args_passes = [m.RULE for m in requested
                       if getattr(m, "PASS_SCOPE", "file") == "file"]
        if skipped_repo_passes:
            print("graftlint: --changed-only skips repo-contract "
                  f"pass(es) {', '.join(skipped_repo_passes)} (a "
                  f"partial file set would fabricate drift) — run the "
                  f"full suite before pushing", file=sys.stderr)
        if not args_passes:
            print("graftlint: no file-scoped passes selected under "
                  "--changed-only", file=sys.stderr)
            return 0
    else:
        args_passes = args.passes or None
    try:
        result = driver.run_passes(repo, args_passes,
                                   baseline_path=baseline,
                                   only_files=only_files)
    except FileNotFoundError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        # a corrupt baseline is a USAGE error (exit 2), not "new
        # violations" (exit 1) — CI reads the exit-code contract
        print(f"graftlint: unreadable baseline file "
              f"({type(e).__name__}: {e})", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = args.baseline or driver.DEFAULT_BASELINE
        fresh = result.new + result.baselined
        # writing from a PASS SUBSET must not clobber other passes'
        # accepted entries: carry over every existing entry whose rule
        # did not run (entries for rules that DID run are replaced by
        # today's findings — that is the accept/retire semantics)
        # "driver" (parse-error) entries refresh only on a FULL run:
        # parse errors are discovered lazily per file a pass asks to
        # parse, so a pass subset may simply not have touched the file
        # an accepted entry covers — dropping it would resurface the
        # debt on the next full run (write_baseline dedupes the
        # overlap when the subset DID re-report an entry)
        ran = set(result.passes)
        if not args.passes:
            ran |= {"driver"}
        keep = [driver.Violation(rule=r, path=p, line=0, message=k,
                                 key=k)
                for (r, p, k) in driver.load_baseline(path)
                if r not in ran]
        driver.write_baseline(path, fresh + keep)
        print(f"graftlint: wrote {len(fresh) + len(keep)} baseline "
              f"entr(ies) to {path}"
              + (f" ({len(keep)} carried over from passes that did "
                 f"not run)" if keep else ""))
        return 0

    if args.as_json:
        print(json.dumps(result.as_dict()))
    else:
        for v in result.new:
            print(v)
        tail = (f"{len(result.new)} violation(s)"
                + (f", {len(result.baselined)} baselined"
                   if result.baselined else "")
                + f" [{', '.join(result.passes)};"
                  f" {result.elapsed_s:.2f}s]")
        print(tail, file=sys.stderr)
    return 1 if result.new else 0


if __name__ == "__main__":
    raise SystemExit(main())
