"""graftlint pass — durable-write: store modules must write through
the graftvault protocol (pertgnn_tpu/store/durable.py), never raw.
Bug-class provenance: ISSUE 19's audit found every store hand-rolling
its own atomicity — the arena/delta stores' double-``os.replace``
backup dance had a crash window where the live entry was GONE, the
AOT store's meta/blob pair could commit half, and nothing anywhere
fsync'd, so "atomic" rename could still surface empty files after a
power cut. The durable helper is the one place that sequence is
right (tmp → fsync → replace → dir fsync, checksummed manifest);
this pass keeps raw write primitives from creeping back in.

Static model (per file, lexical):

- in the store modules (SCOPE below), these calls are violations:
  ``os.replace``/``os.rename`` (a rename outside the protocol is an
  unfsync'd commit), ``np.save``/``numpy.save`` (bypasses the CRC
  manifest — use ``EntryWriter.put_array``), and ``open(...)`` with a
  writing mode (``w``/``a``/``x``, str-constant positional or
  ``mode=`` kwarg — use ``durable_write``/``write_json``/
  ``append_line``);
- reads (``open`` with no mode or an ``r``-only mode, ``np.load``)
  are untouched: the protocol makes every read see a complete old or
  new state without locks;
- the protocol's own primitives (durable.py), the scrub tool's
  quarantine rename, and the watchdog's crash-dump side channel are
  exactly the reviewed exceptions — each carries a line pragma
  ``# graftlint: allow-durable-write`` stating why.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain

RULE = "durable-write"
# per-file findings: sound on any file subset (--changed-only)
PASS_SCOPE = "file"

# every module that writes store/journal state — the durable protocol's
# home included (its raw primitives are the pragma'd exceptions)
SCOPE = ("pertgnn_tpu/store/",
         "pertgnn_tpu/aot/store.py",
         "pertgnn_tpu/batching/arena_store.py",
         "pertgnn_tpu/stream/store.py",
         "pertgnn_tpu/train/checkpoint.py",
         "pertgnn_tpu/telemetry/capture.py")

_RENAMES = {"replace", "rename"}
_WRITE_MODE_CHARS = set("wax+")


def _open_write_mode(call: ast.Call) -> str | None:
    """The mode string when this ``open`` call writes, else None.
    A non-constant mode counts as writing: the pass cannot prove it
    reads, and a dynamic mode in a store module deserves a look."""
    mode: ast.expr | None = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None  # bare open() reads
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value if set(mode.value) & _WRITE_MODE_CHARS else None
    return "<dynamic>"


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    for rel in ctx.files_under(*SCOPE):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            ch = attr_chain(node.func) or []
            if len(ch) == 2 and ch[0] == "os" and ch[1] in _RENAMES:
                out.append(Violation(
                    rule=RULE, path=rel, line=node.lineno,
                    message=(f"raw os.{ch[1]} in a store module — a "
                             f"rename outside store/durable.py is an "
                             f"unfsync'd commit with no checksum; use "
                             f"durable_write/write_json/EntryWriter, "
                             f"or pragma the reviewed exception"),
                    key=f"os.{ch[1]}"))
            elif (len(ch) == 2 and ch[0] in ("np", "numpy")
                    and ch[1] == "save"):
                out.append(Violation(
                    rule=RULE, path=rel, line=node.lineno,
                    message=("raw np.save in a store module bypasses "
                             "the CRC manifest — use "
                             "EntryWriter.put_array"),
                    key="np.save"))
            elif ch == ["open"]:
                mode = _open_write_mode(node)
                if mode is not None:
                    out.append(Violation(
                        rule=RULE, path=rel, line=node.lineno,
                        message=(f"raw open(..., {mode!r}) in a store "
                                 f"module — writes go through "
                                 f"durable_write/append_line (tmp → "
                                 f"fsync → replace → dir fsync), or "
                                 f"pragma the reviewed exception"),
                        key=f"open:{mode}"))
    return out
