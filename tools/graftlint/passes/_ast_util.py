"""Shared AST helpers for the graftlint passes.

Everything here is STATIC and same-file: attribute-chain flattening,
constant-string resolution (one level of local assignment), and the
traced-scope resolver that several passes share — which local functions
end up inside a jitted / pjit'd / Pallas program. Cross-module
resolution is deliberately out of scope (docs/LINTS.md "Limits"): each
pass states what it can see, and what it cannot is covered by the pass
that CAN see it (e.g. model code is keyed wholesale by ``cfg.model``
riding every cache key).
"""

from __future__ import annotations

import ast


def inner_attr_nodes(root: ast.AST) -> set[ast.AST]:
    """The ``.value`` children of every Attribute under `root` — walking
    with these skipped matches only MAXIMAL attribute chains
    (``cfg.train.tau`` without also matching its ``cfg.train`` child)."""
    out: set[ast.AST] = set()
    for n in ast.walk(root):
        if isinstance(n, ast.Attribute):
            out.add(n.value)
    return out


def attr_chain(node: ast.AST) -> list[str] | None:
    """``a.b.c`` -> ["a", "b", "c"]; None when the expression is not a
    pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def const_str_tuple(node: ast.AST) -> list[str] | None:
    """("a", "b", ...) / ["a", ...] of string constants, else None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            s = const_str(elt)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def resolve_str_values(node: ast.AST,
                       scope: ast.AST | None = None) -> set[str] | None:
    """The set of string constants an expression can evaluate to,
    resolved statically: constants, IfExp over constants, and — given
    ``scope`` (the enclosing function) — a Name assigned only constant
    strings anywhere in that scope. None = not statically resolvable
    (dynamic name)."""
    s = const_str(node)
    if s is not None:
        return {s}
    if isinstance(node, ast.IfExp):
        a = resolve_str_values(node.body, scope)
        b = resolve_str_values(node.orelse, scope)
        if a is not None and b is not None:
            return a | b
        return None
    if isinstance(node, ast.Name) and scope is not None:
        values: set[str] = set()
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign):
                targets = [t.id for t in n.targets
                           if isinstance(t, ast.Name)]
                if node.id in targets:
                    # `x = reject = None` sentinel inits contribute
                    # nothing; only a non-None unresolvable value makes
                    # the name dynamic
                    if (isinstance(n.value, ast.Constant)
                            and n.value.value is None):
                        continue
                    got = resolve_str_values(n.value)
                    if got is None:
                        return None
                    values |= got
            elif (isinstance(n, ast.AnnAssign) and n.value is not None
                  and isinstance(n.target, ast.Name)
                  and n.target.id == node.id):
                if (isinstance(n.value, ast.Constant)
                        and n.value.value is None):
                    continue
                got = resolve_str_values(n.value)
                if got is None:
                    return None
                values |= got
        return values or None
    return None


def functions(tree: ast.AST) -> list[ast.FunctionDef | ast.AsyncFunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def enclosing_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    """child function/lambda -> nearest enclosing function (for closure
    reasoning)."""
    out: dict[ast.AST, ast.AST] = {}

    def visit(node, current):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                if current is not None:
                    out[child] = current
                visit(child, child)
            else:
                visit(child, current)

    visit(tree, None)
    return out


_JIT_CHAINS = {
    ("jax", "jit"), ("jax", "pmap"), ("pjit",), ("jit",),
    ("pl", "pallas_call"), ("pallas", "pallas_call"),
    ("jax", "experimental", "pjit", "pjit"),
}
_VJP_CHAINS = {("jax", "custom_vjp"), ("custom_vjp",),
               ("jax", "custom_jvp"), ("custom_jvp",)}
_MODULE_BASES = {("nn", "Module"), ("linen", "Module"),
                 ("flax", "linen", "Module")}


def _is_partial(call: ast.Call) -> bool:
    c = attr_chain(call.func)
    return c is not None and c[-1] == "partial"


def traced_functions(tree: ast.AST) -> dict[ast.AST, set[str]]:
    """Function/lambda nodes of THIS module whose bodies are traced into
    compiled programs -> the subset of their parameter names known to be
    HOST-STATIC at trace time (partial-bound keywords, keyword-only
    params of partial(**kw)-wrapped kernels, custom_vjp nondiff args).
    Resolution, all static and same-file:

    - arguments of jax.jit / jax.pmap / pjit / pl.pallas_call calls
      (Name -> the local def; a call to a local factory -> the factory
      itself, whose body builds+returns the traced closure; a lambda ->
      the lambda node; ``self.X`` -> the method that assigns
      ``self.X = <local fn>``);
    - functions decorated @jax.custom_vjp/@custom_jvp (also via
      functools.partial), plus fwd/bwd registered through ``.defvjp`` —
      ``nondiff_argnums`` positions are static on all three;
    - ``__call__`` of flax ``nn.Module`` subclasses (model code always
      runs under jit in this repo);
    - fixpoint over same-module calls: a local function called by name
      (or ``self.<method>``) from a traced body is traced too.
    """
    by_name: dict[str, list[ast.AST]] = {}
    for fn in functions(tree):
        by_name.setdefault(fn.name, []).append(fn)
    self_assign: dict[str, list[tuple[str, ast.AST]]] = {}
    # self.X = <name>  ->  X: [(name, enclosing method)]
    for fn in functions(tree):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Name):
                for t in node.targets:
                    ch = attr_chain(t)
                    if ch and len(ch) == 2 and ch[0] == "self":
                        self_assign.setdefault(ch[1], []).append(
                            (node.value.id, fn))

    roots: dict[ast.AST, set[str]] = {}

    def _params(fn: ast.AST) -> list[str]:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
            a = fn.args
            return [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]
        return []

    def _kwonly(fn: ast.AST) -> set[str]:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return {x.arg for x in fn.args.kwonlyargs}
        return set()

    def mark(fn: ast.AST, static: set[str]) -> None:
        roots.setdefault(fn, set()).update(static)

    def mark_expr(arg: ast.AST, static: set[str] = frozenset()) -> None:
        if isinstance(arg, ast.Lambda):
            mark(arg, static)
            return
        if isinstance(arg, ast.Name):
            for fn in by_name.get(arg.id, []):
                mark(fn, static)
            return
        if isinstance(arg, ast.Call):
            if _is_partial(arg) and arg.args:
                # partial-bound keywords are host values -> static on
                # the wrapped fn; partial(**kw) binds by keyword too,
                # so the wrapped fn's keyword-only params are static
                bound = {kw.arg for kw in arg.keywords
                         if kw.arg is not None}
                if any(kw.arg is None for kw in arg.keywords):
                    inner = arg.args[0]
                    if isinstance(inner, ast.Name):
                        for fn in by_name.get(inner.id, []):
                            bound |= _kwonly(fn)
                mark_expr(arg.args[0], static | bound)
            elif isinstance(arg.func, ast.Name):
                # factory call: the factory's body (incl. its nested
                # defs and closure reads) produces the traced fn
                for fn in by_name.get(arg.func.id, []):
                    mark(fn, static)
            return
        ch = attr_chain(arg)
        if ch and len(ch) == 2 and ch[0] == "self":
            for name, method in self_assign.get(ch[1], []):
                mark(method, set())
                for fn in by_name.get(name, []):
                    mark(fn, static)

    def _nondiff_names(fn: ast.AST, dec: ast.AST) -> set[str]:
        """param names at custom_vjp/jvp nondiff_argnums positions."""
        if not (isinstance(dec, ast.Call) and _is_partial(dec)):
            return set()
        for kw in dec.keywords:
            if kw.arg == "nondiff_argnums":
                idx = []
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    for e in kw.value.elts:
                        if isinstance(e, ast.Constant):
                            idx.append(int(e.value))
                params = _params(fn)
                return {params[i] for i in idx if i < len(params)}
        return set()

    vjp_nondiff: dict[str, set[str]] = {}  # decorated fn name -> names
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            ch = attr_chain(node.func)
            if ch and tuple(ch) in _JIT_CHAINS and node.args:
                mark_expr(node.args[0])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.args[0] if (isinstance(dec, ast.Call)
                                    and _is_partial(dec)
                                    and dec.args) else dec
                dch = attr_chain(d)
                if dch and tuple(dch) in _VJP_CHAINS:
                    static = _nondiff_names(node, dec)
                    mark(node, static)
                    vjp_nondiff[node.name] = static
        elif isinstance(node, ast.ClassDef):
            bases = [tuple(attr_chain(b) or ()) for b in node.bases]
            if any(b in _MODULE_BASES for b in bases):
                for item in node.body:
                    if (isinstance(item, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and item.name == "__call__"):
                        mark(item, set())

    # f.defvjp(fwd, bwd): fwd/bwd share f's nondiff-leading convention —
    # the same PARAM NAMES are static where they reappear
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("defvjp", "defjvp")):
            base = attr_chain(node.func.value) or []
            inherited = vjp_nondiff.get(base[-1] if base else "", set())
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    for fn in by_name.get(arg.id, []):
                        mark(fn, inherited & set(_params(fn)))
                else:
                    mark_expr(arg)

    # fixpoint: same-module callees of traced bodies are traced
    changed = True
    while changed:
        changed = False
        for fn in list(roots):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee: list[ast.AST] = []
                if isinstance(node.func, ast.Name):
                    callee = by_name.get(node.func.id, [])
                else:
                    ch = attr_chain(node.func)
                    if ch and len(ch) == 2 and ch[0] == "self":
                        callee = by_name.get(ch[1], [])
                for c in callee:
                    if c not in roots:
                        roots[c] = set()
                        changed = True
    # (callers needing closure context — aot-key-coverage — build
    # enclosing_map themselves)
    return roots
