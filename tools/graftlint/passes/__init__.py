"""Pass registry. Order matters only for output stability: excepts
first (pass 0, the historical lint), then the five PR-8 passes."""

from __future__ import annotations

from tools.graftlint.passes import (aot_keys, durable_write, excepts,
                                    flag_config, lock_discipline,
                                    telemetry_drift, trace_hazard)

_ORDER = (excepts, aot_keys, trace_hazard, telemetry_drift,
          lock_discipline, flag_config, durable_write)

# short aliases accepted on the CLI next to the canonical RULE names
ALIASES = {
    "aot": aot_keys, "aot-keys": aot_keys,
    "trace": trace_hazard,
    "telemetry": telemetry_drift,
    "locks": lock_discipline, "lock": lock_discipline,
    "flags": flag_config, "flag": flag_config,
    "durable": durable_write, "vault": durable_write,
}


def registry() -> dict[str, object]:
    return {m.RULE: m for m in _ORDER}


def get_passes(names: list[str] | None = None) -> list:
    if not names:
        return list(_ORDER)
    reg = registry()
    out = []
    for n in names:
        mod = reg.get(n) or ALIASES.get(n)
        if mod is None:
            raise KeyError(
                f"unknown pass {n!r} (choose from {sorted(reg)} "
                f"or aliases {sorted(ALIASES)})")
        if mod not in out:
            out.append(mod)
    return out
