"""graftlint pass — aot-key-coverage: every Config field read inside
program-building code must be reachable from an aot/keys.cache_key
derivation. Bug-class provenance: the PR-3 review found THREE stale-
replay bugs of exactly this shape (the packer budget, the embedding
vocab sizes, and spurious ServeConfig invalidation — CHANGES.md "PR 3
review fixes"); a config field baked into a compiled program as a
constant but absent from the cache key replays yesterday's executable
with today's config, silently.

Static model:

- KEY COVERAGE: every ``aot.cache_key(...)`` / ``cache_key(...)`` call
  site's ``config=`` argument is analyzed (dict literals, one level of
  same-file helper-function indirection — the ``_train_eval_key_config``
  pattern). An attribute chain ``cfg.model`` covers the WHOLE model
  subtree; ``cfg.train.label_scale`` covers one field;
  ``getattr(cfg.train, k) for k in ("lr", ...)`` covers the listed
  fields; ``cfg.graph_type`` covers a top-level scalar. Coverage is the
  UNION over all key sites in the repo: per-program precision would
  need the fn_id -> program mapping, which is runtime information —
  the union still kills the bug class (a field NO key mentions cannot
  be baked into ANY program safely).
- PROGRAM READS: inside the traced scope of the program-building files
  (SCOPE below) — the jitted/pallas'd functions themselves PLUS their
  lexically enclosing functions, because closure captures
  (``label_scale = cfg.train.label_scale`` before the ``def step``) are
  baked into the program exactly like direct reads. A read is an
  attribute chain rooted at a Config value: a parameter annotated
  ``Config`` (or named ``cfg``/``config``), ``self._cfg``/``self.cfg``,
  or a local alias of either. Parameters annotated with a SUBTREE
  config class (``ModelConfig``) read with that subtree as implicit
  prefix — which is how model code is covered: ``cfg.model`` rides
  every key whole, so ModelConfig fields can never drift out.
- a read of a whole subtree (``cfg.serve`` passed to a ladder builder)
  counts as reading every field of it and must be wholly covered or
  explicitly exempted.

Exemptions: the SIGNATURE_VISIBLE allowlist below — fields whose effect
on the program is fully visible in the abstract calling signature or
the store slot name (shape knobs), which the key already hashes; each
entry states why. Plus the line pragma
``# graftlint: allow-aot-key-coverage`` and the baseline file.

Known blind spots (docs/LINTS.md "Limits"): host-side reads whose
VALUE is baked via an object built outside the traced scope (the optax
transform carries ``train.lr``) — those fields must ride the key by
review; the key-side list in _train_eval_key_config carries them today
and this pass verifies they stay covered if the read ever moves into
traced scope.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import (attr_chain,
                                              const_str_tuple,
                                              enclosing_map, functions,
                                              inner_attr_nodes,
                                              traced_functions)

RULE = "aot-key-coverage"
# repo-wide contract: needs the FULL file set (a subset would
# fabricate drift) — skipped under --changed-only
PASS_SCOPE = "repo"

SUBTREES = ("ingest", "data", "model", "train", "parallel", "serve",
            "fleet", "telemetry", "aot")
_SUBTREE_CLASSES = {
    "IngestConfig": "ingest", "DataConfig": "data",
    "ModelConfig": "model", "TrainConfig": "train",
    "ParallelConfig": "parallel", "ServeConfig": "serve",
    "FleetConfig": "fleet", "TelemetryConfig": "telemetry",
    "CompileCacheConfig": "aot",
}

# files whose code builds compiled programs (the ISSUE-8 scope)
SCOPE = ("pertgnn_tpu/aot/", "pertgnn_tpu/serve/engine.py",
         "pertgnn_tpu/train/loop.py", "pertgnn_tpu/train/predict.py",
         "pertgnn_tpu/models/", "pertgnn_tpu/parallel/")

# (file suffix, dotted pattern) -> reason. "sub.*" exempts a whole
# subtree in that file.
SIGNATURE_VISIBLE: dict[tuple[str, str], str] = {
    ("pertgnn_tpu/serve/engine.py", "serve.*"):
        "ladder knobs (bucket_growth/min_bucket_*/max_graphs_per_batch) "
        "only select WHICH rung shapes exist — the shapes ride the "
        "abstract signature and the store slot name, both hashed by the "
        "key; queue/transport knobs never reach the compiled program "
        "(serve/engine.py _rung_entry documents the same restraint). "
        "serve_dtype, the ONE baked field, is keyed explicitly and "
        "verified covered by tests/test_aot.py.",
}


def _covered_from_expr(node: ast.AST, roots: dict[str, tuple[str, ...]],
                       covered: set[str]) -> None:
    """Walk a key-config expression collecting covered dotted paths
    into `covered` ("model.*" for whole subtrees)."""
    getattr_bases: set[ast.AST] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            # getattr(cfg.<sub>, k) with k ranging over a const tuple;
            # the BASE chain must not count as whole-subtree coverage
            fch = attr_chain(n.func)
            if fch == ["getattr"] and len(n.args) >= 2:
                getattr_bases.add(n.args[0])
                base = attr_chain(n.args[0])
                if base and base[0] in roots:
                    prefix = roots[base[0]] + tuple(base[1:])
                    comp = _enclosing_comprehension_consts(node, n)
                    for field in comp:
                        covered.add(".".join(prefix + (field,)))
    inner = inner_attr_nodes(node)
    for n in ast.walk(node):
        if n in getattr_bases or n in inner:
            continue
        ch = attr_chain(n)
        if not ch or ch[0] not in roots:
            continue
        path = roots[ch[0]] + tuple(ch[1:])
        if not path:
            continue
        if len(path) == 1:
            if path[0] in SUBTREES:
                covered.add(f"{path[0]}.*")
            else:
                covered.add(path[0])  # top-level scalar (graph_type)
        else:
            covered.add(".".join(path[:2]))


def _enclosing_comprehension_consts(scope: ast.AST,
                                    call: ast.Call) -> list[str]:
    """For a getattr(...) inside a dict/list comprehension, the constant
    strings its loop variable ranges over."""
    for n in ast.walk(scope):
        if isinstance(n, (ast.DictComp, ast.ListComp, ast.SetComp,
                          ast.GeneratorExp)):
            if any(c is call for c in ast.walk(n)):
                for gen in n.generators:
                    consts = const_str_tuple(gen.iter)
                    if consts:
                        return consts
    return []


def _class_attr_prefixes(tree: ast.AST) -> dict[ast.AST,
                                                dict[str, tuple[str, ...]]]:
    """ClassDef -> {attr: prefix} for class-level annotated config
    attributes (the flax-module pattern ``cfg: ModelConfig`` — reads
    through ``self.cfg`` then carry the ``model.`` prefix)."""
    out: dict[ast.AST, dict[str, tuple[str, ...]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: dict[str, tuple[str, ...]] = {}
        for item in node.body:
            if (isinstance(item, ast.AnnAssign)
                    and isinstance(item.target, ast.Name)):
                ann = attr_chain(item.annotation) or []
                cls = ann[-1] if ann else None
                if cls == "Config":
                    attrs[item.target.id] = ()
                elif cls in _SUBTREE_CLASSES:
                    attrs[item.target.id] = (_SUBTREE_CLASSES[cls],)
        if attrs:
            out[node] = attrs
    return out


def _config_roots(fn: ast.AST,
                  self_attrs: dict[str, tuple[str, ...]] | None = None
                  ) -> dict[str, tuple[str, ...]]:
    """name -> dotted prefix for Config-rooted values visible in `fn`:
    full-Config params map to (), subtree-annotated params map to
    (subtree,), and simple local aliases of self._cfg / self.cfg map
    to the enclosing class's annotation when it has one, else ()."""
    self_attrs = self_attrs or {}
    roots: dict[str, tuple[str, ...]] = {}
    args = []
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
        args = (a.posonlyargs + a.args + a.kwonlyargs
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else []))
    for arg in args:
        ann = attr_chain(arg.annotation) if arg.annotation else None
        cls = ann[-1] if ann else None
        if cls == "Config":
            roots[arg.arg] = ()
        elif cls in _SUBTREE_CLASSES:
            roots[arg.arg] = (_SUBTREE_CLASSES[cls],)
        elif arg.arg in ("cfg", "config") and arg.annotation is None:
            roots[arg.arg] = ()
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            if not isinstance(t, ast.Name):
                continue
            vch = attr_chain(n.value)
            if vch in (["self", "_cfg"], ["self", "cfg"]):
                roots[t.id] = self_attrs.get(vch[1], ())
            elif vch and vch[0] in roots and len(vch) == 1:
                roots[t.id] = roots[vch[0]]
    return roots


def _self_cfg_reads(fn: ast.AST) -> list[tuple[int, tuple[str, ...]]]:
    """(line, dotted path) for reads through self._cfg / self.cfg."""
    out = []
    for n in ast.walk(fn):
        ch = attr_chain(n)
        if ch and len(ch) >= 3 and ch[0] == "self" and ch[1] in ("_cfg",
                                                                 "cfg"):
            out.append((n.lineno, tuple(ch[2:])))
    return out


def collect_coverage(ctx) -> set[str]:
    """Union of key-covered dotted paths over every cache_key call site
    in the repo (one level of same-file helper indirection)."""
    covered: set[str] = set()
    for rel in ctx.files:
        tree = ctx.tree(rel)
        if tree is None:
            continue
        by_name = {}
        for fn in functions(tree):
            by_name.setdefault(fn.name, fn)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            ch = attr_chain(node.func) or []
            if not ch or ch[-1] != "cache_key":
                continue
            cfg_arg = None
            for kw in node.keywords:
                if kw.arg == "config":
                    cfg_arg = kw.value
            if cfg_arg is None:
                continue
            # `config=X` where X is a local assigned earlier in the
            # enclosing function: resolve one assignment level
            if isinstance(cfg_arg, ast.Name):
                encl = _enclosing_fn(tree, node)
                if encl is not None:
                    for n2 in ast.walk(encl):
                        if (isinstance(n2, ast.Assign)
                                and any(isinstance(t, ast.Name)
                                        and t.id == cfg_arg.id
                                        for t in n2.targets)):
                            cfg_arg = n2.value
                            break
            exprs = [cfg_arg]
            # one level of helper indirection: config=_helper(...)
            if (isinstance(cfg_arg, ast.Call)
                    and isinstance(cfg_arg.func, ast.Name)
                    and cfg_arg.func.id in by_name):
                exprs.append(by_name[cfg_arg.func.id])
            for expr in exprs:
                roots = _config_roots(expr) if isinstance(
                    expr, (ast.FunctionDef,
                           ast.AsyncFunctionDef)) else _enclosing_roots(
                               tree, node)
                _covered_from_expr(expr, roots, covered)
                for _line, path in ([] if not isinstance(
                        expr, (ast.FunctionDef, ast.AsyncFunctionDef))
                        else _self_cfg_reads(expr)):
                    _add_path(path, covered)
    return covered


def _enclosing_fn(tree: ast.AST, node: ast.AST) -> ast.AST | None:
    best = None
    for fn in functions(tree):
        if any(n is node for n in ast.walk(fn)):
            best = fn  # later (nested) matches are narrower
    return best


def _enclosing_roots(tree: ast.AST,
                     node: ast.AST) -> dict[str, tuple[str, ...]]:
    fn = _enclosing_fn(tree, node)
    if fn is not None:
        return _config_roots(fn)
    return {"cfg": (), "config": ()}


def _add_path(path: tuple[str, ...], into: set[str]) -> None:
    if not path:
        return
    if len(path) == 1:
        into.add(f"{path[0]}.*" if path[0] in SUBTREES else path[0])
    else:
        into.add(".".join(path[:2]))


def _exempt(rel: str, dotted: str) -> str | None:
    for (suffix, pat), reason in SIGNATURE_VISIBLE.items():
        if not rel.endswith(suffix):
            continue
        if pat == dotted:
            return reason
        if pat.endswith(".*") and (dotted == pat[:-2] + ".*"
                                   or dotted.startswith(pat[:-2] + ".")):
            return reason
    return None


def run(ctx) -> list[Violation]:
    covered = collect_coverage(ctx)
    out: list[Violation] = []
    for rel in ctx.files_under(*SCOPE):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        traced = traced_functions(tree)
        if not traced:
            continue
        class_attrs = _class_attr_prefixes(tree)
        fn_attrs: dict[ast.AST, dict[str, tuple[str, ...]]] = {}
        for cls, attrs in class_attrs.items():
            for n in ast.walk(cls):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    fn_attrs[n] = attrs
        enc = enclosing_map(tree)
        scope_fns: set[ast.AST] = set()
        for fn in traced:
            scope_fns.add(fn)
            cur = fn
            while cur in enc:  # closure captures come from enclosers
                cur = enc[cur]
                scope_fns.add(cur)
        seen: set[tuple[int, str]] = set()
        for fn in scope_fns:
            attrs = fn_attrs.get(fn, {})
            roots = _config_roots(fn, attrs)
            reads: list[tuple[int, tuple[str, ...]]] = []
            inner = inner_attr_nodes(fn)
            for n in ast.walk(fn):
                if n in inner:
                    continue
                ch = attr_chain(n)
                if ch and ch[0] in roots and len(ch) > 1:
                    reads.append((n.lineno, roots[ch[0]] + tuple(ch[1:])))
                elif ch and len(ch) >= 2 and ch[0] == "self":
                    if ch[1] in attrs:
                        reads.append((n.lineno,
                                      attrs[ch[1]] + tuple(ch[2:])))
                    elif ch[1] in ("_cfg", "cfg") and len(ch) >= 3:
                        reads.append((n.lineno, tuple(ch[2:])))
            for line, path in reads:
                dotted = (".".join(path[:2]) if len(path) >= 2 else
                          (f"{path[0]}.*" if path[0] in SUBTREES
                           else path[0]))
                if (line, dotted) in seen:
                    continue
                seen.add((line, dotted))
                sub = dotted.split(".", 1)[0]
                if (dotted in covered or f"{sub}.*" in covered
                        or _exempt(rel, dotted)):
                    continue
                out.append(Violation(
                    rule=RULE, path=rel, line=line,
                    message=(f"config field `{dotted}` is read in "
                             f"program-building scope but no "
                             f"aot/keys.cache_key derivation covers it "
                             f"— a compiled program baking it would "
                             f"replay stale under a config change "
                             f"(the PR-3 bug class); add it to the key "
                             f"config, or exempt it as signature-"
                             f"visible in passes/aot_keys.py"),
                    key=f"uncovered:{dotted}"))
    return out
