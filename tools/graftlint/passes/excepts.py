#!/usr/bin/env python
"""graftlint pass 0 — no silently-swallowed exceptions (PR 4's lint,
migrated verbatim from tools/check_excepts.py; a shim there preserves
the historical CLI and import surface for tests/test_check_excepts.py
and the docs).

The reference codebase's failure story was bare ``except:`` blocks that
ate errors and kept going — a training run that "finished" with half its
batches silently dropped. This repo's rule, enforced in tier-1:

1. bare ``except:`` is forbidden outright (it catches SystemExit and
   KeyboardInterrupt too — nothing in a library should);
2. an ``except Exception`` / ``except BaseException`` handler that
   SWALLOWS (its body neither re-raises nor propagates via a bare
   ``raise``) must leave a trace: a logging call, a ``warnings.warn``,
   or a telemetry counter/gauge/event — failures may be survivable, but
   never invisible.

A handler may also delegate its trace to a HELPER defined in the same
file (e.g. ``models/layers._count_kernel_fallback``, the log+count
helper every ops/ kernel-fallback path routes through): a call to a
same-module function whose own body leaves a trace counts as leaving a
trace. One level only, resolved statically — a helper that itself
delegates must be exempted explicitly.

A deliberate, documented swallow that genuinely needs silence can carry
``# lint: allow-silent-except`` on its ``except`` line (the historical
pragma; the generic ``# graftlint: allow-excepts`` works too); the
escape is greppable, so every exemption stays reviewable.

Standalone usage: ``python tools/check_excepts.py [root ...]`` — prints
one line per violation, exits 1 if any. Defaults to the repo's
pertgnn_tpu/, bench.py, and the top-level benchmarks/*.py: the
benchmarks are EXIT-CODE ORACLES (pipeline_bench, chaos_bench,
coldstart_bench assert their invariants in the return code), so an
exception swallowed there forges a green result — exactly the failure
mode this lint exists to kill. The vendored parity shim
(benchmarks/parity/) is out of scope: it mimics a third-party API, not
this repo's discipline.
"""

from __future__ import annotations

import ast
import os
import sys

RULE = "excepts"
# per-file findings: sound on any file subset (--changed-only)
PASS_SCOPE = "file"
PRAGMA = "lint: allow-silent-except"
# the generic driver-level pragma must work on BOTH tier-1 entry points
# (tests/test_check_excepts.py runs this module's legacy surface
# directly, without the driver's _suppressed pass) — so check_source
# honors it alongside the historical pragma
_GENERIC_PRAGMA = "graftlint: allow-excepts"

# A Call whose func is an Attribute with one of these names counts as
# "leaving a trace" (logger methods, warnings.warn, telemetry bus).
_TRACE_ATTRS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",  # logger.log(level, ...)
    "counter", "gauge", "histogram", "event",  # telemetry bus
}

BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except (reported separately, but also broad)
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _has_trace_call(root: ast.AST) -> bool:
    """Whether any call under `root` is a direct trace (logger method,
    warnings.warn, telemetry bus, loud print)."""
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _TRACE_ATTRS:
                return True
            if isinstance(fn, ast.Name) and fn.id in ("warn", "print"):
                # warnings.warn imported bare / loud CLI print
                return True
    return False


def _trace_helpers(tree: ast.AST) -> set[str]:
    """Names of functions defined in THIS file whose body leaves a
    trace — a handler calling one of them is logging/counting by
    delegation (the ops/ kernel-fallback pattern: one helper owns the
    log+counter so every fallback site stays consistent). Static,
    same-module, one level deep."""
    return {node.name for node in ast.walk(tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and _has_trace_call(node)}


def _leaves_trace(handler: ast.ExceptHandler,
                  helpers: set[str] | None = None) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True  # not a swallow: it propagates
        if isinstance(node, ast.Return) and node.value is not None:
            # `return some_call(...)` style fallbacks still swallow —
            # only an explicit trace call below counts
            pass
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and helpers and fn.id in helpers:
                return True  # same-module helper that itself traces
    return _has_trace_call(handler)


def check_source(path: str, source: str) -> list[tuple[int, str]]:
    """(line, message) findings for one file's source — the legacy
    entry point, which parses itself; the graftlint pass hands the
    driver's cached tree to check_parsed instead (single-parse
    contract)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [(exc.lineno or 0, f"unparseable ({exc.msg})")]
    return check_parsed(tree, source.splitlines())


def check_parsed(tree: ast.AST,
                 lines: list[str]) -> list[tuple[int, str]]:
    """The structured core over an already-parsed module."""
    helpers = _trace_helpers(tree)
    out: list[tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if PRAGMA in line or _GENERIC_PRAGMA in line:
            continue
        if node.type is None:
            out.append((node.lineno,
                        "bare `except:` is forbidden (catch a specific "
                        "type, or at widest `Exception`)"))
            continue
        if _is_broad(node) and not _leaves_trace(node, helpers):
            out.append((
                node.lineno,
                f"`except {ast.unparse(node.type)}` swallows silently — "
                f"log it, count it on the telemetry bus, or re-raise "
                f"(# {PRAGMA} to exempt deliberately)"))
    return out


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return [f"{path}:{line}: {msg}"
            for line, msg in check_source(path, source)]


def check_tree(root: str) -> list[str]:
    violations: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                violations.extend(check_file(os.path.join(dirpath, name)))
    return violations


def default_roots(repo: str) -> list[str]:
    """The default lint scope: the package, bench.py, and the top-level
    benchmark oracles (NOT benchmarks/parity/ — a vendored shim)."""
    import glob

    return ([os.path.join(repo, "pertgnn_tpu"),
             os.path.join(repo, "bench.py")]
            + sorted(glob.glob(os.path.join(repo, "benchmarks", "*.py"))))


def _enclosing_fn_names(tree: ast.AST) -> dict[int, str]:
    """ExceptHandler lineno -> nearest enclosing function name — the
    baseline-key disambiguator (two identical swallows in two functions
    must not share one accepted-debt entry; same-function repeats
    sharing an entry is the deliberate trace-hazard-style granularity)."""
    out: dict[int, str] = {}

    def visit(node, fn_name):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_name = node.name
        if isinstance(node, ast.ExceptHandler):
            out[node.lineno] = fn_name
        for child in ast.iter_child_nodes(node):
            visit(child, fn_name)

    visit(tree, "<module>")
    return out


def run(ctx) -> list:
    """graftlint pass entry point (the driver's Context supplies the
    same scope default_roots computes for the standalone CLI)."""
    from tools.graftlint.driver import Violation

    out = []
    for rel in ctx.files:
        tree = ctx.tree(rel)
        if tree is None:
            continue  # the driver reports the SyntaxError exactly once
        fn_of = _enclosing_fn_names(tree)
        for line, msg in check_parsed(tree, ctx.lines(rel)):
            out.append(Violation(
                rule=RULE, path=rel, line=line, message=msg,
                key=f"{msg}@{fn_of.get(line, '<module>')}"))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        # passes/ -> graftlint/ -> tools/ -> repo root
        tools_dir = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        args = default_roots(os.path.dirname(tools_dir))
    violations = []
    for root in args:
        violations.extend(check_tree(root) if os.path.isdir(root)
                          else check_file(root))
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} silent-exception violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
