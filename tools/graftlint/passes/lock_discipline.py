"""graftlint pass — lock-discipline: in the threaded serve / fleet /
prefetch / supervisor code, instance attributes of a THREADED class
(one that owns both a lock and a thread) must be mutated under the
owning lock. Bug-class provenance: PR 4/5/7 reviews each hand-audited
the serve queue's and fleet router's counter mutations against their
lock; PR 8 found `deadline_exceeded` incremented outside the lock in
BOTH (fixed in this PR) — exactly the drift a hand audit misses once
the class grows past a screenful.

Static model (one class at a time, resolved lexically):

- a class is THREADED when its body both constructs a
  ``threading.Thread`` (or subclasses Thread) and assigns an instance
  lock: ``self.X = threading.Lock()/RLock()/Condition(...)``. A
  Condition wrapping a lock makes both names locks (``with self._wake``
  and ``with self._lock`` guard the same state).
- every mutation of ``self.<attr>`` outside ``__init__`` — assignment,
  augmented assignment, or a call to a known container mutator
  (``self.pending.append(...)``) — must be lexically inside a
  ``with self.<lock>`` block. Methods named ``*_locked`` are exempt BY
  CONVENTION: the suffix asserts that every caller already holds the
  lock — and the pass ENFORCES the caller side: a
  ``self.<x>_locked(...)`` call outside a ``with self.<lock>`` block
  (from a method not itself ``*_locked``) is a violation.
- exemptions, in reviewability order: the per-class ALLOWLIST below
  (attributes owned by exactly one thread, with the reason stated), a
  line pragma ``# graftlint: allow-lock-discipline`` for single sites
  (e.g. the SIGTERM drain flag that deliberately avoids taking the
  lock from a signal handler), or the baseline file.

The model is deliberately conservative: it does not chase aliasing,
cross-object mutation (``worker.inflight -= 1`` guarded by the ROUTER's
lock), or reads. Reads of drifting counters are benign-stale in
CPython; unlocked WRITES are the lost-update bug class this pass kills.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain

RULE = "lock-discipline"
# per-file findings: sound on any file subset (--changed-only)
PASS_SCOPE = "file"

SCOPE = ("pertgnn_tpu/serve/", "pertgnn_tpu/fleet/",
         "pertgnn_tpu/batching/prefetch.py",
         "pertgnn_tpu/train/supervisor.py",
         "pertgnn_tpu/cli/fleet_main.py",
         "pertgnn_tpu/telemetry/",
         # the streaming subsystem: the rollout controller lives under
         # fleet/ (covered above); stream/ is scoped from day one so a
         # future thread + lock there is checked the moment it appears
         "pertgnn_tpu/stream/")

_MUTATORS = {"append", "extend", "insert", "pop", "remove", "clear",
             "add", "discard", "update", "setdefault", "popitem"}

# (class name, attribute) pairs exempt because exactly ONE thread ever
# writes them — the explicit shared-state allowlist the pass contract
# requires (docs/LINTS.md). Since ISSUE 14 the table LIVES in
# tools/graftsync/justify.py (SINGLE_WRITER): one justification file
# for both concurrency analyzers, so the single-writer reasoning is
# never duplicated or half-updated. Keep the reasons there current: an
# entry whose reason stops being true is a data race with a
# permission slip. (Re-exported under the historical name — the
# liveness pins in tests/test_graftlint.py and tests/test_shield.py
# read `lock_discipline.ALLOWLIST`.)
from tools.graftsync.justify import SINGLE_WRITER as ALLOWLIST
# (serve/queue.py's _Dispatcher owns a Thread but synchronizes via a
# Semaphore, not a lock, so the lock-owning-class criterion skips it —
# its handoff ordering is documented on the class.)


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.lock_attrs: set[str] = set()
        self.makes_thread = any(
            (attr_chain(b) or [""])[-1] == "Thread" for b in node.bases)
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                ch = attr_chain(n.func) or []
                if ch and ch[-1] == "Thread":
                    self.makes_thread = True
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                value = n.value
                if value is None or not isinstance(value, ast.Call):
                    continue
                vch = attr_chain(value.func) or []
                if vch and vch[-1] in ("Lock", "RLock", "Condition"):
                    for t in targets:
                        tch = attr_chain(t)
                        if tch and len(tch) == 2 and tch[0] == "self":
                            self.lock_attrs.add(tch[1])
                    # Condition(self._lock): the wrapped lock guards
                    # the same state under either name
                    if vch[-1] == "Condition":
                        for arg in value.args:
                            ach = attr_chain(arg)
                            if ach and len(ach) == 2 and ach[0] == "self":
                                self.lock_attrs.add(ach[1])

    @property
    def threaded(self) -> bool:
        return self.makes_thread and bool(self.lock_attrs)


def _mutations(method: ast.AST, lock_attrs: set[str]):
    """(line, attr, desc) for every self-attribute mutation in `method`
    that is NOT inside a `with self.<lock>` block. Nested defs are
    walked too (a closure runs on whatever thread calls it, so it needs
    the same discipline as its method)."""

    out: list[tuple[int, str, str]] = []

    def locked_by(withitem: ast.withitem) -> bool:
        ch = attr_chain(withitem.context_expr)
        return bool(ch and len(ch) == 2 and ch[0] == "self"
                    and ch[1] in lock_attrs)

    def visit(node, locked: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a closure DEFINED under the lock still EXECUTES later,
            # on whatever thread calls it, with no lock held — its
            # body restarts unlocked
            locked = False
        if isinstance(node, ast.With):
            locked = locked or any(locked_by(i) for i in node.items)
        if (isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign))
                and not locked):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                # `self.x: int = v` mutates exactly like `self.x = v`
                targets = [] if node.value is None else [node.target]
            else:
                targets = [node.target]
            flat: list[ast.AST] = []
            for t in targets:
                # tuple/list unpacking: `self.a, self.b = ...`
                flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
            for t in flat:
                base = t
                sub = ""
                if isinstance(base, ast.Subscript):
                    base = base.value
                    sub = "[...]"
                ch = attr_chain(base)
                if ch and len(ch) == 2 and ch[0] == "self":
                    op = ("augmented assignment"
                          if isinstance(node, ast.AugAssign)
                          else "assignment")
                    out.append((node.lineno, ch[1], f"{op}{sub}"))
        if isinstance(node, ast.Call) and not locked:
            ch = attr_chain(node.func)
            if (ch and len(ch) == 3 and ch[0] == "self"
                    and ch[2] in _MUTATORS):
                out.append((node.lineno, ch[1], f".{ch[2]}() call"))
            elif (ch and len(ch) == 2 and ch[0] == "self"
                    and ch[1].endswith("_locked")):
                # the other half of the *_locked convention: the suffix
                # PROMISES the caller holds the lock — an unlocked call
                # breaks the contract the method's exemption rests on
                out.append((node.lineno, ch[1],
                            "caller-must-hold-the-lock `*_locked` call"))
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for stmt in method.body:
        visit(stmt, False)
    return out


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    for rel in ctx.files_under(*SCOPE):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = _ClassInfo(node)
            if not info.threaded:
                continue
            for method in node.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__":
                    continue  # construction precedes thread start
                if method.name.endswith("_locked"):
                    continue  # caller-holds-the-lock naming contract
                for line, attr, desc in _mutations(method,
                                                   info.lock_attrs):
                    if attr in info.lock_attrs:
                        continue
                    reason = ALLOWLIST.get((node.name, attr))
                    if reason is not None:
                        continue
                    locks = "/".join(f"self.{a}"
                                     for a in sorted(info.lock_attrs))
                    out.append(Violation(
                        rule=RULE, path=rel, line=line,
                        message=(f"{node.name}.{method.name}: {desc} to "
                                 f"self.{attr} outside `with {locks}` — "
                                 f"this class runs threads; move the "
                                 f"mutation under the lock, allowlist "
                                 f"the attribute with its single-writer "
                                 f"reason, or pragma the line"),
                        key=f"{node.name}.{attr}@{method.name}"))
    return out
