"""graftlint pass — flag-config-drift: every dataclass field in
config.py maps to a CLI flag in cli/common.py, and every flag maps back
to a field, across the CLIs. Bug-class provenance: the PR-6/7 reviews
hand-checked that each new config knob (kernel blocks, serve_dtype, the
whole FleetConfig) grew flags on all CLIs; PR 8's first run of this
pass found `ServeConfig.min_bucket_nodes` / `min_bucket_edges` had
never been CLI-reachable (fixed in this PR).

Mapping rules, in order:

1. exact name: field ``X`` <-> flag ``--X`` (any subtree; collisions
   resolve to the serve-side field for the serve flags by virtue of
   exactness — fleet twins carry the ``router_`` prefix);
2. the ALIASES table below (inverted booleans like ``--no_serve_warmup``
   -> ``serve.warmup``, renames like ``--bf16`` ->
   ``model.bf16_activations``, prefixed fleet twins);
3. the NOT_CLI allowlist: fields deliberately config-only (reference-
   parity constants like ``ingest.ts_bucket_ms`` that exist to be
   pinned, not tuned per run) — each with the reason;
4. the NOT_CONFIG allowlist: flags that are operational inputs, not
   Config fields (``--data_dir``, ``--synthetic``, multihost wiring).

Additionally, the "shared by ALL CLIs" contract: every CLI main under
cli/ must install the telemetry and AOT flag groups (docs claim any
entry point can produce telemetry and replay executables — a CLI that
forgets one silently breaks that).

Violations carry the field/flag name as the baseline key, so accepted
debt survives line drift.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain, const_str

RULE = "flag-config-drift"
# repo-wide contract: needs the FULL file set (a subset would
# fabricate drift) — skipped under --changed-only
PASS_SCOPE = "repo"

CONFIG = "pertgnn_tpu/config.py"
COMMON = "pertgnn_tpu/cli/common.py"
CLI_DIR = "pertgnn_tpu/cli/"

# flag name (no --) -> "subtree.field" it sets (inverted/renamed/
# prefixed forms rule 1 cannot see)
ALIASES: dict[str, str] = {
    "bf16": "model.bf16_activations",
    "missing_indicator_is_zero": "model.missing_indicator_is_one",
    "no_device_materialize": "train.device_materialize",
    "staged_epochs": "train.stage_epoch_recipes",
    "no_stage_epoch_recipes": "train.stage_epoch_recipes",
    "no_serve_warmup": "serve.warmup",
    "no_overlap_dispatch": "serve.overlap_dispatch",
    "compile_cache_dir": "aot.cache_dir",
    "aot_min_compile_time_s": "aot.min_compile_time_s",
    "no_serialize_executables": "aot.serialize_executables",
    "router_flush_deadline_ms": "fleet.router_flush_deadline_ms",
    "router_max_pending": "fleet.max_pending",
    "router_request_deadline_ms": "fleet.request_deadline_ms",
    "router_dispatch_timeout_s": "fleet.dispatch_timeout_s",
}

# "subtree.field" -> why it deliberately has no flag
NOT_CLI: dict[str, str] = {
    "ingest.ts_bucket_ms":
        "reference-parity constant (preprocess.py:39); changing it "
        "invalidates every artifact — config-file-only by design",
    "ingest.entry_tiebreak_um":
        "raw-string domain constant of the reference dataset",
    "ingest.resource_aggs":
        "feature-schema constant; the feature width is baked into "
        "checkpoints",
    "ingest.entry_rpctype":
        "reference dataset constant (preprocess.py:113)",
    "data.split":
        "positional split fractions are reference parity "
        "(pert_gnn.py:198-200); not a per-run tunable",
    "data.shuffle_seed":
        "train-split shuffle is keyed off --seed; a separate knob "
        "would double the provenance surface",
    "train.log_every":
        "cosmetic cadence; PERTGNN_LOG_LEVEL covers the use case",
    "train.checkpoint_every":
        "checkpoint cadence rides checkpoint_dir defaults; exposed "
        "via config files for the supervisor",
    "train.stage_recipes_max_mb":
        "a safety cap that should never bind (recipes are O(graphs) "
        "int32s); tuning it per-run would hide the real bug",
    "parallel.data_axis":
        "mesh axis NAMES are API constants shared with the sharding "
        "rules; renaming per-run would break pjit specs",
    "parallel.model_axis": "same as parallel.data_axis",
}

# flag -> why it is not a Config field (operational input)
NOT_CONFIG: dict[str, str] = {
    "synthetic": "input-source selector, not pipeline semantics",
    "synthetic_entries": "synthetic-generator spec (ingest input)",
    "synthetic_traces_per_entry": "synthetic-generator spec",
    "data_dir": "filesystem location of the raw input",
    "artifact_dir": "filesystem location of the L0-L2 cache",
    "stream_factorize": "ingest execution strategy (ids isomorphic, "
                        "not semantic — ingest/io.py)",
    "ingest_workers": "ingest execution parallelism, result-identical",
    "coordinator_address": "multihost process wiring",
    "num_processes": "multihost process wiring",
    "process_id": "multihost process wiring",
    "allow_config_mismatch": "checkpoint cross-check severity switch",
    "profile_dir": "profiler output location",
    "log_level": "stderr logging verbosity (TelemetryConfig covers "
                 "the bus; this is the human stream)",
}


def _config_fields(ctx) -> dict[str, int]:
    """"subtree.field" (plus top-level Config scalars like graph_type)
    -> definition line, from config.py's dataclasses (statically:
    AnnAssign targets). The `Config` class's own annotations name the
    subtrees (ingest: IngestConfig, ...)."""
    tree = ctx.tree(CONFIG)
    classes: dict[str, list[tuple[str, int]]] = {}
    cfg_class: ast.ClassDef | None = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            classes[node.name] = [
                (item.target.id, item.lineno) for item in node.body
                if isinstance(item, ast.AnnAssign)
                and isinstance(item.target, ast.Name)]
            if node.name == "Config":
                cfg_class = node
    subtree_of: dict[str, str] = {}
    if cfg_class is not None:
        for item in cfg_class.body:
            if (isinstance(item, ast.AnnAssign)
                    and isinstance(item.target, ast.Name)):
                ann = attr_chain(item.annotation) or []
                if ann and ann[-1] in classes:
                    subtree_of[item.target.id] = ann[-1]
    out: dict[str, int] = {}
    for sub, cls in subtree_of.items():
        for name, lineno in classes[cls]:
            out[f"{sub}.{name}"] = lineno
    for name, lineno in classes.get("Config", []):
        if name not in subtree_of:
            out[name] = lineno  # top-level scalar (graph_type)
    return out


def _flags(ctx, rel: str) -> dict[str, int]:
    """flag name (no --) -> line, from add_argument calls in `rel`."""
    tree = ctx.tree(rel)
    out: dict[str, int] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args):
            s = const_str(node.args[0])
            if s and s.startswith("--"):
                out.setdefault(s[2:], node.lineno)
    return out


def _consumed_flags(ctx) -> set[str]:
    """Flag names READ from the parsed namespace anywhere under cli/ or
    bench.py: ``args.X`` attribute reads and ``getattr(args, "X", ...)``
    — a flag that is parsed but never consumed is silently ignored at
    runtime (exactly half of this PR's min_bucket_nodes fix: adding the
    add_argument without the config_from_args getattr would have linted
    clean under a name-match-only check)."""
    consumed: set[str] = set()
    for rel in ctx.files_under(CLI_DIR, "bench.py"):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                ch = attr_chain(node)
                if ch and len(ch) == 2 and ch[0] == "args":
                    consumed.add(ch[1])
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id in ("getattr", "hasattr")
                  and len(node.args) >= 2):
                base = attr_chain(node.args[0]) or []
                s = const_str(node.args[1])
                if base == ["args"] and s:
                    consumed.add(s)
    return consumed


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    if CONFIG not in ctx.files or COMMON not in ctx.files:
        return out  # fixture trees without the pair have no contract
    if ctx.tree(CONFIG) is None or ctx.tree(COMMON) is None:
        return out  # the driver reports the SyntaxError itself
    fields = _config_fields(ctx)
    flags = _flags(ctx, COMMON)
    consumed = _consumed_flags(ctx)

    alias_targets = set(ALIASES.values())
    field_names_by_sub = {}  # bare field name -> dotted
    for dotted in fields:
        bare = dotted.split(".")[-1]
        field_names_by_sub.setdefault(bare, []).append(dotted)

    # fields -> flags
    for dotted, lineno in sorted(fields.items()):
        bare = dotted.split(".")[-1]
        if bare in flags or dotted in alias_targets or dotted in NOT_CLI:
            continue
        out.append(Violation(
            rule=RULE, path=CONFIG, line=lineno,
            message=(f"config field `{dotted}` has no CLI flag in "
                     f"{COMMON} — add one (or an ALIASES/NOT_CLI entry "
                     f"in passes/flag_config.py with the reason)"),
            key=f"field:{dotted}"))

    # flags -> fields
    for flag, lineno in sorted(flags.items()):
        if flag not in consumed:
            # parsed but never read: the flag is accepted and silently
            # discarded — worse than missing, it LOOKS wired
            out.append(Violation(
                rule=RULE, path=COMMON, line=lineno,
                message=(f"flag `--{flag}` is parsed but never read "
                         f"from the namespace (no `args.{flag}` / "
                         f"getattr under cli/ or bench.py) — it is "
                         f"silently ignored at runtime; wire it "
                         f"through config_from_args or drop it"),
                key=f"unconsumed:{flag}"))
        if flag in ALIASES or flag in NOT_CONFIG:
            continue
        if flag in field_names_by_sub or flag in fields:
            continue
        out.append(Violation(
            rule=RULE, path=COMMON, line=lineno,
            message=(f"flag `--{flag}` maps to no config.py dataclass "
                     f"field — rename it, add the field, or record it "
                     f"in NOT_CONFIG (passes/flag_config.py) with the "
                     f"reason"),
            key=f"flag:{flag}"))

    # every CLI installs the shared telemetry + AOT flag groups
    for rel in ctx.files_under(CLI_DIR):
        name = rel.rsplit("/", 1)[-1]
        if not name.endswith("_main.py"):
            continue
        tree = ctx.tree(rel)
        if tree is None:
            continue
        called = {(attr_chain(n.func) or [""])[-1]
                  for n in ast.walk(tree) if isinstance(n, ast.Call)}
        for group in ("add_telemetry_flags", "add_aot_flags"):
            if group not in called:
                out.append(Violation(
                    rule=RULE, path=rel, line=0,
                    message=(f"CLI {name} does not install {group}() — "
                             f"docs promise telemetry and the compile "
                             f"cache on EVERY entry point "
                             f"(docs/OBSERVABILITY.md, docs/GUIDE.md)"),
                    key=f"cli:{name}:{group}"))
    return out
