"""graftlint pass — telemetry-drift: every counter/gauge/histogram/span
name the package emits must appear in docs/OBSERVABILITY.md's metric
tables, and every table row must still correspond to something the code
emits. Bug-class provenance: the PR-6 review found `serve.compiles`
counting rung compiles with no documentation row, and PR 2's
observability contract ("the tables below are the schema") rots
silently without a mechanical check.

What counts as an EMISSION: a call ``<recv>.counter/gauge/histogram/
span/wrap/trace_span/finish_trace("name", ...)`` anywhere under
pertgnn_tpu/, tools/graftaudit/ (the auditor emits audit.*), or
tools/graftscope/ (the trace collector — in scope so its stage-name
literals keep the trace.* doc rows honest) whose name argument
resolves statically — a string constant, a constant-armed conditional
expression, or a local variable assigned only string constants in the
same function (the ``counter = "serve.shed"; ... bus.counter(counter)``
pattern the admission fast paths use). A name argument that does NOT
resolve (f-string, concatenation over runtime values) is itself flagged:
dynamic names are invisible to this check and to anyone grepping the
docs, so they need either a literal spelling or an explicit pragma
(``# graftlint: allow-telemetry-drift``) explaining where the names are
enumerated. ``event`` names are out of scope (meta events carry
free-form payloads; the tables document the numeric schema).

What counts as DOCUMENTED: a backticked dotted name in the first cell
of any table row in docs/OBSERVABILITY.md. Relative rows (`` `.h2d` ``
after `` `train.stage_epoch.pack` ``) expand against the previous full
name. The reverse check accepts a documented name when the code carries
the full name as a literal anywhere, or its final dotted segment as a
literal/dict key (names assembled from schema dicts:
``serve.roofline.mfu_pct`` is built by utils/flops.publish_attribution
from the attribution row's keys).

``python -m tools.graftlint telemetry --emit-table`` doubles as a docs
generator: it rewrites the metric tables in place — dropping rows whose
names no longer exist anywhere in the source and appending rows for
undocumented emissions (kind inferred from the call, note left as a
placeholder) — so the observability contract can be re-synced
mechanically instead of rotting.
"""

from __future__ import annotations

import ast
import re

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import resolve_str_values

RULE = "telemetry-drift"
# repo-wide contract: needs the FULL file set (a subset would
# fabricate drift) — skipped under --changed-only
PASS_SCOPE = "repo"

DOC = "docs/OBSERVABILITY.md"
# trace_span/finish_trace are the distributed-tracing emitters
# (telemetry/bus.py) — name-first signatures precisely so this pass
# can resolve them like any other bus call
_BUS_METHODS = {"counter", "gauge", "histogram", "span", "wrap",
                "trace_span", "finish_trace"}
# receivers that are NOT the telemetry bus but share method names
# (none today — time.perf_counter is an attr of a different name).
_NAME_RE = re.compile(r"^[a-z][a-z0-9_./]*$")


def _method_calls(tree: ast.AST):
    """(call, method, name_expr, enclosing_function_stack) for every
    bus-method call — innermost enclosing function LAST; the whole
    stack matters because a forwarded name param may belong to an outer
    def (the bus's wrap() closes over `name` inside its nested
    `timed`)."""

    def name_expr(call: ast.Call) -> ast.AST | None:
        """The metric-name argument: positional first, or the `name=`
        keyword (bus methods declare `name` as a regular param, so
        keyword spelling is legal and must not be invisible)."""
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg == "name":
                return kw.value
        return None

    def visit(node, fns):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            fns = fns + [node]
        for child in ast.iter_child_nodes(node):
            visit(child, fns)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BUS_METHODS):
            arg = name_expr(node)
            if arg is not None:
                calls.append((node, node.func.attr, arg, fns))

    calls: list[tuple[ast.Call, str, ast.AST, list[ast.AST]]] = []
    visit(tree, [])
    return calls


def _forwards_param(arg: ast.AST, fns: list[ast.AST]) -> bool:
    if not isinstance(arg, ast.Name):
        return False
    for fn in fns:
        a = fn.args
        if arg.id in {x.arg for x in a.posonlyargs + a.args
                      + a.kwonlyargs}:
            return True
    return False


def collect_emissions(ctx) -> tuple[dict[str, list[tuple[str, int, str]]],
                                    list[Violation]]:
    """name -> [(path, line, kind)] over the package, plus violations
    for dynamic (unresolvable) names."""
    emitted: dict[str, list[tuple[str, int, str]]] = {}
    dynamic: list[Violation] = []
    for rel in ctx.files_under("pertgnn_tpu", "tools/graftaudit",
                               "tools/graftscope"):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for call, method, arg, fns in _method_calls(tree):
            # forwarding plumbing (the bus's own span()/wrap(), the
            # module-level telemetry.span helper): the name argument is
            # a PARAMETER of an enclosing function passed through —
            # not an emission site; the real call sites are checked
            if _forwards_param(arg, fns):
                continue
            names = resolve_str_values(arg, fns[-1] if fns else None)
            if names is None:
                # key carries the enclosing function so baselining one
                # dynamic site cannot silently accept a future one
                # elsewhere in the file (same-function repeats sharing
                # an entry is the deliberate granularity)
                fn_name = next(
                    (f.name for f in reversed(fns)
                     if isinstance(f, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))),
                    "<module>")
                dynamic.append(Violation(
                    rule=RULE, path=rel, line=call.lineno,
                    message=(f"dynamic {method}() metric name — not "
                             f"statically resolvable, so neither this "
                             f"check nor {DOC} can see it; spell the "
                             f"name(s) as literals or pragma with a "
                             f"pointer to where they are enumerated"),
                    key=f"dynamic-name@{method}:{fn_name}"))
                continue
            for name in names:
                if _NAME_RE.match(name):
                    emitted.setdefault(name, []).append(
                        (rel, call.lineno, method))
                else:
                    # a constant name the schema regex rejects would be
                    # silently invisible to the contract check — the
                    # same hole dynamic names are flagged for
                    dynamic.append(Violation(
                        rule=RULE, path=rel, line=call.lineno,
                        message=(f"metric name {name!r} does not match "
                                 f"the dotted lower_snake schema "
                                 f"({_NAME_RE.pattern}) — rename it so "
                                 f"the {DOC} contract check can see "
                                 f"it"),
                        key=f"bad-name:{name}"))
    return emitted, dynamic


_ROW_RE = re.compile(r"^\|\s*(?P<cell>[^|]*)\|")
_TICK_RE = re.compile(r"`([^`]+)`")


def _expand_tokens(cell: str) -> list[tuple[str, str]]:
    """(full_name, raw_backticked_token) pairs in one table cell,
    expanding `.suffix` relative tokens against the previous full
    name. The raw token is kept so emit_table can surgically remove a
    dead name from a multi-name row."""
    names: list[tuple[str, str]] = []
    prev_full: str | None = None
    for raw in _TICK_RE.findall(cell):
        tok = raw.strip()
        if tok.startswith("."):
            if prev_full is None:
                continue
            suffix = tok
            nseg = suffix.count(".")
            base = prev_full.rsplit(".", nseg)[0]
            tok = base + suffix
        if _NAME_RE.match(tok) and "." in tok:
            names.append((tok, raw))
            prev_full = tok
    return names


def parse_doc_tables(lines: list[str]
                     ) -> list[tuple[int, list[tuple[str, str]]]]:
    """(line_number_1based, [(name, raw_token)]) per metric-table row.
    Only tables whose header is `| name | kind | ... |` count — prose
    tables (the JSONL field schema) do not document metric names."""
    out: list[tuple[int, list[tuple[str, str]]]] = []
    in_metric_table = False
    for i, line in enumerate(lines):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_metric_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if cells and cells[0].lower() == "name":
            in_metric_table = True
            continue
        if cells and set(cells[0]) <= {"-", " ", ":"}:
            continue
        if not in_metric_table:
            continue
        names = _expand_tokens(cells[0])
        if names:
            out.append((i + 1, names))
    return out


def _package_literals(ctx) -> set[str]:
    """Every string constant in the package source, plus dict-literal
    keys — the reverse check's evidence that a documented name (or its
    final segment) still exists somewhere in code."""
    out: set[str] = set()
    for rel in ctx.files_under("pertgnn_tpu", "tools/graftaudit",
                             "tools/graftscope"):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                             str):
                out.add(node.value)
    return out


def run(ctx) -> list[Violation]:
    emitted, violations = collect_emissions(ctx)
    try:
        doc_lines = ctx.lines(DOC)
    except OSError:
        if not emitted and not violations:
            return []  # nothing emitted, nothing to document
        return violations + [Violation(
            rule=RULE, path=DOC, line=0,
            message="docs/OBSERVABILITY.md is missing — the telemetry "
                    "contract has nowhere to live", key="missing-doc")]
    rows = parse_doc_tables(doc_lines)
    documented: dict[str, int] = {}
    for line_no, pairs in rows:
        for n, _raw in pairs:
            documented.setdefault(n, line_no)

    # forward: emitted but undocumented
    for name in sorted(emitted):
        if name in documented:
            continue
        path, line, kind = emitted[name][0]
        violations.append(Violation(
            rule=RULE, path=path, line=line,
            message=(f"telemetry {kind} `{name}` is emitted but has no "
                     f"row in {DOC} — document it (or run `python -m "
                     f"tools.graftlint telemetry --emit-table`)"),
            key=f"undocumented:{name}"))

    # reverse: documented but gone from the source
    literals = _package_literals(ctx)
    for name, line_no in sorted(documented.items()):
        last_seg = name.rsplit(".", 1)[-1]
        if name in emitted or name in literals or last_seg in literals:
            continue
        violations.append(Violation(
            rule=RULE, path=DOC, line=line_no,
            message=(f"documented metric `{name}` no longer appears "
                     f"anywhere in pertgnn_tpu/, tools/graftaudit/ or tools/graftscope/ — "
                     f"drop the row or restore the emission"),
            key=f"stale-doc:{name}"))
    return violations


# -- docs generator (`python -m tools.graftlint telemetry --emit-table`)


def _strip_dead_tokens(line: str, raws: list[str]) -> str:
    """Remove dead backticked name tokens (plus an adjacent `/` or `,`
    separator and any `(trace)`-style annotation) from a table row's
    FIRST cell, leaving the rest of the row untouched."""
    parts = line.split("|")
    if len(parts) < 2:
        return line
    cell = parts[1]
    ann = r"(?:\s*\([a-z ]+\))?"
    for raw in raws:
        tok = re.escape(f"`{raw}`")
        for pat in (tok + ann + r"\s*[/,]\s*",
                    r"\s*[/,]\s*" + tok + ann,
                    tok + ann):
            new = re.sub(pat, " ", cell, count=1)
            if new != cell:
                cell = new
                break
    parts[1] = " " + cell.strip() + " "
    return "|".join(parts)


def emit_table(ctx) -> tuple[str, dict]:
    """Regenerated docs/OBSERVABILITY.md content + a summary dict.

    Conservative rewrite: hand-written rows and prose are preserved;
    rows whose every name vanished from the source are dropped; new
    emissions are appended to the metric table sharing the longest
    dotted-prefix with them (kind inferred from the emitting call, note
    a placeholder for a human to fill)."""
    emitted, _ = collect_emissions(ctx)
    literals = _package_literals(ctx)
    lines = ctx.lines(DOC)
    rows = {ln: pairs for ln, pairs in parse_doc_tables(lines)}
    documented = {n for pairs in rows.values() for n, _raw in pairs}

    def alive(name: str) -> bool:
        seg = name.rsplit(".", 1)[-1]
        return name in emitted or name in literals or seg in literals

    dropped: list[str] = []
    out: list[str] = []
    table_rows = sorted(rows.items())
    drop_lines = set()
    # partially-dead multi-name rows: strip only the dead tokens so the
    # stale-doc remediation the run() violation recommends actually
    # converges (a row is dropped whole only when EVERY name is dead)
    partial: dict[int, list[str]] = {}
    for ln, pairs in table_rows:
        dead = [(n, raw) for n, raw in pairs if not alive(n)]
        if not dead:
            continue
        if len(dead) == len(pairs):
            drop_lines.add(ln)
        else:
            partial[ln] = [raw for _n, raw in dead]
        dropped.extend(n for n, _raw in dead)

    missing = [n for n in sorted(emitted) if n not in documented]
    # best insertion table per missing name: the table containing the
    # documented name with the longest shared dotted prefix
    def prefix_len(a: str, b: str) -> int:
        pa, pb = a.split("."), b.split(".")
        n = 0
        while n < len(pa) and n < len(pb) and pa[n] == pb[n]:
            n += 1
        return n

    inserts: dict[int, list[str]] = {}
    leftovers: list[str] = []
    for name in missing:
        best_ln, best_score = None, 0
        for ln, pairs in table_rows:
            if ln in drop_lines:
                continue
            score = max((prefix_len(name, n) for n, _raw in pairs),
                        default=0)
            # later rows win ties so appends land at a table's end
            if score > best_score or (score == best_score and score
                                      and best_ln is not None
                                      and ln > best_ln):
                best_ln, best_score = ln, score
        if best_ln is None or best_score == 0:
            leftovers.append(name)
            continue
        kind = emitted[name][0][2]
        kind = {"wrap": "span"}.get(kind, kind)
        inserts.setdefault(best_ln, []).append(
            f"| `{name}` | {kind} | _auto-added by `graftlint telemetry "
            f"--emit-table`; describe me_ |")

    for i, line in enumerate(lines):
        ln = i + 1
        if ln in drop_lines:
            continue
        if ln in partial:
            line = _strip_dead_tokens(line, partial[ln])
        out.append(line)
        for row in inserts.get(ln, []):
            out.append(row)
    summary = {"dropped_rows": dropped,
               # only names that actually landed in a table — an
               # unplaced name is reported as such, never as "added"
               "added": [n for n in missing if n not in leftovers],
               "unplaced": leftovers}
    return "\n".join(out) + "\n", summary
