"""graftlint pass — trace-hazard: host synchronization and Python side
effects inside functions that are traced into compiled programs
(jitted / pjit'd / pallas_call'd / custom_vjp'd, plus flax Module
``__call__``s — resolved statically by passes/_ast_util.traced_functions
with a same-file call fixpoint). Bug-class provenance: the reference
codebase's per-batch ``float()`` metric syncs were the original perf
sin the train loop exists to kill (train/loop.py docstring), and the
PR-5/6 reviews hand-checked every new kernel and overlap path for
accidental ``.item()`` / ``np.asarray`` syncs and trace-time clocks.

Hazards flagged inside a traced body:

- H1 ``x.item()`` — a device->host sync per call;
- H2 ``np.<fn>(...)`` on a non-static argument — numpy forces
  concretization of a tracer (``jnp`` is what belongs inside traces);
  also ``jax.device_get`` and ``.block_until_ready()``;
- H3 ``bool()/float()/int()`` on a non-static argument — implicit
  concretization (a TracerBoolConversionError at best, a silent sync
  on concrete re-execution paths at worst);
- H4 an ``if``/``while`` test that CALLS into ``jnp``/``jax.numpy`` —
  Python control flow on a traced value (use ``lax.cond``/``select``);
- H5 trace-time side effects that silently desynchronize from
  execution: ``print`` and wall-clock reads (``time.time`` /
  ``perf_counter``) run ONCE at trace time, not per step. (Logging
  calls are deliberately exempt: the kernel-fallback pattern logs+counts
  once per compiled program ON PURPOSE — docs/OBSERVABILITY.md
  `model.kernel_fallback`.)

"Static" arguments that defuse H2/H3: constants, ``x.shape`` /
``x.ndim`` / ``.dtype`` expressions (shapes are compile-time in jax),
``len(...)``, ``math.*`` and ``np.*`` math over static values,
config-rooted attribute chains, parameters KNOWN static (partial-bound
keywords of a pallas kernel, custom_vjp nondiff args — resolved by
_ast_util.traced_functions), and free variables (a name the traced
function neither takes nor assigns is a closure/global — a host value
at trace time). False positives carry the line pragma
``# graftlint: allow-trace-hazard`` with a why.
"""

from __future__ import annotations

import ast

from tools.graftlint.driver import Violation
from tools.graftlint.passes._ast_util import attr_chain, traced_functions

RULE = "trace-hazard"
# per-file findings: sound on any file subset (--changed-only)
PASS_SCOPE = "file"

_CONFIG_ROOTS = {"cfg", "config", "self"}
_STATIC_TAILS = {"shape", "ndim", "dtype", "size"}
_NP_ROOTS = {"np", "onp", "numpy"}


def _bound_names(fn: ast.AST) -> set[str]:
    """Names BOUND inside the traced function (params of it and its
    nested defs/lambdas/comprehensions, assignment targets, loop vars):
    potentially tracers. Anything else is free = host-static."""
    bound: set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            a = n.args
            for x in (a.posonlyargs + a.args + a.kwonlyargs
                      + ([a.vararg] if a.vararg else [])
                      + ([a.kwarg] if a.kwarg else [])):
                bound.add(x.arg)
        elif isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
        elif isinstance(n, ast.For):
            for sub in ast.walk(n.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
        elif isinstance(n, ast.comprehension):
            for sub in ast.walk(n.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
        elif isinstance(n, ast.withitem) and n.optional_vars:
            for sub in ast.walk(n.optional_vars):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
    return bound


class _Env:
    def __init__(self, static: set[str], bound: set[str]):
        self.static = static
        self.bound = bound


def _is_static(node: ast.AST, env: _Env) -> bool:
    """Whether an expression is knowably host-static at trace time."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in env.static or node.id not in env.bound
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_static(e, env) for e in node.elts)
    if isinstance(node, (ast.UnaryOp,)):
        return _is_static(node.operand, env)
    if isinstance(node, ast.BinOp):
        return _is_static(node.left, env) and _is_static(node.right, env)
    if isinstance(node, ast.Subscript):
        return _is_static(node.value, env)
    if isinstance(node, ast.Call):
        ch = attr_chain(node.func) or []
        if ch and (ch[0] == "math" or ch[0] in _NP_ROOTS
                   or ch[-1] == "len"):
            return all(_is_static(a, env) for a in node.args)
        return False
    ch = attr_chain(node)
    if ch:
        if ch[-1] in _STATIC_TAILS:
            return True
        if ch[0] in _CONFIG_ROOTS and len(ch) >= 2:
            return True
        if ch[0] in env.static or (ch[0] not in env.bound
                                   and ch[0] != "self"):
            return True
    return False


def _hazards(fn: ast.AST, static_params: set[str]):
    env = _Env(static=set(static_params), bound=_bound_names(fn))
    out: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            ch = attr_chain(node.func) or []
            # attr-name checks, not chains: `.item()` on a CALL result
            # (x.sum().item()) has no resolvable chain but is the same
            # sync
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else "")
            if attr == "item" and not node.args:
                out.append((node.lineno,
                            "H1 `.item()` inside a traced function — a "
                            "device->host sync per call; keep metrics "
                            "on device and sync once per log interval"))
            elif attr == "block_until_ready":
                out.append((node.lineno,
                            "H2 `.block_until_ready()` inside a traced "
                            "function — host sync"))
            elif (len(ch) == 2 and ch[0] in _NP_ROOTS
                  and node.args
                  and not all(_is_static(a, env) for a in node.args)):
                out.append((node.lineno,
                            f"H2 `{'.'.join(ch)}(...)` on a non-static "
                            f"argument inside a traced function — numpy "
                            f"concretizes tracers; use jnp"))
            elif ch in (["jax", "device_get"],):
                out.append((node.lineno,
                            "H2 `jax.device_get` inside a traced "
                            "function — host transfer"))
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("bool", "float", "int")
                  and node.args
                  and not all(_is_static(a, env) for a in node.args)):
                out.append((node.lineno,
                            f"H3 `{node.func.id}(...)` on a non-static "
                            f"argument inside a traced function — "
                            f"implicit tracer concretization"))
            elif isinstance(node.func, ast.Name) and node.func.id == "print":
                out.append((node.lineno,
                            "H5 `print` inside a traced function — runs "
                            "once at trace time, not per step (use "
                            "jax.debug.print for runtime values)"))
            elif ch in (["time", "time"], ["time", "perf_counter"],
                        ["time", "monotonic"]):
                out.append((node.lineno,
                            "H5 wall-clock read inside a traced "
                            "function — evaluates ONCE at trace time "
                            "and is baked into the program"))
        elif isinstance(node, (ast.If, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call):
                    sch = attr_chain(sub.func) or []
                    if sch and sch[0] in ("jnp", "jax") and len(sch) >= 2:
                        out.append((
                            node.lineno,
                            "H4 Python control flow on a traced value "
                            "(`if`/`while` over a jnp expression) — "
                            "this concretizes the tracer or silently "
                            "retraces; use lax.cond / jnp.where"))
                        break
    return out


def run(ctx) -> list[Violation]:
    out: list[Violation] = []
    for rel in ctx.files_under("pertgnn_tpu"):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        seen: set[tuple[int, str]] = set()
        for fn, static_params in traced_functions(tree).items():
            fn_name = getattr(fn, "name", "<lambda>")
            for line, msg in _hazards(fn, static_params):
                if (line, msg) in seen:
                    continue  # nested traced fns overlap lexically
                seen.add((line, msg))
                # baseline key is LINE-INDEPENDENT (driver contract:
                # keys survive drift): hazard class + traced function;
                # same-class repeats in one function share the entry
                out.append(Violation(
                    rule=RULE, path=rel, line=line, message=msg,
                    key=f"{msg.split(' ', 1)[0]}@{fn_name}"))
    return out
