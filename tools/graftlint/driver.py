"""The shared graftlint driver: file discovery, AST cache, per-line
suppression pragmas, baseline file, JSON + human output, exit codes.

Contract every pass plugs into (tools/graftlint/passes/__init__.py):

- a pass module exposes ``RULE`` (its kebab-case name) and
  ``run(ctx) -> list[Violation]``;
- the driver parses each in-scope file ONCE (shared AST cache) — a pass
  never re-reads source it can get from the Context;
- a violation on a line carrying ``# graftlint: allow-<rule>`` is
  suppressed at the driver level (the ``excepts`` pass additionally
  honors its historical ``# lint: allow-silent-except`` pragma);
- a violation whose ``(rule, path, key)`` triple appears in the baseline
  file is reported as *baselined* (visible in --json, excluded from the
  exit code) — the escape hatch for accepted debt, reviewable because
  the file lives in-tree (tools/graftlint/baseline.json by default);
- exit codes: 0 clean (or everything baselined), 1 new violations,
  2 usage / internal error.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")
PRAGMA_PREFIX = "graftlint: allow-"


@dataclasses.dataclass
class Violation:
    """One finding. ``key`` is the violation's stable identity for the
    baseline file (line numbers drift; keys should not) — it defaults
    to the message."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based; 0 = file-level finding
    message: str
    key: str = ""

    def __post_init__(self):
        if not self.key:
            self.key = self.message

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "key": self.key}


class Context:
    """Per-run shared state: the repo root, the discovered file list,
    and a parse cache. Paths are repo-relative with forward slashes."""

    # the lint scope, mirroring check_excepts' historical default: the
    # package, bench.py, and the top-level benchmark oracles — plus
    # tools/graftaudit (the auditor emits audit.* telemetry, so the
    # telemetry-drift contract must see it; it gets the excepts/
    # trace-hazard discipline for free). The vendored parity shim
    # mimics a third-party API — out of scope.
    # Glob semantics are pathlib-style: `*` stays within one path
    # segment, `**/` crosses directories — so "benchmarks/*.py" is
    # top-level only, exactly the legacy default_roots contract.
    INCLUDE = ("pertgnn_tpu/**/*.py", "bench.py", "benchmarks/*.py",
               "tools/graftaudit/**/*.py")
    EXCLUDE = ("benchmarks/parity/**",)

    def __init__(self, repo: str, only: list[str] | None = None):
        self.repo = os.path.abspath(repo)
        self.files = self._discover()
        if only is not None:
            # --changed-only: restrict the in-scope set to the given
            # repo-relative paths (files outside INCLUDE stay out)
            wanted = {p.replace(os.sep, "/") for p in only}
            self.files = [f for f in self.files if f in wanted]
        self._source: dict[str, str] = {}
        self._tree: dict[str, ast.AST | None] = {}
        self.parse_errors: list[Violation] = []

    def _discover(self) -> list[str]:
        include = [_compile_glob(p) for p in self.INCLUDE]
        exclude = [_compile_glob(p) for p in self.EXCLUDE]
        out: list[str] = []
        for dirpath, dirnames, filenames in os.walk(self.repo):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            rel_dir = os.path.relpath(dirpath, self.repo).replace(os.sep,
                                                                  "/")
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                rel = name if rel_dir == "." else f"{rel_dir}/{name}"
                if any(pat.match(rel) for pat in exclude):
                    continue
                if any(pat.match(rel) for pat in include):
                    out.append(rel)
        return out

    def abspath(self, rel: str) -> str:
        return os.path.join(self.repo, rel.replace("/", os.sep))

    def source(self, rel: str) -> str:
        if rel not in self._source:
            with open(self.abspath(rel), encoding="utf-8") as f:
                self._source[rel] = f.read()
        return self._source[rel]

    def lines(self, rel: str) -> list[str]:
        return self.source(rel).splitlines()

    def tree(self, rel: str) -> ast.AST | None:
        """Parsed module, or None when the file does not parse — the
        driver reports the SyntaxError once; passes just skip None."""
        if rel not in self._tree:
            try:
                self._tree[rel] = ast.parse(self.source(rel), filename=rel)
            except SyntaxError as exc:
                self._tree[rel] = None
                self.parse_errors.append(Violation(
                    rule="driver", path=rel, line=exc.lineno or 0,
                    message=f"unparseable ({exc.msg})"))
        return self._tree[rel]

    def files_under(self, *prefixes: str) -> list[str]:
        """In-scope files whose repo-relative path starts with any of
        the given prefixes (or equals one exactly)."""
        return [f for f in self.files
                if any(f == p or f.startswith(p.rstrip("/") + "/")
                       for p in prefixes)]


def _compile_glob(pat: str):
    """Pathlib-style glob -> compiled regex: ``**/`` crosses any number
    of directories (including zero), ``**`` crosses everything, ``*``
    and ``?`` stay within one segment — fnmatch's slash-crossing ``*``
    would silently widen "benchmarks/*.py" to nested files."""
    out = []
    i = 0
    while i < len(pat):
        if pat.startswith("**/", i):
            out.append(r"(?:.*/)?")
            i += 3
        elif pat.startswith("**", i):
            out.append(r".*")
            i += 2
        elif pat[i] == "*":
            out.append(r"[^/]*")
            i += 1
        elif pat[i] == "?":
            out.append(r"[^/]")
            i += 1
        else:
            out.append(re.escape(pat[i]))
            i += 1
    return re.compile("".join(out) + r"\Z")


def _suppressed(ctx: Context, v: Violation,
                pragma_prefix: str = PRAGMA_PREFIX) -> bool:
    if not v.line:
        return False
    try:
        line = ctx.lines(v.path)[v.line - 1]
    except (OSError, IndexError):
        return False
    return f"{pragma_prefix}{v.rule}" in line


def split_findings(ctx: Context, modules: list, baseline: set,
                   pragma_prefix: str = PRAGMA_PREFIX
                   ) -> tuple[list[Violation], list[Violation]]:
    """The driver core shared with graftsync (single source of truth):
    run the pass modules over `ctx`, drop pragma-suppressed findings,
    split the rest (parse errors included — --write-baseline must
    leave a tree that lints clean) against the baseline, and sort
    both sides deterministically."""
    new: list[Violation] = []
    baselined: list[Violation] = []
    for mod in modules:
        for v in mod.run(ctx):
            if _suppressed(ctx, v, pragma_prefix):
                continue
            if (v.rule, v.path, v.key) in baseline:
                baselined.append(v)
            else:
                new.append(v)
    for v in ctx.parse_errors:
        if (v.rule, v.path, v.key) in baseline:
            baselined.append(v)
        else:
            new.append(v)
    new.sort(key=lambda v: (v.path, v.line, v.rule))
    baselined.sort(key=lambda v: (v.path, v.line, v.rule))
    return new, baselined


def load_baseline(path: str) -> set[tuple[str, str, str]]:
    """(rule, path, key) triples accepted as known debt. A missing file
    is an empty baseline; a corrupt one is a usage error (raises)."""
    if not path or not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return {(e["rule"], e["path"], e["key"]) for e in doc.get("entries", [])}


def write_baseline(path: str, violations: list[Violation]) -> None:
    entries = sorted(
        {(v.rule, v.path, v.key) for v in violations})
    entries = [{"rule": r, "path": p, "key": k} for r, p, k in entries]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1,
                  sort_keys=False)
        f.write("\n")


@dataclasses.dataclass
class LintResult:
    new: list[Violation]
    baselined: list[Violation]
    elapsed_s: float
    passes: list[str]

    @property
    def ok(self) -> bool:
        return not self.new

    def as_dict(self) -> dict:
        return {
            "version": 1,
            "passes": self.passes,
            "ok": self.ok,
            "elapsed_s": round(self.elapsed_s, 3),
            "violations": [v.as_dict() for v in self.new],
            "baselined": [v.as_dict() for v in self.baselined],
        }


def run_passes(repo: str, pass_names: list[str] | None = None,
               baseline_path: str | None = None,
               only_files: list[str] | None = None) -> LintResult:
    """Run the named passes (default: all, in registry order) over the
    repo and split the findings against the baseline. `only_files`
    restricts the Context's file set (the --changed-only path — the
    CLI only sends FILE-scoped passes down it; a repo-contract pass on
    a partial file set would fabricate drift violations)."""
    from tools.graftlint.passes import get_passes

    t0 = time.perf_counter()
    ctx = Context(repo, only=only_files)
    baseline = load_baseline(
        DEFAULT_BASELINE if baseline_path is None else baseline_path)
    modules = get_passes(pass_names)
    new, baselined = split_findings(ctx, modules, baseline)
    return LintResult(new=new, baselined=baselined,
                      elapsed_s=time.perf_counter() - t0,
                      passes=[m.RULE for m in modules])


def run_repo(repo: str) -> LintResult:
    """The full suite with the default baseline — what
    tests/test_graftlint.py and bench.py --gate call."""
    return run_passes(repo)
