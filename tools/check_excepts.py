#!/usr/bin/env python
"""Back-compat shim: the silent-exception lint now lives in
``tools/graftlint/passes/excepts.py`` as graftlint's pass 0 (PR 8 —
docs/LINTS.md). This module preserves the historical surface verbatim —
``python tools/check_excepts.py [root ...]`` and the import API
(check_file / check_tree / default_roots / main / PRAGMA / the private
helpers tests/test_check_excepts.py pins) — so existing docs, scripts,
and tests keep working unchanged. New code should run the whole suite:
``python -m tools.graftlint``.
"""

from __future__ import annotations

import os
import sys

# importable both as tools.check_excepts (package) and as a top-level
# module via `sys.path.insert(0, ".../tools")` (how the historical test
# loads it) — the latter needs the REPO root on sys.path for the
# tools.graftlint package import below
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.graftlint.passes.excepts import (  # noqa: E402,F401
    BROAD, PRAGMA, _TRACE_ATTRS, _has_trace_call, _is_broad,
    _leaves_trace, _trace_helpers, check_file, check_source, check_tree,
    default_roots, main)

if __name__ == "__main__":
    raise SystemExit(main())
