"""graftaudit — static analysis over TRACED programs (jaxpr/StableHLO).

graftlint (tools/graftlint/) reads source text; this suite reads the IR
of the stack's real compiled programs — the ground truth for the three
invariants the dynamic tests can only spot-check: padded lanes never
influence real outputs, the quantized serve tiers never silently upcast
their matmuls, and compiled programs never smuggle in host syncs. The
driver enumerates the programs the stack actually runs (every serve
ladder rung x serve_dtype x attention_impl, the train/eval/init
programs, the sharded variants) at a toy config on CPU, lowers each to
its jaxpr, and runs five IR passes (docs/LINTS.md):

- padding-taint   dataflow proof of pad-lane independence
- dtype-flow      no f32 matmuls in bf16/int8 serve programs; int8
                  leaves enter as int8 with exactly one dequantize
- donation        train-step state buffers are donated (StableHLO)
- host-interop    zero callbacks/infeed/outfeed in serve+train programs
- collective-audit collective axis names match the mesh spec; no
                  collectives in single-device programs

Same contract as graftlint: exit 0 clean / 1 new violations / 2 usage
error, JSON + human output, an in-tree baseline file, and (instead of
per-line pragmas — traced IR has no comment lines) a per-program
ALLOWLIST in driver.py whose entries carry their justification.
"""

from tools.graftaudit.driver import run_passes, run_repo

__all__ = ["run_passes", "run_repo"]
