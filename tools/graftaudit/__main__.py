"""``python -m tools.graftaudit`` — see tools/graftaudit/cli.py."""

from tools.graftaudit.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
