"""graftaudit pass — collective-audit: named-axis collectives in the
traced IR match the program's declared mesh.

The edge-sharded attention (parallel/graph_shard.py) writes its
psum/pmax collectives by hand under shard_map; a renamed mesh axis or
a shard_map whose mesh disagrees with the trainer's mesh fails at
runtime on a real slice — hours into a TPU reservation — while
tracing on CPU happily succeeds. This pass checks, per program:

- every collective's axis name (``psum``/``pmax``/``all_gather``/
  ``ppermute``/``axis_index``/...) is an axis of the declared mesh;
- every ``shard_map`` body binds a mesh whose axis names are a subset
  of the declared mesh's;
- a program NOT declared sharded contains no collectives or shard_map
  at all (a single-device serve/train program that traps a collective
  would deadlock the moment it runs on a multi-device mesh).

Implicit-SPMD data parallelism (jit + in_shardings) inserts its
collectives inside XLA, after this IR — those are the partitioner's
to get right; what this pass owns is every axis name WE wrote.
"""

from __future__ import annotations

from tools.graftaudit._ir import src_line, walk_eqns
from tools.graftlint.driver import Violation

RULE = "collective-audit"

COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
    "axis_index", "pgather", "psum_invariant",
})


def _axis_names(eqn) -> list[str]:
    names = []
    for key in ("axes", "axis_name", "axis_index_groups_axis"):
        v = eqn.params.get(key)
        if v is None:
            continue
        if isinstance(v, (tuple, list)):
            names.extend(str(a) for a in v)
        else:
            names.append(str(v))
    return names


def run(programs) -> list[Violation]:
    found: list[Violation] = []
    for spec in programs:
        mesh_axes = set(spec.mesh_axes or ())
        sharded = spec.mesh_axes is not None
        for eqn in walk_eqns(spec.jaxpr):
            name = eqn.primitive.name
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                axes = [str(a) for a in
                        getattr(mesh, "axis_names", ())]
                if not sharded:
                    found.append(Violation(
                        rule=RULE, path=spec.name, line=0,
                        message=(f"shard_map at {src_line(eqn)} in a "
                                 f"program with no declared mesh"),
                        key=f"shard_map@{src_line(eqn)}"))
                else:
                    for a in axes:
                        if a not in mesh_axes:
                            found.append(Violation(
                                rule=RULE, path=spec.name, line=0,
                                message=(f"shard_map at {src_line(eqn)} "
                                         f"binds mesh axis {a!r}, not "
                                         f"an axis of the program's "
                                         f"mesh {sorted(mesh_axes)}"),
                                key=f"shard_map-axis:{a}"))
                continue
            if name not in COLLECTIVES:
                continue
            axes = _axis_names(eqn)
            if not sharded:
                found.append(Violation(
                    rule=RULE, path=spec.name, line=0,
                    message=(f"collective `{name}` over "
                             f"{axes or 'unknown axes'} at "
                             f"{src_line(eqn)} in a single-device "
                             f"program — this deadlocks the moment the "
                             f"program runs on a mesh"),
                    key=f"{name}@{src_line(eqn)}"))
                continue
            for a in axes:
                if a not in mesh_axes:
                    found.append(Violation(
                        rule=RULE, path=spec.name, line=0,
                        message=(f"collective `{name}` at "
                                 f"{src_line(eqn)} names axis {a!r}, "
                                 f"which is not an axis of the "
                                 f"program's mesh "
                                 f"{sorted(mesh_axes)} — this fails "
                                 f"only at runtime on a real slice"),
                        key=f"{name}-axis:{a}"))
    return found
