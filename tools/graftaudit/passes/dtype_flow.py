"""graftaudit pass — dtype-flow: the quantized serve tiers keep their
promises at the IR level.

``serve_dtype=bf16`` promises the hot path runs bf16 through the MXU;
``int8`` additionally promises weights ENTER the compiled program as
int8 (quarter HBM bytes — the whole point, ops/quantize.py) and are
dequantized in-graph exactly once. Both rot silently: one
``.astype(jnp.float32)`` upstream of a matmul and the tier quietly
serves f32 GEMMs at bf16's advertised cost. This pass checks the
traced serve programs directly:

- no float32/float64 ``dot_general`` / ``conv_general_dilated`` in a
  bf16 or int8 serve program (live code only — dead eqns are DCE'd
  first). Pallas kernel bodies are exempt at the call boundary: their
  f32 accumulators are deliberate flash-attention practice, and the
  kernels' cost model is pinned by benchmarks/kernel_bench.py instead;
- an int8 program must have at least one int8 input leaf, and every
  int8 input must be consumed by EXACTLY ONE ``convert_element_type``
  (through any number of structural reshapes/broadcasts) whose target
  is bf16 — zero converts means a dead quantized leaf, two means a
  double dequantize, an f32 target means the dequantize itself
  upcasts.
"""

from __future__ import annotations

from tools.graftaudit._ir import dce, src_line, sub_jaxprs
from tools.graftlint.driver import Violation

RULE = "dtype-flow"

_MATMULS = {"dot_general", "conv_general_dilated"}
_WIDE = {"float32", "float64"}
_STRUCTURAL = {"reshape", "broadcast_in_dim", "transpose", "squeeze",
               "slice", "copy"}


def _wide_matmuls(jaxpr, found, prog):
    """Flag wide matmuls in live eqns, recursing through calls but not
    kernels (tools/graftaudit/_ir.py KERNEL_BOUNDARY)."""
    for eqn in dce(jaxpr):
        name = eqn.primitive.name
        if name in _MATMULS:
            dts = {str(v.aval.dtype) for v in eqn.invars
                   if hasattr(v, "aval")}
            dts.add(str(eqn.outvars[0].aval.dtype))
            wide = sorted(dts & _WIDE)
            if wide:
                found.append(Violation(
                    rule=RULE, path=prog, line=0,
                    message=(f"{wide[0]} `{name}` at {src_line(eqn)} in "
                             f"a quantized serve program — the hot-path "
                             f"GEMMs must stay bf16/int8 (a silent "
                             f"upcast serves f32 at bf16's advertised "
                             f"cost)"),
                    key=f"wide-matmul@{src_line(eqn)}"))
        if name == "pallas_call":
            continue
        for sub in sub_jaxprs(eqn.params):
            _wide_matmuls(sub, found, prog)


def _trace_int8_converts(jaxpr, var, out):
    """Append (target_dtype, eqn) for every convert consuming `var`,
    following structural pass-through ops and call boundaries."""
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in jx.eqns:
        if var not in eqn.invars:
            continue
        name = eqn.primitive.name
        if name == "convert_element_type":
            out.append((str(eqn.outvars[0].aval.dtype), eqn))
        elif name in _STRUCTURAL:
            _trace_int8_converts(jx, eqn.outvars[0], out)
        else:
            sub = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    break
            if sub is not None and hasattr(sub, "jaxpr"):
                if len(sub.jaxpr.invars) == len(eqn.invars):
                    inner = sub.jaxpr.invars[eqn.invars.index(var)]
                    _trace_int8_converts(sub.jaxpr, inner, out)
                else:
                    # soundness direction: a call we cannot map into
                    # must surface as a finding, never vanish
                    out.append((f"<unresolvable `{name}` call "
                                f"(const-carrying arity)>", eqn))
            else:
                out.append(("<non-convert use: %s>" % name, eqn))


def _check_int8_leaves(spec, found):
    jx = spec.jaxpr.jaxpr
    int8_vars = [v for v in jx.invars if str(v.aval.dtype) == "int8"]
    if not int8_vars:
        found.append(Violation(
            rule=RULE, path=spec.name, line=0,
            message="int8 serve program has NO int8 input leaves — "
                    "quantization happened outside the compiled "
                    "program, so the executable reads full-width "
                    "weights from HBM (ops/quantize.py contract)",
            key="no-int8-leaves"))
        return
    for i, v in enumerate(int8_vars):
        uses: list = []
        _trace_int8_converts(jx, v, uses)
        converts = [(dt, e) for dt, e in uses
                    if not dt.startswith("<")]
        odd = [(dt, e) for dt, e in uses if dt.startswith("<")]
        if odd:
            dt, eqn = odd[0]
            found.append(Violation(
                rule=RULE, path=spec.name, line=0,
                message=(f"int8 leaf #{i} feeds {dt} at "
                         f"{src_line(eqn)} — int8 weights may only be "
                         f"dequantized (convert + scale)"),
                key=f"int8-nonconvert-use@{i}"))
        if len(converts) != 1:
            found.append(Violation(
                rule=RULE, path=spec.name, line=0,
                message=(f"int8 leaf #{i} has {len(converts)} in-graph "
                         f"dequantize converts (contract: exactly one "
                         f"— zero is a dead leaf, several re-read the "
                         f"leaf and defeat the HBM saving)"),
                key=f"int8-convert-count@{i}"))
        for dt, eqn in converts:
            if dt in _WIDE:
                found.append(Violation(
                    rule=RULE, path=spec.name, line=0,
                    message=(f"int8 leaf #{i} dequantizes to {dt} at "
                             f"{src_line(eqn)} — the dequantize target "
                             f"is bf16 (ops/quantize.dequantize_array)"),
                    key=f"int8-wide-dequant@{i}"))


def run(programs) -> list[Violation]:
    found: list[Violation] = []
    for spec in programs:
        if "serve" not in spec.tags:
            continue
        if not ({"bf16", "int8"} & spec.tags):
            continue
        _wide_matmuls(spec.jaxpr, found, spec.name)
        if "int8" in spec.tags:
            _check_int8_leaves(spec, found)
    return found
