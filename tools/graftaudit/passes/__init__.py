"""Pass registry — graftlint's conventions (tools/graftlint/passes/)."""

from __future__ import annotations

from tools.graftaudit.passes import (collective_audit, donation,
                                     dtype_flow, host_interop,
                                     padding_taint)

_ORDER = (padding_taint, dtype_flow, donation, host_interop,
          collective_audit)

ALIASES = {
    "padding": padding_taint, "taint": padding_taint,
    "dtype": dtype_flow,
    "donate": donation,
    "host": host_interop, "interop": host_interop,
    "collective": collective_audit, "collectives": collective_audit,
}


def registry() -> dict[str, object]:
    return {m.RULE: m for m in _ORDER}


def get_passes(names: list[str] | None = None) -> list:
    if not names:
        return list(_ORDER)
    reg = registry()
    out = []
    for n in names:
        mod = reg.get(n) or ALIASES.get(n)
        if mod is None:
            raise KeyError(
                f"unknown pass {n!r} (choose from {sorted(reg)} "
                f"or aliases {sorted(ALIASES)})")
        if mod not in out:
            out.append(mod)
    return out
