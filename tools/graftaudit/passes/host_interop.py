"""graftaudit pass — host-interop: compiled serve/train/eval/init
programs carry ZERO host callbacks, infeed, or outfeed.

graftlint's ``trace-hazard`` pass catches the SOURCE patterns that
create host round-trips (``.item()``, ``print`` under jit, np-on-
tracer) — heuristically, in the files it can see. This pass closes the
loop at the IR: whatever the source looked like, if a host callback
made it into the traced program, it is a per-dispatch host sync on the
serve path / a per-step stall on the train path, and it shows up here
as a ``pure_callback`` / ``io_callback`` / ``debug_callback`` /
``infeed`` / ``outfeed`` eqn. Deliberately NO dead-code elimination
here: a value-dead ``pure_callback`` traces with empty effects on
this jax, DCE would drop it, and whether XLA also drops the custom
call is backend detail — it should not be in the program at all.
Pallas kernel bodies are exempt (``pl.debug_print`` is device-side).
"""

from __future__ import annotations

from tools.graftaudit._ir import src_line, sub_jaxprs
from tools.graftlint.driver import Violation

RULE = "host-interop"

HOST_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "infeed",
    "outfeed", "host_callback", "outside_call",
})


def _scan(jaxpr, found, prog):
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in jx.eqns:
        name = eqn.primitive.name
        if name in HOST_PRIMS:
            cb = eqn.params.get("callback", "")
            found.append(Violation(
                rule=RULE, path=prog, line=0,
                message=(f"`{name}` at {src_line(eqn)} — a compiled "
                         f"program with a host round-trip stalls every "
                         f"dispatch on the host (callback: {cb!r:.80})"),
                key=f"{name}@{src_line(eqn)}"))
        if name == "pallas_call":
            continue
        for sub in sub_jaxprs(eqn.params):
            _scan(sub, found, prog)


def run(programs) -> list[Violation]:
    found: list[Violation] = []
    for spec in programs:
        _scan(spec.jaxpr, found, spec.name)
    return found
