"""graftaudit pass — padding-taint: a dataflow PROOF, on the jaxpr,
that padded node/edge/graph lanes cannot influence the real outputs of
a serve program.

The dynamic padding-invariance tests (tests/test_serve.py,
tests/test_model.py) re-pack one request at several pad shapes and
assert bit-identical predictions — strong evidence, but per-shape and
per-config. This pass proves the property for EVERY enumerated serve
program by abstract interpretation over a taint domain:

per variable, per lane class (node / edge / graph), the dependence on
that class's PADDED input values is either absent, *confined* to a set
of (axis, class) pad-lane regions, or unconfined (dirty). Masking
idioms discharge confinements:

- ``select_n`` over a mask (False on every pad lane) replaces pad
  lanes with the constant branch;
- multiplication by a mask-zeroed array pins pad lanes to 0, which
  reductions / dot contractions / scatter-adds then ignore;
- scatter combiners drop pad-lane updates pinned to their identity
  (0 for add, -inf for max), so data-dependent ROUTING of pad rows
  (senders/receivers/node_graph are themselves padded data) is a
  no-op;
- gathers whose indices are the packer's routing arrays leak an
  operand's pad-lane dependence only into the gather's own pad rows —
  sound because real routing values index only real lanes, a PACKER
  invariant this analysis assumes and the packing tests pin
  dynamically (docs/LINTS.md "assumptions").

A program is clean when every output's remaining dependence is
confined to pad-lane regions the caller discards (the serve engine
slices predictions to the real graph count). Anything the rule table
cannot discharge — an unmodeled primitive, an unmasked reduction, a
``pallas_call`` boundary — degrades to dirty and is reported with the
source line from the eqn traceback; soundness direction: the pass can
cry wolf, it cannot certify a leak away.

Known modeling assumptions (shared by the fp semantics of the masking
idioms themselves, and documented in docs/LINTS.md): ``0 * x == 0``
and ``0 / x == 0`` — non-finite pad-lane values would break both, and
those are caught at runtime by the engine's NonFiniteOutput guard.
"""

from __future__ import annotations

import dataclasses
import math

from tools.graftaudit._ir import src_line
from tools.graftlint.driver import Violation

RULE = "padding-taint"

DIRTY = "DIRTY"
_NEG_INF = float("-inf")


@dataclasses.dataclass
class Abs:
    """Abstract value of one jaxpr var.

    deps: lane class -> DIRTY or set of (axis, lane_class) confinement
      regions (dependence on the class's padded inputs lives only in
      the union of those regions' pad lanes).
    padv: (axis, lane_class) -> scalar pinned on every pad lane of
      that region (masks after cast, masked products, -inf scores).
    const: scalar when the whole array is that constant.
    routes / routes_like: packer routing class of the var's REAL-lane
      values (routes_like: an arithmetic shift of a routing array, the
      negative-index wrap idiom).
    rng: (lo, hi) value bounds (iota / int consts) for the
      mask-vs-iota comparison rule.
    ident_axis: value along this axis equals the position (iota).
    why: lane class -> first reason the class went dirty.
    """

    deps: dict = dataclasses.field(default_factory=dict)
    padv: dict = dataclasses.field(default_factory=dict)
    const: object = None
    routes: str | None = None
    routes_like: str | None = None
    rng: tuple | None = None
    ident_axis: int | None = None
    why: dict = dataclasses.field(default_factory=dict)

    def copy(self) -> "Abs":
        return Abs(deps={c: (d if d is DIRTY else set(d))
                         for c, d in self.deps.items()},
                   padv=dict(self.padv), const=self.const,
                   routes=self.routes, routes_like=self.routes_like,
                   rng=self.rng, ident_axis=self.ident_axis,
                   why=dict(self.why))

    def route_class(self) -> str | None:
        return self.routes or self.routes_like

    def dep_members(self) -> set:
        out = set()
        for d in self.deps.values():
            if d is not DIRTY:
                out |= d
        return out

    def has_dirty(self) -> bool:
        return any(d is DIRTY for d in self.deps.values())

    def normalize(self) -> "Abs":
        """padv implies the region's lanes are constant — drop dep
        members covered by a pinned region."""
        for cls in list(self.deps):
            d = self.deps[cls]
            if d is DIRTY:
                continue
            d -= set(self.padv)
            if not d:
                del self.deps[cls]
        return self


def _clean(const=None, **kw) -> Abs:
    return Abs(const=const, **kw)


def _taint(why_map) -> Abs:
    a = Abs()
    for cls, why in why_map.items():
        a.deps[cls] = DIRTY
        a.why[cls] = why
    return a


def _join_deps(ins, out_rank=None):
    """Union of operand deps (+ why), the default elementwise rule —
    sound because lanes align positionally for same-rank broadcasting
    ops. Confinements on axes beyond the output rank degrade to
    dirty."""
    deps, why = {}, {}
    for a in ins:
        for cls, d in a.deps.items():
            if d is DIRTY or deps.get(cls) is DIRTY:
                deps[cls] = DIRTY
                why.setdefault(cls, a.why.get(cls, "joined dirty input"))
                continue
            members = set(d)
            if out_rank is not None and any(ax >= out_rank
                                            for ax, _ in members):
                deps[cls] = DIRTY
                why.setdefault(cls, "confinement axis lost in join")
                continue
            deps.setdefault(cls, set()).update(members)
    return deps, why


_PADV_FNS = {
    "add": lambda a, b: a + b, "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b if b else math.nan,
    "max": max, "min": min,
    "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b,
    "eq": lambda a, b: a == b, "ne": lambda a, b: a != b,
    "and": lambda a, b: bool(a) and bool(b),
    "or": lambda a, b: bool(a) or bool(b),
    "xor": lambda a, b: bool(a) ^ bool(b),
    "not": lambda a: not a,
    "exp": math.exp, "neg": lambda a: -a, "abs": abs,
    "is_finite": lambda a: math.isfinite(a),
    "sign": lambda a: (a > 0) - (a < 0),
    "convert_element_type": lambda a: a,
    "reduce_precision": lambda a: a,
    "square": lambda a: a * a,
    "integer_pow": None,  # exponent rides eqn.params["y"]; _ew builds
    #                       the concrete fn per eqn
}

_ELEMENTWISE = frozenset(_PADV_FNS) | frozenset({
    "rsqrt", "sqrt", "log", "log1p", "expm1", "logistic", "tanh",
    "sin", "cos", "erf", "erf_inv", "floor", "ceil", "round", "pow",
    "rem", "atan2", "clamp", "nextafter", "copy", "real", "imag",
    "stop_gradient", "cbrt", "sinh", "cosh", "asin", "acos", "atan",
    "exp2",
})

# kills: pinning any operand's region to the absorbing element pins
# the result region regardless of the other operands
_ABSORBING = {"mul": 0, "and": False, "or": True}

_REDUCE_VAL = {
    "reduce_sum": lambda v, n: v * n, "reduce_prod": lambda v, n: v**n,
    "reduce_max": lambda v, n: v, "reduce_min": lambda v, n: v,
    "reduce_and": lambda v, n: v, "reduce_or": lambda v, n: v,
}

_SCATTER_IDENTITY = {"scatter-add": 0.0, "scatter-max": _NEG_INF,
                     "scatter-min": float("inf")}

_CALL_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


class _Interp:
    def __init__(self, spec):
        self.spec = spec

    # -- environment ------------------------------------------------------

    def read(self, env, v) -> Abs:
        if hasattr(v, "val"):  # Literal
            val = v.val
            if getattr(val, "ndim", 0) == 0:
                try:
                    return _clean(const=val.item()
                                  if hasattr(val, "item") else val)
                except (ValueError, TypeError):
                    return _clean()
            return _clean()
        return env.get(v, _clean())

    def eval_closed(self, closed, in_abs) -> list[Abs]:
        jx = closed.jaxpr
        env = {}
        for var, const in zip(jx.constvars, closed.consts):
            c = None
            if getattr(const, "ndim", 0) == 0:
                try:
                    c = const.item()
                except (ValueError, TypeError):
                    c = None
            env[var] = _clean(const=c)
        if len(jx.invars) != len(in_abs):
            raise ValueError("arity mismatch")
        for var, a in zip(jx.invars, in_abs):
            env[var] = a
        for eqn in jx.eqns:
            outs = self.eval_eqn(eqn, [self.read(env, v)
                                       for v in eqn.invars])
            for var, a in zip(eqn.outvars, outs):
                if type(var).__name__ != "DropVar":
                    env[var] = a.normalize()
        return [self.read(env, v) for v in jx.outvars]

    # -- dispatch ---------------------------------------------------------

    def eval_eqn(self, eqn, ins) -> list[Abs]:
        name = eqn.primitive.name
        handler = getattr(self, "_p_" + name.replace("-", "_"), None)
        if handler is not None:
            return handler(eqn, ins)
        if name == "select_n":
            return self._select_n(eqn, ins)
        if name in _ELEMENTWISE:
            return [self._ew(eqn, ins, name)]
        for key in _CALL_JAXPR_KEYS:
            sub = eqn.params.get(key)
            if sub is not None and hasattr(sub, "jaxpr"):
                return self._call(eqn, ins, sub)
        return self._unknown(eqn, ins, f"unmodeled primitive `{name}`")

    def _unknown(self, eqn, ins, reason) -> list[Abs]:
        """Sound default: a pure function of clean inputs is clean;
        any input dependence becomes unconfined."""
        why_map = {}
        for a in ins:
            for cls in a.deps:
                why_map.setdefault(
                    cls, f"{reason} at {src_line(eqn)}")
        out = _taint(why_map)
        return [out.copy() for _ in eqn.outvars]

    # -- elementwise ------------------------------------------------------

    def _ew(self, eqn, ins, name) -> Abs:
        out_aval = eqn.outvars[0].aval
        out_rank = len(out_aval.shape)
        deps, why = _join_deps(ins, out_rank)
        res = Abs(deps=deps, why=why)

        # absorbing element (mul by a mask-zeroed array): pins the
        # region AND discharges every confinement on it
        absorb = _ABSORBING.get(name)
        if name == "div":
            absorb = None  # only the numerator absorbs for div
        keys = set()
        for a in ins:
            keys |= set(a.padv)
        if absorb is not None:
            for k in keys:
                if any(a.padv.get(k, a.const) == absorb
                       and _axis_ok(a, k, out_aval) for a in ins):
                    res.padv[k] = absorb
        if name == "div" and ins and ins[0].padv:
            for k, v in ins[0].padv.items():
                if v == 0 and _axis_ok(ins[0], k, out_aval):
                    # 0 / x == 0 (documented fp assumption)
                    res.padv[k] = 0
        # constant propagation across a pinned region
        fn = _PADV_FNS.get(name)
        if name == "integer_pow":
            fn = lambda a, _y=eqn.params["y"]: a ** _y  # noqa: E731
        if fn is not None:
            for k in keys:
                if k in res.padv:
                    continue
                vals = []
                for a in ins:
                    v = a.padv.get(k, a.const)
                    if v is None:
                        break
                    vals.append(v)
                else:
                    try:
                        res.padv[k] = fn(*vals)
                    except (TypeError, ValueError, OverflowError,
                            ZeroDivisionError):
                        pass
        # eq/ne of a pinned region against a bounded-range operand
        # (the blocked-dense incidence: receivers pinned to -1 vs an
        # iota that is always >= 0)
        if name in ("eq", "ne") and len(ins) == 2:
            for a, b in ((ins[0], ins[1]), (ins[1], ins[0])):
                if b.rng is None:
                    continue
                lo, hi = b.rng
                for k, v in a.padv.items():
                    if k in res.padv or not isinstance(v, (int, float)):
                        continue
                    if v < lo or v > hi:
                        res.padv[k] = (name == "ne")
        if all(a.const is not None for a in ins) and fn is not None:
            try:
                res.const = fn(*[a.const for a in ins])
            except (TypeError, ValueError, OverflowError,
                    ZeroDivisionError):
                pass
        if name == "convert_element_type":
            src = ins[0]
            res.routes, res.routes_like = src.routes, src.routes_like
            res.rng, res.ident_axis = src.rng, src.ident_axis
        elif name in ("add", "sub"):
            routed = [a for a in ins if a.route_class() is not None]
            consts = [a for a in ins if a.const is not None]
            if len(routed) == 1 and len(routed) + len(consts) == len(ins):
                res.routes_like = routed[0].route_class()
        return res

    def _select_n(self, eqn, ins) -> list[Abs]:
        pred, *cases = ins
        out_aval = eqn.outvars[0].aval
        out_rank = len(out_aval.shape)
        if len(cases) != 2:
            return [self._ew(eqn, ins, "select_n_generic")]
        res = Abs()
        res.deps, res.why = _join_deps([pred], out_rank)
        # which case each pinned predicate region selects
        pinned = {k: v for k, v in pred.padv.items()
                  if isinstance(v, bool)}
        if pred.const is not None and isinstance(pred.const, bool):
            chosen_all = cases[int(pred.const)]
            res = chosen_all.copy()
            d, w = _join_deps([pred], out_rank)
            _merge(res, d, w)
            return [res.normalize()]
        for i, case in enumerate(cases):
            d, w = _join_deps([case], out_rank)
            for cls, members in d.items():
                if members is DIRTY:
                    res.deps[cls] = DIRTY
                    res.why.setdefault(cls, w.get(cls, ""))
                    continue
                kept = {m for m in members
                        if not (m in pinned and pinned[m] != bool(i))}
                if kept:
                    cur = res.deps.get(cls)
                    if cur is not DIRTY:
                        res.deps.setdefault(cls, set()).update(kept)
        for k, sel in pinned.items():
            chosen = cases[int(sel)]
            v = chosen.padv.get(k, chosen.const)
            if v is not None and _axis_ok(chosen, k, out_aval):
                res.padv[k] = v
        # the negative-index wrap idiom keeps routing through a select
        rc = {c.route_class() for c in cases}
        if len(rc) == 1 and None not in rc and not pred.has_dirty():
            res.routes = rc.pop()
        return [res.normalize()]

    # -- structural -------------------------------------------------------

    def _p_broadcast_in_dim(self, eqn, ins) -> list[Abs]:
        src = ins[0]
        bd = eqn.params["broadcast_dimensions"]
        res = Abs(const=src.const, rng=src.rng, routes=src.routes,
                  routes_like=src.routes_like)
        amap = {i: bd[i] for i in range(len(bd))}
        _remap(src, res, amap)
        if src.ident_axis is not None and src.ident_axis in amap:
            res.ident_axis = amap[src.ident_axis]
        return [res]

    def _p_reshape(self, eqn, ins) -> list[Abs]:
        src = ins[0]
        in_shape = eqn.invars[0].aval.shape
        out_shape = eqn.outvars[0].aval.shape
        amap = _reshape_axis_map(in_shape, out_shape)
        res = Abs(const=src.const, rng=src.rng, routes=src.routes,
                  routes_like=src.routes_like)
        _remap(src, res, amap,
               lost=f"reshape {in_shape}->{out_shape} at {src_line(eqn)}")
        if src.ident_axis is not None and src.ident_axis in amap:
            res.ident_axis = amap[src.ident_axis]
        return [res]

    def _p_transpose(self, eqn, ins) -> list[Abs]:
        src = ins[0]
        perm = eqn.params["permutation"]
        amap = {old: new for new, old in enumerate(perm)}
        res = Abs(const=src.const, rng=src.rng, routes=src.routes,
                  routes_like=src.routes_like)
        _remap(src, res, amap)
        if src.ident_axis is not None:
            res.ident_axis = amap.get(src.ident_axis)
        return [res]

    def _p_squeeze(self, eqn, ins) -> list[Abs]:
        src = ins[0]
        dropped = set(eqn.params["dimensions"])
        rank = len(eqn.invars[0].aval.shape)
        amap, j = {}, 0
        for i in range(rank):
            if i not in dropped:
                amap[i] = j
                j += 1
        res = Abs(const=src.const, rng=src.rng, routes=src.routes,
                  routes_like=src.routes_like)
        _remap(src, res, amap)
        if src.ident_axis is not None:
            res.ident_axis = amap.get(src.ident_axis)
        return [res]

    def _p_slice(self, eqn, ins) -> list[Abs]:
        src = ins[0]
        starts = eqn.params["start_indices"]
        strides = eqn.params["strides"] or [1] * len(starts)
        res = Abs(const=src.const, routes=src.routes,
                  routes_like=src.routes_like)
        # a from-0 unit-stride prefix keeps lane positions; anything
        # else shifts them out from under the confinement
        amap = {a: a for a in range(len(starts))
                if starts[a] == 0 and strides[a] == 1}
        _remap(src, res, amap,
               lost=f"offset slice at {src_line(eqn)}")
        return [res]

    def _p_concatenate(self, eqn, ins) -> list[Abs]:
        dim = eqn.params["dimension"]
        out_rank = len(eqn.outvars[0].aval.shape)
        deps, why = {}, {}
        for a in ins:
            for cls, d in a.deps.items():
                if d is DIRTY or deps.get(cls) is DIRTY:
                    deps[cls] = DIRTY
                    why.setdefault(cls, a.why.get(cls, ""))
                    continue
                for m in d:
                    if m[0] == dim or m[0] >= out_rank:
                        deps[cls] = DIRTY
                        why.setdefault(
                            cls, f"concatenate along confined axis at "
                                 f"{src_line(eqn)}")
                        break
                else:
                    deps.setdefault(cls, set()).update(d)
        res = Abs(deps=deps, why=why)
        # conservative padv: keep a region only when every operand pins
        # the same value on it (axis != concat dim)
        keys = set()
        for a in ins:
            keys |= set(a.padv)
        for k in keys:
            if k[0] == dim:
                continue
            vals = {a.padv.get(k, a.const) for a in ins}
            if len(vals) == 1 and None not in vals:
                res.padv[k] = vals.pop()
        return [res.normalize()]

    def _p_iota(self, eqn, ins) -> list[Abs]:
        dim = eqn.params["dimension"]
        size = eqn.outvars[0].aval.shape[dim]
        return [Abs(rng=(0, max(size - 1, 0)), ident_axis=dim)]

    def _p_pad(self, eqn, ins) -> list[Abs]:
        return self._unknown(eqn, ins, "lax.pad over confined lanes")

    # -- reductions -------------------------------------------------------

    def _reduce(self, eqn, ins, name) -> list[Abs]:
        src = ins[0]
        axes = set(eqn.params["axes"])
        in_shape = eqn.invars[0].aval.shape
        amap, j = {}, 0
        for i in range(len(in_shape)):
            if i not in axes:
                amap[i] = j
                j += 1
        res = Abs()
        for cls, d in src.deps.items():
            if d is DIRTY:
                res.deps[cls] = DIRTY
                res.why[cls] = src.why.get(cls, "")
                continue
            members = set()
            for ax, mcls in d:
                if ax in axes:
                    res.deps[cls] = DIRTY
                    res.why[cls] = (
                        f"`{name}` over unmasked {mcls}-pad lanes at "
                        f"{src_line(eqn)} — mask (select_n / multiply "
                        f"by the {mcls} mask) before reducing")
                    break
                members.add((amap[ax], mcls))
            else:
                if members:
                    res.deps[cls] = members
        valfn = _REDUCE_VAL.get(name)
        if valfn is not None:
            n_red = 1
            for a in axes:
                n_red *= in_shape[a]
            for (ax, mcls), v in src.padv.items():
                if ax not in axes and isinstance(v, (int, float, bool)):
                    try:
                        res.padv[(amap[ax], mcls)] = valfn(v, n_red)
                    except (TypeError, OverflowError):
                        pass
        res.normalize()
        return [res.copy() for _ in eqn.outvars]

    def _p_reduce_sum(self, eqn, ins):
        return self._reduce(eqn, ins, "reduce_sum")

    def _p_reduce_max(self, eqn, ins):
        return self._reduce(eqn, ins, "reduce_max")

    def _p_reduce_min(self, eqn, ins):
        return self._reduce(eqn, ins, "reduce_min")

    def _p_reduce_prod(self, eqn, ins):
        return self._reduce(eqn, ins, "reduce_prod")

    def _p_reduce_and(self, eqn, ins):
        return self._reduce(eqn, ins, "reduce_and")

    def _p_reduce_or(self, eqn, ins):
        return self._reduce(eqn, ins, "reduce_or")

    def _p_argmax(self, eqn, ins):
        return self._reduce(eqn, ins, "argmax")

    def _p_argmin(self, eqn, ins):
        return self._reduce(eqn, ins, "argmin")

    def _p_cumsum(self, eqn, ins):
        return self._unknown(eqn, ins, "cumulative op over confined "
                                       "lanes")

    def _p_cumlogsumexp(self, eqn, ins):
        return self._p_cumsum(eqn, ins)

    def _p_cummax(self, eqn, ins):
        return self._p_cumsum(eqn, ins)

    def _p_sort(self, eqn, ins):
        return self._unknown(eqn, ins, "sort over confined lanes")

    # -- contraction ------------------------------------------------------

    def _p_dot_general(self, eqn, ins) -> list[Abs]:
        lhs, rhs = ins
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lshape = eqn.invars[0].aval.shape
        rshape = eqn.invars[1].aval.shape
        lfree = [a for a in range(len(lshape))
                 if a not in lc and a not in lb]
        rfree = [a for a in range(len(rshape))
                 if a not in rc and a not in rb]
        lmap = {a: i for i, a in enumerate(lb)}
        lmap.update({a: len(lb) + i for i, a in enumerate(lfree)})
        rmap = {a: i for i, a in enumerate(rb)}
        rmap.update({a: len(lb) + len(lfree) + i
                     for i, a in enumerate(rfree)})
        pair = dict(zip(lc, rc))
        pair_r = dict(zip(rc, lc))

        res = Abs()

        def side(a, other, amap, contracted, opair):
            for cls, d in a.deps.items():
                if d is DIRTY or res.deps.get(cls) is DIRTY:
                    res.deps[cls] = DIRTY
                    res.why.setdefault(cls, a.why.get(cls, ""))
                    continue
                for ax, mcls in d:
                    if ax in contracted:
                        # discharged when the other operand pins its
                        # paired contracted region to 0 (masked)
                        ok = (other.padv.get((opair[ax], mcls)) == 0
                              or other.const == 0)
                        if not ok:
                            res.deps[cls] = DIRTY
                            res.why[cls] = (
                                f"dot_general contracts unmasked "
                                f"{mcls}-pad lanes at {src_line(eqn)}")
                            break
                    else:
                        res.deps.setdefault(cls, set()).add(
                            (amap[ax], mcls))
            for (ax, mcls), v in a.padv.items():
                if ax not in contracted and v == 0:
                    res.padv[(amap[ax], mcls)] = 0

        side(lhs, rhs, lmap, set(lc), pair)
        side(rhs, lhs, rmap, set(rc), pair_r)
        return [res.normalize()]

    # -- gather / scatter -------------------------------------------------

    def _p_gather(self, eqn, ins) -> list[Abs]:
        operand, indices = ins
        dn = eqn.params["dimension_numbers"]
        op_shape = eqn.invars[0].aval.shape
        idx_shape = eqn.invars[1].aval.shape
        out_rank = len(eqn.outvars[0].aval.shape)
        if (tuple(dn.start_index_map) != (0,)
                or tuple(dn.collapsed_slice_dims) != (0,)
                or getattr(dn, "operand_batching_dims", ())):
            return self._unknown(eqn, ins, "unmodeled gather shape")
        offset = list(dn.offset_dims)
        batch_out = [d for d in range(out_rank) if d not in offset]
        idx_batch_map = {i: batch_out[i] for i in range(len(batch_out))}
        op_map = {}
        for k, a in enumerate(range(1, len(op_shape))):
            if k < len(offset):
                op_map[a] = offset[k]
        res = Abs()
        # indices' own dependence lands on the gather's batch axes
        idx_leak = set()
        for cls, d in indices.deps.items():
            if d is DIRTY:
                res.deps[cls] = DIRTY
                res.why[cls] = indices.why.get(cls, "")
                continue
            mapped = set()
            for ax, mcls in d:
                if ax not in idx_batch_map:
                    res.deps[cls] = DIRTY
                    res.why[cls] = (f"gather index confinement lost at "
                                    f"{src_line(eqn)}")
                    break
                mapped.add((idx_batch_map[ax], mcls))
            else:
                if mapped:
                    res.deps.setdefault(cls, set()).update(mapped)
                idx_leak |= mapped
        # operand dependence on non-indexed axes maps through offsets
        for cls, d in operand.deps.items():
            if d is DIRTY or res.deps.get(cls) is DIRTY:
                res.deps[cls] = DIRTY
                res.why.setdefault(cls, operand.why.get(cls, ""))
                continue
            for ax, mcls in d:
                if ax == 0:
                    # rows are selected by data: sound only when real
                    # index values stay inside real lanes — the packer
                    # routing invariant
                    if indices.route_class() == mcls:
                        if indices.has_dirty():
                            res.deps[cls] = DIRTY
                            res.why[cls] = ("routing indices are "
                                            "unconfined at "
                                            + src_line(eqn))
                            break
                        res.deps.setdefault(cls, set()).update(
                            idx_leak)
                    elif indices.ident_axis == 0:
                        res.deps.setdefault(cls, set()).add(
                            (idx_batch_map.get(0, 0), mcls))
                    else:
                        res.deps[cls] = DIRTY
                        res.why[cls] = (
                            f"gather selects {mcls}-pad rows with "
                            f"non-routing indices at {src_line(eqn)}")
                        break
                elif ax in op_map:
                    res.deps.setdefault(cls, set()).add(
                        (op_map[ax], mcls))
                else:
                    res.deps[cls] = DIRTY
                    res.why[cls] = (f"gather drops a confined operand "
                                    f"axis at {src_line(eqn)}")
                    break
        for (ax, mcls), v in operand.padv.items():
            if ax in op_map:
                res.padv[(op_map[ax], mcls)] = v
        if operand.const is not None and not indices.deps:
            res.const = operand.const
        return [res.normalize()]

    def _scatter(self, eqn, ins, name) -> list[Abs]:
        operand, indices, updates = ins
        dn = eqn.params["dimension_numbers"]
        if (not dn.scatter_dims_to_operand_dims
                and not dn.inserted_window_dims
                and eqn.invars[1].aval.size == 0
                and name == "scatter"):
            # degenerate full-array overwrite (`.at[:n].set(u)` with
            # n == the padded size, which 128-aligned serve rungs
            # always hit): the result IS the updates
            return [updates.copy()]
        if (tuple(dn.scatter_dims_to_operand_dims) != (0,)
                or tuple(dn.inserted_window_dims) != (0,)
                or getattr(dn, "operand_batching_dims", ())):
            return self._unknown(eqn, ins, "unmodeled scatter shape")
        up_rank = len(eqn.invars[2].aval.shape)
        window = list(dn.update_window_dims)
        batch = [d for d in range(up_rank) if d not in window]
        win_map = {w: 1 + k for k, w in enumerate(window)}
        identity = _SCATTER_IDENTITY.get(name)
        res = Abs()
        ident_updates_regions = {
            (ax, c) for (ax, c), v in updates.padv.items()
            if ax in batch and v == identity}
        if name == "scatter" and indices.ident_axis == 0:
            # .at[:n].set(x): position-identity embed of the updates
            emb_map = {b: 0 for b in batch}
            emb_map.update(win_map)
            for src_abs in (operand, updates):
                amap = (emb_map if src_abs is updates
                        else {i: i for i in range(
                            len(eqn.invars[0].aval.shape))})
                for cls, d in src_abs.deps.items():
                    if d is DIRTY or res.deps.get(cls) is DIRTY:
                        res.deps[cls] = DIRTY
                        res.why.setdefault(cls,
                                           src_abs.why.get(cls, ""))
                        continue
                    for ax, mcls in d:
                        if ax in amap:
                            res.deps.setdefault(cls, set()).add(
                                (amap[ax], mcls))
                        else:
                            res.deps[cls] = DIRTY
                            res.why[cls] = ("scatter embed lost a "
                                            "confined axis at "
                                            + src_line(eqn))
                            break
            for (ax, mcls), v in updates.padv.items():
                if ax in batch:
                    res.padv[(0, mcls)] = v
                elif ax in win_map:
                    res.padv[(win_map[ax], mcls)] = v
            return [res.normalize()]
        if identity is None:
            return self._unknown(
                eqn, ins, "overwrite-scatter with data-dependent "
                          "routing")
        # combining scatter: identity-pinned pad updates are no-ops,
        # so both their values and their (data-dependent) routing die
        for cls, d in updates.deps.items():
            if d is DIRTY or res.deps.get(cls) is DIRTY:
                res.deps[cls] = DIRTY
                res.why.setdefault(cls, updates.why.get(cls, ""))
                continue
            for ax, mcls in d:
                if ax in batch:
                    res.deps[cls] = DIRTY
                    res.why[cls] = (
                        f"`{name}` scatters unmasked {mcls}-pad rows "
                        f"at {src_line(eqn)} — pin pad updates to the "
                        f"combiner identity ({identity}) first")
                    break
                res.deps.setdefault(cls, set()).add(
                    (win_map[ax], mcls))
        idx_member_classes = set()
        for cls, d in indices.deps.items():
            if d is DIRTY or res.deps.get(cls) is DIRTY:
                res.deps[cls] = DIRTY
                res.why.setdefault(cls, indices.why.get(cls, ""))
                continue
            for ax, mcls in d:
                idx_member_classes.add(mcls)
                if (ax, mcls) not in ident_updates_regions:
                    res.deps[cls] = DIRTY
                    res.why[cls] = (
                        f"`{name}` routes non-identity values by "
                        f"{mcls}-padded indices at {src_line(eqn)}")
                    break
        for cls, d in operand.deps.items():
            if d is DIRTY or res.deps.get(cls) is DIRTY:
                res.deps[cls] = DIRTY
                res.why.setdefault(cls, operand.why.get(cls, ""))
            else:
                res.deps.setdefault(cls, set()).update(d)
        # pad slots of the target stay at the operand's constant when
        # real rows route real (packer invariant) and pad rows are
        # identity no-ops
        target = indices.route_class()
        if (target is not None and operand.const is not None
                and all((0, c) in ident_updates_regions
                        or updates.padv.get((0, c)) == identity
                        for c in idx_member_classes)):
            res.padv[(0, target)] = operand.const
        return [res.normalize()]

    def _p_scatter_add(self, eqn, ins):
        return self._scatter(eqn, ins, "scatter-add")

    def _p_scatter_max(self, eqn, ins):
        return self._scatter(eqn, ins, "scatter-max")

    def _p_scatter_min(self, eqn, ins):
        return self._scatter(eqn, ins, "scatter-min")

    def _p_scatter(self, eqn, ins):
        return self._scatter(eqn, ins, "scatter")

    def _p_scatter_mul(self, eqn, ins):
        return self._unknown(eqn, ins, "scatter-mul routing")

    # -- calls / control flow --------------------------------------------

    def _call(self, eqn, ins, closed) -> list[Abs]:
        try:
            return self.eval_closed(closed, ins)
        except ValueError:
            return self._unknown(eqn, ins,
                                 f"call arity mismatch in "
                                 f"`{eqn.primitive.name}`")

    def _p_cond(self, eqn, ins) -> list[Abs]:
        pred, *args = ins
        branches = eqn.params["branches"]
        try:
            branch_outs = [self.eval_closed(b, [a.copy() for a in args])
                           for b in branches]
        except ValueError:
            return self._unknown(eqn, ins, "cond arity mismatch")
        res_list = []
        for outs in zip(*branch_outs):
            res = outs[0].copy()
            for other in outs[1:]:
                d, w = _join_deps([other])
                _merge(res, d, w)
                res.padv = {k: v for k, v in res.padv.items()
                            if other.padv.get(k, other.const) == v}
                if res.const != other.const:
                    res.const = None
                if res.routes != other.routes:
                    res.routes = None
            if pred.deps:
                d, w = _join_deps([pred])
                for cls in d:
                    res.deps[cls] = DIRTY
                    res.why.setdefault(
                        cls, f"branch selected by {cls}-padded data "
                             f"at {src_line(eqn)}")
            res_list.append(res.normalize())
        return res_list

    def _p_while(self, eqn, ins):
        return self._unknown(eqn, ins, "while loop over confined data")

    def _p_scan(self, eqn, ins):
        return self._unknown(eqn, ins, "scan over confined data")

    def _p_pallas_call(self, eqn, ins):
        return self._unknown(
            eqn, ins, "pallas_call boundary (kernel bodies are not "
                      "modeled — docs/LINTS.md)")


def _axis_ok(a: Abs, key, out_aval) -> bool:
    """A padv claim transfers to the output only when the claiming
    operand actually spans that output axis (a size-1 broadcast axis
    holds ONE value for all lanes — its padv key could not exist)."""
    return key[0] < len(out_aval.shape)


def _merge(res: Abs, deps: dict, why: dict) -> None:
    for cls, d in deps.items():
        cur = res.deps.get(cls)
        if d is DIRTY or cur is DIRTY:
            res.deps[cls] = DIRTY
            res.why.setdefault(cls, why.get(cls, ""))
        else:
            res.deps.setdefault(cls, set()).update(d)


def _reshape_axis_map(in_shape, out_shape) -> dict:
    """in-axis -> out-axis where the axis keeps its row-major digit
    (equal size and equal suffix product) — lane positions along it
    are preserved exactly."""

    def suffix(shape, i):
        p = 1
        for d in shape[i + 1:]:
            p *= d
        return p

    amap = {}
    for i in range(len(in_shape)):
        for j in range(len(out_shape)):
            if (in_shape[i] == out_shape[j]
                    and suffix(in_shape, i) == suffix(out_shape, j)):
                amap[i] = j
                break
    return amap


def _remap(src: Abs, res: Abs, amap: dict, lost: str = "") -> None:
    for cls, d in src.deps.items():
        if d is DIRTY:
            res.deps[cls] = DIRTY
            res.why[cls] = src.why.get(cls, "")
            continue
        for ax, mcls in d:
            if ax in amap:
                res.deps.setdefault(cls, set()).add((amap[ax], mcls))
            else:
                res.deps[cls] = DIRTY
                res.why[cls] = lost or "confined axis dropped"
                break
    for (ax, mcls), v in src.padv.items():
        if ax in amap:
            res.padv[(amap[ax], mcls)] = v
    res.normalize()


def seed_inputs(spec) -> list[Abs]:
    """Input Abs values from the program's declared invar roles."""
    seeds = []
    for role in spec.invar_roles:
        if role.kind == "param":
            seeds.append(_clean())
        elif role.kind == "mask":
            seeds.append(Abs(padv={(0, role.cls): False}))
        elif role.kind == "route":
            seeds.append(Abs(deps={role.cls: {(0, role.cls)}},
                             routes=role.target))
        else:  # data
            seeds.append(Abs(deps={role.cls: {(0, role.cls)}}))
    return seeds


def audit_program(spec) -> list[Violation]:
    interp = _Interp(spec)
    try:
        outs = interp.eval_closed(spec.jaxpr, seed_inputs(spec))
    except RecursionError:
        return [Violation(rule=RULE, path=spec.name, line=0,
                          message="interpreter recursion limit — "
                                  "program too deeply nested to prove",
                          key="interp-recursion")]
    found = []
    for oi, a in enumerate(outs):
        for cls in sorted(a.deps):
            d = a.deps[cls]
            if d is DIRTY:
                why = a.why.get(cls, "unproven dataflow")
                found.append(Violation(
                    rule=RULE, path=spec.name, line=0,
                    message=(f"output {oi} may depend on {cls}-padded "
                             f"input lanes: {why}"),
                    key=f"{cls}-pad@out{oi}"))
                continue
            leaked = sorted({mcls for _ax, mcls in d
                             if mcls not in spec.out_discard})
            if leaked:
                found.append(Violation(
                    rule=RULE, path=spec.name, line=0,
                    message=(f"output {oi} carries {cls}-padded data "
                             f"in {', '.join(leaked)}-pad lanes, which "
                             f"the caller does NOT discard (discarded: "
                             f"{sorted(spec.out_discard) or 'none'})"),
                    key=f"{cls}-pad-leak@out{oi}"))
    return found


def run(programs) -> list[Violation]:
    # the audit subject is any program that declares invar roles — the
    # serve/lens matrix, and the scan-free SAR bucket body (which must
    # prove its accumulated sums clean rather than rely on a caller
    # discarding pad lanes)
    out = []
    for spec in programs:
        if spec.invar_roles is None:
            continue
        out.extend(audit_program(spec))
    return out
