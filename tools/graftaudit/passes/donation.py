"""graftaudit pass — donation: train-step state buffers must be
donated, checked on the LOWERED program (the IR where donation is
ground truth: the lowering's per-argument aliasing table, surfaced as
``Lowered.args_info``, is what becomes ``tf.aliasing_output`` in the
StableHLO module).

Every ``make_train_*`` jits with ``donate_argnums=0`` so each step
updates the state in place instead of holding old+new copies — at real
scale that is the difference between fitting in HBM and not. The flag
is one refactor away from silently vanishing (a wrapper that re-jits,
a new step maker that forgets it), and nothing fails when it does: the
program is still correct, just 2x the state footprint. This pass reads
the lowered aliasing table and reports when the undonated share of the
state exceeds a threshold — a handful of scalar counters legitimately
stay undonated (XLA refuses to alias buffers it repacks), but
params/opt_state must alias through.
"""

from __future__ import annotations

from tools.graftaudit._ir import aval_bytes
from tools.graftlint.driver import Violation

RULE = "donation"

# undonated state bytes above this fail the audit. The toy programs'
# whole state is tens of KiB, so a dropped donate_argnums blows far
# past it while XLA's refusal to alias a couple of odd scalars stays
# under.
THRESHOLD_BYTES = 4096


def donated_flags(lowered) -> list | None:
    """Per-flat-argument (donated, aval) from the lowering's aliasing
    table, aligned with the traced program's flat inputs."""
    import jax

    info = getattr(lowered, "args_info", None)
    if info is None:
        return None
    leaves = jax.tree.leaves(info,
                             is_leaf=lambda x: hasattr(x, "donated"))
    if not all(hasattr(a, "donated") for a in leaves):
        return None
    return [(bool(a.donated), getattr(a, "aval", None) or a._aval)
            for a in leaves]


def run(programs) -> list[Violation]:
    found: list[Violation] = []
    for spec in programs:
        if not spec.expect_donated_state:
            continue
        lowered = spec.lowered_text()
        flags = donated_flags(lowered) if lowered is not None else None
        if flags is None or len(flags) < spec.state_flat_count:
            found.append(Violation(
                rule=RULE, path=spec.name, line=0,
                message=(f"cannot read the lowering's aliasing table "
                         f"for {spec.state_flat_count} state inputs — "
                         f"the donation check needs Lowered.args_info"),
                key="unreadable-aliasing-table"))
            continue
        undonated = [(spec.state_paths[i] if i < len(spec.state_paths)
                      else f"state[{i}]", aval_bytes(flags[i][1]))
                     for i in range(spec.state_flat_count)
                     if not flags[i][0]]
        total = sum(b for _p, b in undonated)
        if total >= THRESHOLD_BYTES:
            worst = sorted(undonated, key=lambda x: -x[1])[:5]
            listing = ", ".join(f"{p} ({b}B)" for p, b in worst)
            found.append(Violation(
                rule=RULE, path=spec.name, line=0,
                message=(f"{total} bytes of train state are NOT "
                         f"donated ({len(undonated)} of "
                         f"{spec.state_flat_count} leaves; worst: "
                         f"{listing}) — the step should alias its "
                         f"state in place (donate_argnums=0 in "
                         f"train/loop.py make_train_*)"),
                key="undonated-state"))
    return found
