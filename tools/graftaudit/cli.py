"""graftaudit CLI.

    python -m tools.graftaudit [PASS ...] [options]

Options:
    --json             machine-readable result (one JSON object)
    --baseline PATH    baseline file (default tools/graftaudit/
                       baseline.json when it exists)
    --no-baseline      ignore any baseline
    --write-baseline   accept today's findings into the baseline file
                       and exit 0 (the file is in-tree and reviewable;
                       prefer FIXING findings — docs/LINTS.md)
    --programs GLOB    audit only programs matching the glob (e.g.
                       'serve/int8/*')
    --list             list passes and exit

Exit codes: 0 clean (or all findings baselined), 1 new violations,
2 usage / internal error — graftlint's contract, which
tests/test_graftaudit.py pins in tier-1 and bench.py --gate refuses
captures on.

The audit builds and traces the stack's real programs, so it needs
the repo's package importable (editable install or repo-root cwd) and
forces the CPU backend with 8 virtual devices when it owns the jax
import (tools/graftaudit/programs.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: list[str] | None = None) -> int:
    from tools.graftaudit.passes import get_passes, registry

    p = argparse.ArgumentParser(
        prog="graftaudit",
        description="jaxpr/StableHLO-level auditor for the stack's "
                    "real compiled programs (docs/LINTS.md)")
    p.add_argument("passes", nargs="*",
                   help="pass names to run (default: all); "
                        f"canonical: {', '.join(registry())}")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--baseline", default=None)
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--programs", default=None, metavar="GLOB")
    p.add_argument("--list", action="store_true")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    if args.list:
        for name, mod in registry().items():
            doc = next(iter((mod.__doc__ or "").strip().splitlines()),
                       "")
            print(f"{name:18s} {doc}")
        return 0

    try:
        get_passes(args.passes or None)
    except KeyError as e:
        print(f"graftaudit: {e.args[0]}", file=sys.stderr)
        return 2

    from tools.graftaudit import driver
    from tools.graftaudit.programs import force_cpu_env

    baseline_path = "" if args.no_baseline else args.baseline
    if (baseline_path and not args.write_baseline
            and not os.path.exists(baseline_path)):
        # same contract as graftlint: a typo'd explicit baseline path
        # must not silently resurface (or fork) accepted debt
        print(f"graftaudit: baseline file not found: {baseline_path} "
              f"(--write-baseline creates one; --no-baseline ignores "
              f"baselines)", file=sys.stderr)
        return 2
    force_cpu_env()
    try:
        result = driver.run_repo(args.passes or None,
                                 baseline_path=baseline_path,
                                 program_glob=args.programs)
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        print(f"graftaudit: unreadable baseline file "
              f"({type(e).__name__}: {e})", file=sys.stderr)
        return 2

    if args.write_baseline:
        if args.programs:
            # writing from a program subset would drop every OTHER
            # program's accepted entries (graftlint's --changed-only
            # guard, applied to the analogous combination here)
            print("graftaudit: --write-baseline over a --programs "
                  "subset would drop every other program's accepted "
                  "entries — write from a full run", file=sys.stderr)
            return 2
        path = args.baseline or driver.DEFAULT_BASELINE
        fresh = result.new + result.baselined
        driver.write_baseline(path, fresh)
        print(f"graftaudit: wrote {len(fresh)} baseline entr(ies) to "
              f"{path}")
        return 0

    if args.as_json:
        print(json.dumps(result.as_dict()))
    else:
        for v in result.new:
            print(v)
        tail = (f"{len(result.new)} violation(s) over "
                f"{len(result.programs)} program(s)"
                + (f", {len(result.baselined)} baselined"
                   if result.baselined else "")
                + (f", {len(result.allowed)} allowlisted"
                   if result.allowed else "")
                + f" [{', '.join(result.passes)};"
                  f" {result.elapsed_s:.2f}s]")
        print(tail, file=sys.stderr)
    return 1 if result.new else 0


if __name__ == "__main__":
    raise SystemExit(main())
