"""Shared jaxpr utilities for the graftaudit passes: recursive eqn
walking across every call-like primitive, a mark-and-sweep DCE (traced
jaxprs keep dead eqns — e.g. the serve program's unused local head —
and a pass must not report on code XLA will delete), and source-line
extraction so IR findings point back at pertgnn_tpu source."""

from __future__ import annotations

from typing import Iterator

# Primitives whose body the eqn walk does NOT descend into by default:
# Pallas kernel bodies are audited at the call boundary (docs/LINTS.md
# "known limits") — their internal f32 accumulators and device-side
# debug prints are kernel implementation details, not program contract.
KERNEL_BOUNDARY = frozenset({"pallas_call"})


def sub_jaxprs(params: dict):
    """Every jaxpr nested in an eqn's params — handles the bare Jaxpr,
    ClosedJaxpr, and tuple-of-branches (cond) spellings."""
    for v in params.values():
        for j in _as_jaxprs(v):
            yield j


def _as_jaxprs(v):
    if hasattr(v, "eqns"):
        return [v]
    if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
        return [v.jaxpr]
    if isinstance(v, (tuple, list)):
        out = []
        for w in v:
            out.extend(_as_jaxprs(w))
        return out
    return []


def walk_eqns(jaxpr, *, into_kernels: bool = False) -> Iterator:
    """Depth-first over every eqn, descending through pjit / cond /
    scan / custom_* / shard_map bodies. `jaxpr` may be a ClosedJaxpr."""
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in jx.eqns:
        yield eqn
        if eqn.primitive.name in KERNEL_BOUNDARY and not into_kernels:
            continue
        for sub in sub_jaxprs(eqn.params):
            yield from walk_eqns(sub, into_kernels=into_kernels)


def dce(jaxpr):
    """Live eqns of a (Closed)Jaxpr in original order — reverse sweep
    from the outvars, keeping effectful eqns. Top level only: a live
    call eqn keeps its whole body (the walk descends into it)."""
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    live = {v for v in jx.outvars
            if not _is_drop(v) and not hasattr(v, "val")}
    keep = []
    for eqn in reversed(jx.eqns):
        if (getattr(eqn, "effects", None)
                or any(v in live for v in eqn.outvars)):
            keep.append(eqn)
            live.update(v for v in eqn.invars
                        if not hasattr(v, "val"))  # skip Literals
    keep.reverse()
    return keep


def _is_drop(v) -> bool:
    return type(v).__name__ == "DropVar"


def src_line(eqn, repo_hint: str = "pertgnn_tpu") -> str:
    """"path:line" of the innermost user frame that produced this eqn
    (first frame whose filename mentions `repo_hint`), or "<ir>" when
    the traceback carries no user frame — diagnostics only, never
    load-bearing."""
    tb = getattr(getattr(eqn, "source_info", None), "traceback", None)
    if tb is None:
        return "<ir>"
    try:
        frames = list(tb.frames)
    except AttributeError:
        return "<ir>"
    for fr in frames:
        fname = getattr(fr, "file_name", "") or ""
        if repo_hint in fname:
            short = fname[fname.index(repo_hint):]
            return f"{short}:{getattr(fr, 'start_line', 0)}"
    for fr in frames:
        fname = getattr(fr, "file_name", "") or ""
        if "site-packages" not in fname and fname:
            return f"{fname.rsplit('/', 1)[-1]}:{getattr(fr, 'start_line', 0)}"
    return "<ir>"


def aval_bytes(aval) -> int:
    size = 1
    for d in getattr(aval, "shape", ()):
        size *= int(d)
    return size * getattr(getattr(aval, "dtype", None), "itemsize", 4)
