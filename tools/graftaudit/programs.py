"""Enumerate the stack's REAL compiled programs at a toy config on CPU.

The audit's subject is not synthetic example code — it is the programs
the stack actually dispatches: one serve step per ladder rung, per
``serve_dtype`` tier, per ``attention_impl`` (serve/engine.py builds
them exactly this way), the train/eval/init programs fit() runs
(train/loop.py's own makers), and the sharded variants from parallel/.
Everything here TRACES (jaxpr) and at most LOWERS (StableHLO, for the
donation pass) — nothing is XLA-compiled, which is what keeps a full
repo-wide audit inside its tier-1 budget on CPU.

Program names are stable audit identities (baseline / allowlist keys):

    serve/<dtype>/<impl>/rung<i>_g<G>n<N>e<E>
    train/<step|chunk>_<packed|compact>       eval/...
    init/model_init
    sharded/train_step_dp   sharded/train_step_edge_shard

The per-invar role table drives the padding-taint seed: which flat
inputs are padded lane data, which are the masks, which are routing
index arrays (senders/receivers/node_graph). The routing arrays' "real
lanes index only real lanes" property is a PACKER invariant the
analysis assumes — it is asserted dynamically by
tests/test_serve.py::test_matches_epoch_packer_invariants and the
packing property tests, and documented in docs/LINTS.md.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import sys
from typing import Any, Callable

log = logging.getLogger(__name__)

# (lane class, kind[, routing target]) per PackedBatch field — the
# padding-taint seed. kind: "data" = pad lanes hold padded values;
# "mask" = boolean, False on every pad lane; "route" = data whose
# real-lane values are valid REAL indices of the target class.
BATCH_ROLES = {
    "x": ("node", "data"), "ms_id": ("node", "data"),
    "node_depth": ("node", "data"),
    "node_graph": ("node", "route", "graph"),
    "node_mask": ("node", "mask"),
    "pattern_prob": ("node", "data"), "pattern_size": ("node", "data"),
    "senders": ("edge", "route", "node"),
    "receivers": ("edge", "route", "node"),
    "edge_iface": ("edge", "data"), "edge_rpctype": ("edge", "data"),
    "edge_duration": ("edge", "data"), "edge_mask": ("edge", "mask"),
    "entry_id": ("graph", "data"), "y": ("graph", "data"),
    "graph_mask": ("graph", "mask"),
}


@dataclasses.dataclass
class Role:
    kind: str                # "param" | "data" | "mask" | "route"
    cls: str | None = None   # lane class: "node" | "edge" | "graph"
    target: str | None = None  # routing target class (kind == "route")
    path: str = ""


@dataclasses.dataclass
class ProgramSpec:
    """One traced program plus the contract metadata the passes need."""

    name: str
    tags: frozenset            # subset of {"serve","train","eval","init",
    #                            "sharded"} + dtype + impl tags
    jaxpr: Any                 # ClosedJaxpr
    invar_roles: list | None = None   # aligned with jaxpr.jaxpr.invars
    # output contract: classes whose output pad lanes the caller
    # discards (the serve engine slices [:g] — graph-pad lanes of the
    # prediction vector never reach a caller)
    out_discard: frozenset = frozenset()
    mesh_axes: tuple | None = None
    # donation contract: the first `state_flat_count` flat invars are
    # the train state and must be donated (checked on the StableHLO)
    expect_donated_state: bool = False
    state_flat_count: int = 0
    state_paths: tuple = ()
    lower: Callable | None = None     # () -> jax.stages.Lowered (lazy)

    def lowered_text(self):
        if self.lower is None:
            return None
        if not hasattr(self, "_lowered"):
            self._lowered = self.lower()
        return self._lowered


def force_cpu_env() -> None:
    """Point an un-imported jax at CPU with enough fake devices for the
    sharded programs — same recipe as tests/conftest.py. A no-op when
    jax is already imported (the importer owns the platform then)."""
    if "jax" in sys.modules:
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _toy_config():
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, ServeConfig, TrainConfig)

    return Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=60, batch_size=4),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(label_scale=1000.0),
        serve=ServeConfig(max_graphs_per_batch=4),
        graph_type="pert",
    )


_CACHE: dict = {}


def _toy_stack():
    """(dataset, cfg, model, state) shared by every program build —
    cached per process (tier-1 and the bench gate both audit once)."""
    if "stack" in _CACHE:
        return _CACHE["stack"]
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.train.loop import restore_target_state

    cfg = _toy_config()
    synth = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=12, num_entries=2, patterns_per_entry=2,
        traces_per_entry=12, seed=7))
    pre = preprocess(synth.spans, synth.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    model, state = restore_target_state(ds, cfg)
    _CACHE["stack"] = (ds, cfg, model, state)
    return _CACHE["stack"]


def _serve_roles(variables_abs, n_feat: int) -> list:
    import jax

    roles = [Role(kind="param", path="variables")
             for _ in jax.tree.leaves(variables_abs)]
    from pertgnn_tpu.batching.pack import PackedBatch

    for field in PackedBatch._fields:
        spec = BATCH_ROLES[field]
        roles.append(Role(kind=spec[1], cls=spec[0],
                          target=spec[2] if len(spec) > 2 else None,
                          path=f"batch.{field}"))
    return roles


def _serve_specs(ds, cfg, state, out: list, errors: list) -> None:
    import jax

    from pertgnn_tpu.batching.pack import BatchBudget
    from pertgnn_tpu.config import ATTENTION_IMPLS, SERVE_DTYPES
    from pertgnn_tpu.serve.engine import InferenceEngine, abstract_batch

    # a widened budget gives the toy ladder >= 2 rungs, so the audit
    # exercises the rung enumeration, not just a single shape
    budget = BatchBudget(max_graphs=cfg.serve.max_graphs_per_batch,
                         max_nodes=max(ds.budget.max_nodes, 256),
                         max_edges=max(ds.budget.max_edges, 256))
    for dtype in SERVE_DTYPES:
        for impl in ATTENTION_IMPLS:
            name_prefix = f"serve/{dtype}/{impl}"
            try:
                c = dataclasses.replace(
                    cfg,
                    serve=dataclasses.replace(cfg.serve,
                                              serve_dtype=dtype),
                    model=dataclasses.replace(cfg.model,
                                              attention_impl=impl))
                model_cfg = c.model
                if dtype in ("bf16", "int8"):
                    model_cfg = dataclasses.replace(
                        c.model, bf16_activations=True)
                from pertgnn_tpu.models.pert_model import make_model

                model = make_model(model_cfg, ds.num_ms, ds.num_entries,
                                   ds.num_interfaces, ds.num_rpctypes)
                eng = InferenceEngine(model, state, c, ds.mixtures,
                                      ds.lookup, budget)
                var_abs = jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    eng._variables)
                roles = _serve_roles(var_abs, eng._n_feat)
                for i, rung in enumerate(eng.ladder):
                    abs_args = (var_abs,
                                abstract_batch(rung, eng._n_feat))
                    traced = jax.jit(eng._step).trace(*abs_args)
                    out.append(ProgramSpec(
                        name=(f"{name_prefix}/rung{i}_g{rung.max_graphs}"
                              f"n{rung.max_nodes}e{rung.max_edges}"),
                        tags=frozenset({"serve", dtype, impl}),
                        jaxpr=traced.jaxpr,
                        invar_roles=roles,
                        out_discard=frozenset({"graph"})))
            except Exception as e:  # noqa: BLE001 — a variant that no
                # longer traces is itself an audit finding, never a skip
                log.exception("graftaudit: building %s failed",
                              name_prefix)
                errors.append((name_prefix,
                               f"{type(e).__name__}: {e}"))


def _train_specs(ds, cfg, model, state, out: list, errors: list) -> None:
    from pertgnn_tpu.train.loop import (_abstract_tree,
                                        _resolve_device_materialize,
                                        _train_eval_abstract,
                                        make_eval_chunk,
                                        make_eval_chunk_compact,
                                        make_eval_step,
                                        make_eval_step_compact,
                                        make_train_chunk,
                                        make_train_chunk_compact,
                                        make_train_step,
                                        make_train_step_compact, make_tx)
    import jax

    tx = make_tx(cfg)
    chunked = cfg.train.scan_chunk > 1
    suffix = "chunk" if chunked else "step"
    for compact in (False, True):
        if compact and not _resolve_device_materialize(ds, cfg):
            continue
        kind = "compact" if compact else "packed"
        try:
            if compact:
                dev = ds.device_arenas()
                mn, me = ds.budget.max_nodes, ds.budget.max_edges
                train_jit = (make_train_chunk_compact(model, cfg, tx, dev,
                                                      mn, me) if chunked
                             else make_train_step_compact(model, cfg, tx,
                                                          dev, mn, me))
                eval_jit = (make_eval_chunk_compact(model, cfg, dev, mn,
                                                    me) if chunked
                            else make_eval_step_compact(model, cfg, dev,
                                                        mn, me))
            else:
                train_jit = (make_train_chunk(model, cfg, tx) if chunked
                             else make_train_step(model, cfg, tx))
                eval_jit = (make_eval_chunk(model, cfg) if chunked
                            else make_eval_step(model, cfg))
            abs_args = _train_eval_abstract(ds, cfg, state, compact)
            state_leaves = jax.tree_util.tree_flatten_with_path(
                abs_args[0])[0]
            n_state = len(state_leaves)
            paths = tuple(jax.tree_util.keystr(p)
                          for p, _ in state_leaves)
            for tag, jit_fn, donated in (("train", train_jit, True),
                                         ("eval", eval_jit, False)):
                traced = jit_fn.trace(*abs_args)
                out.append(ProgramSpec(
                    name=f"{tag}/{suffix}_{kind}",
                    tags=frozenset({tag}),
                    jaxpr=traced.jaxpr,
                    expect_donated_state=donated,
                    state_flat_count=n_state,
                    state_paths=paths,
                    lower=(lambda t=traced: t.lower())
                    if donated else None))
        except Exception as e:  # noqa: BLE001 — see _serve_specs
            log.exception("graftaudit: building train/%s failed", kind)
            errors.append((f"train/{suffix}_{kind}",
                           f"{type(e).__name__}: {e}"))


def _init_spec(ds, cfg, model, state, out: list, errors: list) -> None:
    import jax

    from pertgnn_tpu.train.loop import (_abstract_tree, _jitted_model_init,
                                        _train_sample)

    try:
        init_jit = _jitted_model_init(model)
        sample = _train_sample(ds)
        rng = jax.random.PRNGKey(cfg.train.seed)
        traced = init_jit.trace(_abstract_tree(rng),
                                _abstract_tree(sample))
        out.append(ProgramSpec(name="init/model_init",
                               tags=frozenset({"init"}),
                               jaxpr=traced.jaxpr))
    except Exception as e:  # noqa: BLE001 — see _serve_specs
        log.exception("graftaudit: building init/model_init failed")
        errors.append(("init/model_init", f"{type(e).__name__}: {e}"))


def _sharded_specs(ds, cfg, model, state, out: list,
                   errors: list) -> None:
    import jax

    if len(jax.devices()) < 2:
        errors.append(("sharded",
                       "fewer than 2 devices — cannot trace the sharded "
                       "programs (run under the CPU test platform: "
                       "XLA_FLAGS=--xla_force_host_platform_device_count"
                       "=8 before jax import)"))
        return
    from pertgnn_tpu.parallel import data_parallel as dp
    from pertgnn_tpu.parallel.mesh import make_mesh
    from pertgnn_tpu.train.loop import _abstract_tree, make_tx

    tx = make_tx(cfg)
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    axes = tuple(str(a) for a in mesh.axis_names)
    try:
        sstep, sstate = dp.make_sharded_train_step(model, cfg, tx, mesh,
                                                   state)
        gb = next(dp.grouped_batches(ds.batches("train"), 2))
        traced = sstep.trace(_abstract_tree(sstate), _abstract_tree(gb))
        n_state = len(jax.tree.leaves(sstate))
        out.append(ProgramSpec(
            name="sharded/train_step_dp",
            tags=frozenset({"train", "sharded"}),
            jaxpr=traced.jaxpr, mesh_axes=axes,
            expect_donated_state=True, state_flat_count=n_state,
            state_paths=tuple(
                jax.tree_util.keystr(p) for p, _ in
                jax.tree_util.tree_flatten_with_path(sstate)[0]),
            lower=lambda t=traced: t.lower()))
    except Exception as e:  # noqa: BLE001 — see _serve_specs
        log.exception("graftaudit: building sharded/train_step_dp failed")
        errors.append(("sharded/train_step_dp",
                       f"{type(e).__name__}: {e}"))
    try:
        from pertgnn_tpu.models.pert_model import make_model

        es_model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                              ds.num_interfaces, ds.num_rpctypes,
                              edge_shard_mesh=mesh)
        estep, estate = dp.make_edge_sharded_train_step(
            es_model, cfg, tx, mesh, state)
        b = next(ds.batches("train"))
        traced = estep.trace(_abstract_tree(estate), _abstract_tree(b))
        out.append(ProgramSpec(
            name="sharded/train_step_edge_shard",
            tags=frozenset({"train", "sharded"}),
            jaxpr=traced.jaxpr, mesh_axes=axes))
    except Exception as e:  # noqa: BLE001 — see _serve_specs
        log.exception(
            "graftaudit: building sharded/train_step_edge_shard failed")
        errors.append(("sharded/train_step_edge_shard",
                       f"{type(e).__name__}: {e}"))


def _scale_specs(ds, cfg, model, state, out: list, errors: list) -> None:
    """The giant-corpus scale-out programs (parallel/scale.py, ISSUE 18)
    as first-class audit subjects:

    - ``scale/allreduce_{sum,min}`` — the collective statistics rounds
      the sharded merge runs (collective-audit: the only axis name used
      is a mesh axis);
    - ``scale/sar_step_packed`` — the full bucket-scanned accumulated
      train step, declared UNsharded (collective-audit proves the
      single-host SAR path traps no stray collective that would
      deadlock on a mesh) and donation-checked like every train step;
    - ``scale/sar_bucket_terms`` — the scan-free per-bucket body the
      SAR step scans, with full invar roles: the padding-taint pass
      proves a zero-masked padding bucket cannot leak into the
      accumulated loss sums, batch statistics, or metric sums (the
      scan itself is beyond the taint interpreter, but every scan
      iteration IS this program — same factored function object).
    """
    import jax

    from pertgnn_tpu.parallel.scale import (allreduce_fn,
                                            make_sar_train_step,
                                            sar_bucket_terms_fn)
    from pertgnn_tpu.train.loop import _train_eval_abstract, make_tx

    tx = make_tx(cfg)
    abs_state, abs_batch = _train_eval_abstract(ds, cfg, state,
                                                compact=False,
                                                plain_step=True)
    if len(jax.devices()) >= 2:
        from pertgnn_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
        axes = tuple(str(a) for a in mesh.axis_names)
        for op in ("sum", "min"):
            try:
                traced = jax.jit(allreduce_fn(mesh, op)).trace(
                    jax.ShapeDtypeStruct((2, 16), jax.numpy.int32))
                out.append(ProgramSpec(
                    name=f"scale/allreduce_{op}",
                    tags=frozenset({"sharded", "scale"}),
                    jaxpr=traced.jaxpr, mesh_axes=axes))
            except Exception as e:  # noqa: BLE001 — see _serve_specs
                log.exception("graftaudit: building scale/allreduce_%s "
                              "failed", op)
                errors.append((f"scale/allreduce_{op}",
                               f"{type(e).__name__}: {e}"))
    else:
        errors.append(("scale/allreduce",
                       "fewer than 2 devices — cannot trace the merge "
                       "collectives (see the sharded/ error recipe)"))
    try:
        step = make_sar_train_step(model, cfg, tx, remat=True)
        stacked = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct((2,) + s.shape, s.dtype),
            abs_batch)
        traced = step.trace(abs_state, stacked)
        state_leaves = jax.tree_util.tree_flatten_with_path(
            abs_state)[0]
        out.append(ProgramSpec(
            name="scale/sar_step_packed",
            tags=frozenset({"train", "scale"}),
            jaxpr=traced.jaxpr,
            expect_donated_state=True,
            state_flat_count=len(state_leaves),
            state_paths=tuple(jax.tree_util.keystr(p)
                              for p, _ in state_leaves),
            lower=lambda t=traced: t.lower()))
    except Exception as e:  # noqa: BLE001 — see _serve_specs
        log.exception("graftaudit: building scale/sar_step_packed "
                      "failed")
        errors.append(("scale/sar_step_packed",
                       f"{type(e).__name__}: {e}"))
    try:
        terms = sar_bucket_terms_fn(model, cfg)
        params_abs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (state.params, state.batch_stats))

        def bucket_terms(params, stats, b):
            # dropout is 0 at the toy config — no rng invar to role
            return terms(params, stats, b, None)

        traced = jax.jit(bucket_terms).trace(params_abs[0],
                                             params_abs[1], abs_batch)
        out.append(ProgramSpec(
            name="scale/sar_bucket_terms",
            tags=frozenset({"train", "scale"}),
            jaxpr=traced.jaxpr,
            invar_roles=_serve_roles(params_abs, 0),
            # every output (loss sums, new batch stats, metric sums)
            # must be PROVABLY clean — nothing is discarded downstream:
            # the scan carries all of it into the epoch gradient
            out_discard=frozenset()))
    except Exception as e:  # noqa: BLE001 — see _serve_specs
        log.exception("graftaudit: building scale/sar_bucket_terms "
                      "failed")
        errors.append(("scale/sar_bucket_terms",
                       f"{type(e).__name__}: {e}"))


def _toy_window_dataset():
    """A window dataset built through the REAL stream path (base +
    delta shards, vocab-stable ingest, mixture merge, sliding window) —
    the continual fine-tune program's audit subject must be constructed
    the way stream/continual.py constructs it, not simulated."""
    if "window_ds" in _CACHE:
        return _CACHE["window_ds"]
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.assemble import assemble
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.stream import (base_shard, ingest_delta, merge_shards,
                                    shard_frames_by_window, window_dataset)

    cfg = _toy_config()
    span = 6 * 60 * 1000
    synth = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=12, num_entries=2, patterns_per_entry=2,
        traces_per_entry=16, seed=7, time_span_ms=span,
        missing_resource_frac=0.0,
        ensure_pattern_coverage_before_ms=span // 2))
    shards = shard_frames_by_window(synth.spans, synth.resources,
                                    [span // 2])
    pre0 = preprocess(shards[0][0], shards[0][1], cfg.ingest)
    table0 = assemble(pre0, cfg.ingest)
    base_ds = build_dataset(pre0, cfg, table0)
    base = base_shard(pre0, table0, cfg.graph_type, cfg.ingest)
    delta = ingest_delta(shards[1][0], shards[1][1], base,
                         cfg.graph_type, cfg.ingest)
    merged, info = merge_shards(base, [delta], cfg)
    win = window_dataset(merged, info.window_split(1),
                         {"valid": base_ds.splits["valid"],
                          "test": base_ds.splits["test"]})
    _CACHE["window_ds"] = (win, cfg)
    return _CACHE["window_ds"]


def _continual_spec(out: list, errors: list) -> None:
    """The warm-restart fine-tune program (stream/continual.py), traced
    through the continual module's own construction path so the
    donation / dtype-flow / host-interop / collective passes cover the
    continual-training program as a first-class subject."""
    import jax

    from pertgnn_tpu.train.loop import _train_eval_abstract

    try:
        from pertgnn_tpu.stream import finetune_programs

        win_ds, cfg = _toy_window_dataset()
        _model, state, train_jit, _eval_jit, compact = finetune_programs(
            win_ds, cfg)
        abs_args = _train_eval_abstract(win_ds, cfg, state, compact)
        state_leaves = jax.tree_util.tree_flatten_with_path(
            abs_args[0])[0]
        suffix = "chunk" if cfg.train.scan_chunk > 1 else "step"
        kind = "compact" if compact else "packed"
        traced = train_jit.trace(*abs_args)
        out.append(ProgramSpec(
            name=f"continual/finetune_{suffix}_{kind}",
            tags=frozenset({"train", "continual"}),
            jaxpr=traced.jaxpr,
            expect_donated_state=True,
            state_flat_count=len(state_leaves),
            state_paths=tuple(jax.tree_util.keystr(p)
                              for p, _ in state_leaves),
            lower=lambda t=traced: t.lower()))
    except Exception as e:  # noqa: BLE001 — see _serve_specs
        log.exception("graftaudit: building continual/finetune failed")
        errors.append(("continual/finetune",
                       f"{type(e).__name__}: {e}"))


def _lens_specs(ds, cfg, state, out: list, errors: list) -> None:
    """The lens serving programs (pertgnn_tpu/lens/, ISSUE 15) as
    first-class audit subjects: (a) the MULTI-QUANTILE step — the
    non-crossing head widens the output to (G, T), and graph-pad lanes
    of every column must stay discarded; (b) the LOCAL-pred-returning
    (attribution) step — its second output keeps NODE lanes, so the
    padding-taint pass must prove the in-graph -inf pin on pad rows
    (the 'padded rows provably unrankable' claim, statically). Both
    trace through the engine's OWN step construction, exactly like the
    standard serve matrix."""
    import jax

    from pertgnn_tpu.batching.pack import BatchBudget
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.serve.engine import InferenceEngine, abstract_batch

    budget = BatchBudget(max_graphs=cfg.serve.max_graphs_per_batch,
                         max_nodes=max(ds.budget.max_nodes, 256),
                         max_edges=max(ds.budget.max_edges, 256))
    variants = (
        ("lens/quantile", dataclasses.replace(
            cfg, model=dataclasses.replace(
                cfg.model, quantile_taus=(0.5, 0.95, 0.99))), False),
        ("lens/local", dataclasses.replace(
            cfg, model=dataclasses.replace(
                cfg.model, local_loss_weight=0.1)), True),
    )
    for name_prefix, c, local in variants:
        try:
            model = make_model(c.model, ds.num_ms, ds.num_entries,
                               ds.num_interfaces, ds.num_rpctypes)
            var_state = state
            if not local:
                # the multi-quantile head widens global_head2: the toy
                # single-tau state's tree no longer fits — init a fresh
                # one through the restore-target path (cheap at toy
                # scale; shapes are all the audit consumes)
                from pertgnn_tpu.train.loop import restore_target_state

                _m, var_state = restore_target_state(ds, c)
            eng = InferenceEngine(model, var_state, c, ds.mixtures,
                                  ds.lookup, budget)
            var_abs = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                eng._variables)
            roles = _serve_roles(var_abs, eng._n_feat)
            step = eng._step_local if local else eng._step
            for i, rung in enumerate(eng.ladder):
                abs_args = (var_abs, abstract_batch(rung, eng._n_feat))
                traced = jax.jit(step).trace(*abs_args)
                out.append(ProgramSpec(
                    name=(f"{name_prefix}/rung{i}_g{rung.max_graphs}"
                          f"n{rung.max_nodes}e{rung.max_edges}"),
                    tags=frozenset({"serve", "lens", "f32", "segment",
                                    "local" if local else "quantile"}),
                    jaxpr=traced.jaxpr,
                    invar_roles=roles,
                    # the caller discards graph-pad prediction lanes
                    # ([:g] slice); the local output's NODE lanes are
                    # KEPT — the -inf pin is what must make them clean
                    out_discard=frozenset({"graph"})))
        except Exception as e:  # noqa: BLE001 — see _serve_specs
            log.exception("graftaudit: building %s failed", name_prefix)
            errors.append((name_prefix, f"{type(e).__name__}: {e}"))


def build_programs() -> tuple[list[ProgramSpec], list[tuple[str, str]]]:
    """(specs, build_errors). Build errors are audit findings (rule
    "driver"), not skips — a program variant that stopped tracing is
    exactly the kind of rot the audit exists to catch. Cached per
    process; the underlying toy dataset/model are shared."""
    if "programs" in _CACHE:
        return _CACHE["programs"]
    force_cpu_env()
    ds, cfg, model, state = _toy_stack()
    specs: list[ProgramSpec] = []
    errors: list[tuple[str, str]] = []
    _serve_specs(ds, cfg, state, specs, errors)
    _lens_specs(ds, cfg, state, specs, errors)
    _train_specs(ds, cfg, model, state, specs, errors)
    _init_spec(ds, cfg, model, state, specs, errors)
    _sharded_specs(ds, cfg, model, state, specs, errors)
    _scale_specs(ds, cfg, model, state, specs, errors)
    _continual_spec(specs, errors)
    _CACHE["programs"] = (specs, errors)
    return _CACHE["programs"]
