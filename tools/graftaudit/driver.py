"""The graftaudit driver: program enumeration, pass dispatch, the
per-program allowlist, the baseline file, JSON + human output, exit
codes — graftlint's driver conventions (tools/graftlint/driver.py)
applied to traced programs instead of source files.

Contract every pass plugs into (tools/graftaudit/passes/__init__.py):

- a pass module exposes ``RULE`` and ``run(programs) ->
  list[Violation]`` over a list of ProgramSpec (programs.py);
- a Violation's ``path`` is the PROGRAM name (stable audit identity),
  ``line`` is always 0 (IR has no lines; ``message`` carries the
  source location extracted from the eqn traceback);
- traced IR has no comment lines to carry pragmas, so deliberate
  exceptions live in the ALLOWLIST below — (rule, program glob, key
  glob) plus the justification, reviewable in-tree and pinned against
  rot by tests/test_graftaudit.py (every entry must still suppress a
  live finding);
- the baseline file (tools/graftaudit/baseline.json, same format and
  semantics as graftlint's) is the emergency hatch for accepted debt;
  the tree audits clean with no baseline file today — keep it that way;
- exit codes: 0 clean, 1 new violations, 2 usage / internal error.
"""

from __future__ import annotations

import fnmatch
import time

from tools.graftlint.driver import (Violation, load_baseline,
                                    write_baseline)

import os

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

# Each entry: (rule, program glob, key glob, justification). An entry
# must keep suppressing at least one live finding — tier-1 fails on
# dead entries, so every exemption stays an honest record.
ALLOWLIST = (
    ("padding-taint", "serve/*/pallas/*", "*",
     "Pallas kernel bodies are audited at the call boundary: the "
     "dataflow proof cannot see through pallas_call, so lane "
     "independence for the flash-style kernels is pinned dynamically "
     "instead (tests/test_pallas_attention.py parity + the "
     "attention_impl padding-invariance grid in tests/test_serve.py)."),
    ("padding-taint", "serve/*/pallas_fused/*", "*",
     "Same call-boundary limit as serve/*/pallas/*: the fused-epilogue "
     "kernel's masking lives inside the pallas_call body; covered by "
     "kernel parity tests and the serve padding-invariance grid."),
)


def allowlisted(v: Violation) -> str | None:
    """The justification suppressing this violation, or None."""
    for rule, prog_glob, key_glob, reason in ALLOWLIST:
        if (v.rule == rule and fnmatch.fnmatchcase(v.path, prog_glob)
                and fnmatch.fnmatchcase(v.key, key_glob)):
            return reason
    return None


class AuditResult:
    def __init__(self, new, baselined, allowed, elapsed_s, passes,
                 programs):
        self.new = new
        self.baselined = baselined
        self.allowed = allowed          # [(Violation, reason)]
        self.elapsed_s = elapsed_s
        self.passes = passes
        self.programs = programs        # audited program names

    @property
    def ok(self) -> bool:
        return not self.new

    def allowlist_hits(self) -> set[int]:
        """Indices of ALLOWLIST entries that suppressed something —
        the liveness pin tests/test_graftaudit.py asserts."""
        hits = set()
        for v, _reason in self.allowed:
            for i, (rule, pg, kg, _r) in enumerate(ALLOWLIST):
                if (v.rule == rule and fnmatch.fnmatchcase(v.path, pg)
                        and fnmatch.fnmatchcase(v.key, kg)):
                    hits.add(i)
        return hits

    def as_dict(self) -> dict:
        return {
            "version": 1,
            "passes": self.passes,
            "ok": self.ok,
            "elapsed_s": round(self.elapsed_s, 3),
            "programs": self.programs,
            "violations": [v.as_dict() for v in self.new],
            "baselined": [v.as_dict() for v in self.baselined],
            "allowlisted": [{**v.as_dict(), "reason": r}
                            for v, r in self.allowed],
        }


def run_passes(programs, pass_names=None, baseline=None,
               build_errors=()) -> AuditResult:
    """Run the named passes (default: all) over already-built
    ProgramSpecs — the unit-test entry point; run_repo() wraps it with
    the real program enumeration."""
    from tools.graftaudit.passes import get_passes

    t0 = time.perf_counter()
    baseline = baseline or set()
    modules = get_passes(pass_names)
    new, baselined, allowed = [], [], []
    found = [Violation(rule="driver", path=name, line=0,
                       message=f"program no longer builds: {err}",
                       key="build-error")
             for name, err in build_errors]
    for mod in modules:
        found.extend(mod.run(programs))
    for v in found:
        reason = allowlisted(v)
        if reason is not None:
            allowed.append((v, reason))
        elif (v.rule, v.path, v.key) in baseline:
            baselined.append(v)
        else:
            new.append(v)
    new.sort(key=lambda v: (v.path, v.rule, v.key))
    baselined.sort(key=lambda v: (v.path, v.rule, v.key))
    return AuditResult(new=new, baselined=baselined, allowed=allowed,
                       elapsed_s=time.perf_counter() - t0,
                       passes=[m.RULE for m in modules],
                       programs=[p.name for p in programs])


def run_repo(pass_names=None, baseline_path=None,
             program_glob=None) -> AuditResult:
    """The full audit over the stack's real programs — what tier-1
    (tests/test_graftaudit.py) and bench.py --gate run. Emits
    ``audit.programs`` / ``audit.violations`` / ``audit.seconds`` on
    the telemetry bus (docs/OBSERVABILITY.md)."""
    from tools.graftaudit.programs import build_programs

    t0 = time.perf_counter()
    baseline = load_baseline(
        DEFAULT_BASELINE if baseline_path is None else baseline_path)
    specs, errors = build_programs()
    if program_glob:
        specs = [s for s in specs
                 if fnmatch.fnmatchcase(s.name, program_glob)]
        errors = [(n, e) for n, e in errors
                  if fnmatch.fnmatchcase(n, program_glob)]
    result = run_passes(specs, pass_names, baseline=baseline,
                        build_errors=errors)
    result.elapsed_s = time.perf_counter() - t0

    from pertgnn_tpu import telemetry

    bus = telemetry.get_bus()
    bus.gauge("audit.programs", len(result.programs))
    bus.gauge("audit.violations", len(result.new))
    bus.gauge("audit.seconds", result.elapsed_s)
    return result


__all__ = ["ALLOWLIST", "AuditResult", "Violation", "allowlisted",
           "load_baseline", "run_passes", "run_repo", "write_baseline",
           "DEFAULT_BASELINE"]
