"""graftscope CLI — collect, attribute, assert, export.

    python -m tools.graftscope --telemetry_dir runs/fleet1
    python -m tools.graftscope --telemetry_dir runs/fleet1 \
        --assert_complete --expect_ok 2000 --perfetto fleet1.trace.json

Prints ONE JSON report line on stdout (the benches embed it in their
own records). Exit codes, same contract as graftlint/graftaudit
(docs/LINTS.md): 0 = collected clean (and assertions held), 1 = orphan
spans, multi-root traces, or a failed ``--assert_complete`` /
``--expect_ok``, 2 = usage/IO errors.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.graftscope.collect import (CollectError, OrphanSpanError,
                                      collect)
from tools.graftscope.export import write_chrome_trace
from tools.graftscope.report import build_report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--telemetry_dir", required=True,
                   help="the shared dir every fleet process wrote its "
                        "telemetry-p*-*.jsonl into (rotation .partN "
                        "files are merged automatically)")
    p.add_argument("--top_k", type=int, default=5,
                   help="slowest exemplar traces to inline in the "
                        "report")
    p.add_argument("--allow_orphans", action="store_true",
                   help="report orphan spans instead of refusing — for "
                        "inspecting a knowingly partial file set; the "
                        "exit code still flags them")
    p.add_argument("--assert_complete", action="store_true",
                   help="exit 1 unless every ok-rooted trace has "
                        "exactly one root and a complete stage chain "
                        "(what fleet_bench/stream_bench gate on)")
    p.add_argument("--expect_ok", type=int, default=-1,
                   help="exit 1 unless exactly this many ok-rooted "
                        "traces collected (-1 = don't check) — pins "
                        "trace count to the bench's served count")
    p.add_argument("--perfetto", default="",
                   help="also write Chrome/Perfetto trace-event JSON "
                        "here (load at ui.perfetto.dev)")
    p.add_argument("--out", default="",
                   help="also write the report JSON to this path")
    args = p.parse_args(argv)

    try:
        result = collect(args.telemetry_dir,
                         allow_orphans=args.allow_orphans)
    except OrphanSpanError as exc:
        print(f"graftscope: REFUSING: {exc}", file=sys.stderr)
        return 1
    except CollectError as exc:
        print(f"graftscope: {exc}", file=sys.stderr)
        return 2

    report = build_report(result, top_k=args.top_k)
    if args.perfetto:
        report["perfetto_events"] = write_chrome_trace(result,
                                                       args.perfetto)
        report["perfetto_path"] = args.perfetto

    failures: list[str] = []
    if result.orphans:
        failures.append(f"{len(result.orphans)} orphan span(s)")
    if result.multi_root:
        failures.append(f"{len(result.multi_root)} multi-root trace(s)")
    if args.assert_complete and report["incomplete"]:
        failures.append(
            f"{report['incomplete']} incomplete ok trace(s); first: "
            f"{report['completeness_violations'][0]}")
    if args.expect_ok >= 0 and report["traces_ok"] != args.expect_ok:
        failures.append(f"expected {args.expect_ok} ok traces, "
                        f"collected {report['traces_ok']}")
    report["failures"] = failures

    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    for f_ in failures:
        print(f"graftscope FAIL: {f_}", file=sys.stderr)
    return 1 if failures else 0
