"""Chrome/Perfetto trace-event export of collected request trees.

The trace-event JSON format (load at ui.perfetto.dev or
chrome://tracing): complete events (``"ph": "X"``) with microsecond
stamps on the ALIGNED clock — after collect's bounded-skew estimate,
one request's router and worker spans render on a shared timeline even
though the processes stamped them on unrelated monotonic clocks.

Layout choice: Perfetto rows are (pid, tid) pairs. Real pids keep the
process split visible (one track group per fleet member); the tid is a
stable per-trace hash so the spans of one request stack on one row
inside each process, making a single request's hop pattern readable in
a fleet serving thousands of concurrent requests.
"""

from __future__ import annotations

from tools.graftscope.collect import CollectResult


def _tid(trace_id: str) -> int:
    return int(trace_id[:8], 16) % (2 ** 31 - 1) + 1


def chrome_trace_events(result: CollectResult) -> list[dict]:
    """Trace-event dicts, ready for ``json.dump({"traceEvents": ...})``."""
    events: list[dict] = []
    if not result.traces:
        return events
    # rebase to the earliest aligned stamp so timestamps start near 0
    t_base = min(s.atm0 for spans in result.traces.values()
                 for s in spans)
    for tid_str, spans in sorted(result.traces.items()):
        row = _tid(tid_str)
        for s in sorted(spans, key=lambda s: s.atm0):
            events.append({
                "name": s.name,
                "cat": "graftscope",
                "ph": "X",
                "ts": round((s.atm0 - t_base) * 1e6, 3),
                "dur": round(s.dur_ms * 1e3, 3),
                "pid": s.pid,
                "tid": row,
                "args": {"trace_id": s.trace_id,
                         "span_id": s.span_id,
                         "parent_span_id": s.parent_id,
                         **s.tags},
            })
    return events


def write_chrome_trace(result: CollectResult, path: str) -> int:
    """Write the export; returns the event count."""
    import json

    events = chrome_trace_events(result)
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)
