"""Trace collection: JSONL merge, tree assembly, clock alignment.

Every process of a run writes spans on ITS OWN CLOCK_MONOTONIC clock
(telemetry/writer.py stamps ``tm``/``tm0``); the clocks share no epoch
across hosts and may drift. Alignment uses the only ground truth the
stream carries: a cross-process parent/child pair is a request/response
bounding — the worker's spans happened INSIDE the router's transport
span. Each pair therefore bounds the worker->router clock offset d:

    parent.tm0 <= child.tm0 + d      (the request left before work began)
    child.tm1 + d <= parent.tm1      (the response landed after it ended)

so d is in [parent.tm0 - child.tm0, parent.tm1 - child.tm1]; the
intersection over all pairs of one process pair tightens it, the
midpoint is the estimate and the half-width the reported uncertainty.
An EMPTY intersection means the stamps are inconsistent (a broken
clock, reused pids across hosts) — reported per process, never papered
over. On one Linux host the offsets come out ~0 (CLOCK_MONOTONIC is
system-wide), which is itself a useful self-check of the estimator.

Orphans — spans whose parent id resolves nowhere in their trace — are
collected and REFUSED by default (``OrphanSpanError``): an orphan means
a writer lost its parent emission or files are missing from the merge,
and attributing around a hole silently would corrupt the percentiles
this tool exists to make trustworthy. A crash-killed worker's TRUNCATED
final line is not an orphan source (the schema reader skips it), and a
lost worker's spans still resolve: the router emits its transport span
with ``outcome="lost"`` after the failure.
"""

from __future__ import annotations

import dataclasses
import os
import re


class CollectError(RuntimeError):
    """The telemetry dir cannot be collected (missing, unreadable, or
    schema-invalid beyond the tolerated crash tail)."""


class OrphanSpanError(CollectError):
    """Orphan spans found and not explicitly allowed."""


# the writer's naming scheme, rotation parts included:
#   telemetry-p<pi>-<host>-<pid>.jsonl
#   telemetry-p<pi>-<host>-<pid>.part<N>.jsonl
_FILE_RE = re.compile(
    r"^telemetry-p\d+-.+?-\d+(\.part(?P<part>\d+))?\.jsonl$")


@dataclasses.dataclass
class Span:
    """One v2 span event, trace identity resolved."""

    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    pid: int
    pi: int
    t_wall: float
    tm0: float
    tm1: float
    dur_ms: float
    tags: dict
    file: str
    # alignment output: stamps on the reference process's clock
    atm0: float = 0.0
    atm1: float = 0.0

    @property
    def stage(self) -> str:
        """'trace.router_queue' -> 'router_queue' (report stage key)."""
        return self.name.split(".", 1)[-1]


@dataclasses.dataclass
class CollectResult:
    traces: dict[str, list[Span]]
    orphans: list[Span]
    multi_root: dict[str, int]          # trace_id -> root count (> 1)
    clock: dict[int, dict]              # pid -> alignment report
    files: list[str]
    n_events: int
    n_spans: int


def telemetry_files(telemetry_dir: str) -> list[str]:
    """Every telemetry JSONL under the dir, rotation parts in order."""
    if not os.path.isdir(telemetry_dir):
        raise CollectError(f"not a directory: {telemetry_dir!r}")

    def sort_key(fname: str):
        m = _FILE_RE.match(fname)
        part = int(m.group("part") or 0) if m else 0
        return (fname.split(".part")[0], part)

    out = [os.path.join(telemetry_dir, f)
           for f in sorted(os.listdir(telemetry_dir), key=sort_key)
           if _FILE_RE.match(f)]
    return out


def load_spans(telemetry_dir: str) -> tuple[list[Span], list[str], int]:
    """(trace-carrying spans, files read, total event count)."""
    from pertgnn_tpu.telemetry import SchemaError, iter_events

    files = telemetry_files(telemetry_dir)
    if not files:
        raise CollectError(
            f"no telemetry-*.jsonl files under {telemetry_dir!r}")
    spans: list[Span] = []
    n_events = 0
    for path in files:
        try:
            with open(path) as f:
                for ev in iter_events(f, strict=True):
                    n_events += 1
                    if ev["kind"] != "span" or "trace_id" not in ev:
                        continue
                    tm1 = ev.get("tm", 0.0)
                    tm0 = ev.get("tm0", tm1 - ev["dur_ms"] / 1e3)
                    spans.append(Span(
                        trace_id=ev["trace_id"],
                        span_id=ev.get("span_id", ""),
                        parent_id=ev.get("parent_span_id"),
                        name=ev["name"], pid=ev["pid"], pi=ev["pi"],
                        t_wall=ev["t"], tm0=tm0, tm1=tm0 + ev["dur_ms"] / 1e3,
                        dur_ms=ev["dur_ms"],
                        tags=ev.get("tags") or {}, file=path))
        except (OSError, SchemaError) as exc:
            raise CollectError(f"cannot read {path}: {exc}") from exc
    return spans, files, n_events


def _align_clocks(traces: dict[str, list[Span]]) -> dict[int, dict]:
    """Per-pid offset (seconds, added to that pid's stamps) onto the
    reference process's clock + the bounded-skew report. Mutates spans'
    atm0/atm1."""
    # offset bounds per (parent_pid, child_pid) pair
    bounds: dict[tuple[int, int], list[float]] = {}
    n_pairs: dict[tuple[int, int], int] = {}
    root_count: dict[int, int] = {}
    pids: set[int] = set()
    for spans in traces.values():
        by_id = {s.span_id: s for s in spans}
        for s in spans:
            pids.add(s.pid)
            if s.parent_id is None:
                root_count[s.pid] = root_count.get(s.pid, 0) + 1
                continue
            parent = by_id.get(s.parent_id)
            if parent is None or parent.pid == s.pid:
                continue
            key = (parent.pid, s.pid)
            lo, hi = parent.tm0 - s.tm0, parent.tm1 - s.tm1
            cur = bounds.get(key)
            if cur is None:
                bounds[key] = [lo, hi]
            else:
                cur[0] = max(cur[0], lo)
                cur[1] = min(cur[1], hi)
            n_pairs[key] = n_pairs.get(key, 0) + 1
    # reference = the process owning the most roots (the front door);
    # deterministic tie-break on pid
    ref = (max(sorted(root_count), key=lambda p: root_count[p])
           if root_count else (min(pids) if pids else 0))
    offset: dict[int, float] = {ref: 0.0}
    report: dict[int, dict] = {ref: {
        "offset_ms": 0.0, "uncertainty_ms": 0.0, "pairs": 0,
        "reference": True, "consistent": True}}
    # BFS over the pair graph from the reference (fleet topology is a
    # star router->workers; transitive hops compose offsets)
    frontier = [ref]
    edges: dict[int, list[tuple[int, tuple[int, int], int]]] = {}
    for (a, b), _ in bounds.items():
        edges.setdefault(a, []).append((b, (a, b), +1))
        edges.setdefault(b, []).append((a, (a, b), -1))
    while frontier:
        cur = frontier.pop()
        for nxt, key, sign in edges.get(cur, ()):
            if nxt in offset:
                continue
            lo, hi = bounds[key]
            mid = (lo + hi) / 2.0
            consistent = lo <= hi
            # child offset d maps CHILD clock onto PARENT clock; going
            # parent->child applies +d to the child, child->parent -d
            offset[nxt] = offset[cur] + sign * mid
            report[nxt] = {
                "offset_ms": round(offset[nxt] * 1e3, 6),
                "uncertainty_ms": round(abs(hi - lo) / 2.0 * 1e3, 6),
                "pairs": n_pairs[key],
                "reference": False,
                "consistent": consistent,
            }
            frontier.append(nxt)
    for p in pids:
        if p not in offset:
            offset[p] = 0.0
            report[p] = {"offset_ms": 0.0, "uncertainty_ms": None,
                         "pairs": 0, "reference": False,
                         "consistent": None, "unaligned": True}
    for spans in traces.values():
        for s in spans:
            d = offset[s.pid]
            s.atm0, s.atm1 = s.tm0 + d, s.tm1 + d
    return report


def collect(telemetry_dir: str,
            allow_orphans: bool = False) -> CollectResult:
    """Merge + assemble + align one telemetry dir. Raises
    OrphanSpanError on orphan spans unless explicitly allowed — a hole
    in the tree is a finding, not something to attribute around."""
    spans, files, n_events = load_spans(telemetry_dir)
    traces: dict[str, list[Span]] = {}
    for s in spans:
        traces.setdefault(s.trace_id, []).append(s)
    orphans: list[Span] = []
    multi_root: dict[str, int] = {}
    for tid, tspans in traces.items():
        ids = {s.span_id for s in tspans}
        n_roots = sum(1 for s in tspans if s.parent_id is None)
        if n_roots > 1:
            multi_root[tid] = n_roots
        orphans.extend(s for s in tspans
                       if s.parent_id is not None
                       and s.parent_id not in ids)
    if orphans and not allow_orphans:
        ex = orphans[0]
        raise OrphanSpanError(
            f"{len(orphans)} orphan span(s): e.g. {ex.name} "
            f"(trace {ex.trace_id}, span {ex.span_id}) references "
            f"parent {ex.parent_id!r} which no merged file contains — "
            f"a missing file or a dropped parent emission; rerun with "
            f"allow_orphans to inspect anyway")
    clock = _align_clocks(traces)
    return CollectResult(traces=traces, orphans=orphans,
                         multi_root=multi_root, clock=clock,
                         files=files, n_events=n_events,
                         n_spans=len(spans))
