"""Tail attribution: per-stage critical-path percentiles + exemplars.

The stage chain of one fleet request (standalone serving has no
transport leg — the worker stages hang directly off the root):

    trace.request                     (root — the future's whole life)
      trace.router_queue              admission -> handed to a sender
      trace.transport                 the HTTP round trip (per attempt)
        trace.worker_queue            worker admission -> left the queue
        trace.pack                    host pack into the rung shape
        trace.dispatch                program launch
        trace.compute                 block until host-readable
      trace.complete                  rows back -> future resolved

The breakdown reports EXCLUSIVE transport time (round trip minus the
worker stages nested in it — i.e. wire + HTTP + worker-side handler
overhead) so the stages sum toward the total instead of double
counting; the remainder (``other``) is the unattributed slack
(scheduling, GIL, clock noise) and is reported, not hidden.

Completeness — the invariant fleet_bench/stream_bench exit-code-assert:
every trace whose root settled ``outcome="ok"`` has EXACTLY one root
and a full stage chain. Slow-kept partial traces (root tagged
``sampled="slow"`` — the head said no, the always-keep override flushed
the front-door spans anyway) are exempt from the worker-side chain by
construction and are excluded from the stage percentiles; they still
feed the exemplar list, which is their entire purpose.
"""

from __future__ import annotations

from tools.graftscope.collect import CollectResult, Span

STAGES = ("router_queue", "transport", "worker_queue", "pack",
          "dispatch", "compute", "complete")

WORKER_STAGES = ("worker_queue", "pack", "dispatch", "compute")

PERCENTILES = (50.0, 95.0, 99.0, 99.9)


def percentile(sorted_vals: list[float], q: float) -> float | None:
    """Linear-interpolation percentile over pre-sorted values (numpy's
    default method, stdlib-only so the collector stays dependency-free)."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _summary(vals: list[float]) -> dict:
    vals = sorted(vals)
    out = {"count": len(vals)}
    for q in PERCENTILES:
        key = f"p{q:g}".replace(".", "_")
        out[f"{key}_ms"] = (round(percentile(vals, q), 3)
                            if vals else None)
    return out


def _trace_breakdown(root: Span, spans: list[Span]) -> dict:
    """Exclusive per-stage milliseconds of one trace."""
    by_stage: dict[str, float] = {s: 0.0 for s in STAGES}
    for s in spans:
        if s.parent_id is not None and s.stage in by_stage:
            by_stage[s.stage] += s.dur_ms
    worker_ms = sum(by_stage[s] for s in WORKER_STAGES)
    if by_stage["transport"]:
        by_stage["transport"] = max(by_stage["transport"] - worker_ms,
                                    0.0)
    total = root.dur_ms
    attributed = sum(by_stage.values())
    by_stage["other"] = max(total - attributed, 0.0)
    by_stage["total"] = total
    return by_stage


def _is_partial(root: Span) -> bool:
    return root.tags.get("sampled") == "slow"


def _chain_missing(spans: list[Span]) -> list[str]:
    """Stage names missing from one trace's chain (empty = complete).
    Fleet traces need an ok transport attempt + the worker stages +
    router_queue + complete; standalone traces just the worker
    stages."""
    stages = {s.stage for s in spans if s.parent_id is not None}
    transports = [s for s in spans if s.stage == "transport"]
    if transports:
        missing = [st for st in
                   ("router_queue", *WORKER_STAGES, "complete")
                   if st not in stages]
        if not any(s.tags.get("outcome") == "ok" for s in transports):
            missing.append("transport(outcome=ok)")
    else:  # standalone serving: worker stages hang off the root
        missing = [st for st in WORKER_STAGES if st not in stages]
    return missing


def check_completeness(result: CollectResult) -> list[str]:
    """Violations of the one-root + full-stage-chain invariant over
    every ok-rooted trace (partial slow-kept traces exempted from the
    worker chain; see module docstring)."""
    violations: list[str] = []
    for tid, mr in sorted(result.multi_root.items()):
        violations.append(f"trace {tid}: {mr} roots (want exactly 1)")
    for tid, spans in sorted(result.traces.items()):
        roots = [s for s in spans if s.parent_id is None]
        if len(roots) != 1:
            if not roots:
                violations.append(f"trace {tid}: no root span")
            continue  # multi-root already reported
        root = roots[0]
        if root.tags.get("outcome") != "ok":
            continue  # failed requests may legitimately stop anywhere
        if _is_partial(root):
            # front-door spans only, by design: require the queue leg
            # so even a partial exemplar attributes SOMETHING
            stages = {s.stage for s in spans if s.parent_id is not None}
            if "router_queue" not in stages \
                    and "worker_queue" not in stages:
                violations.append(
                    f"trace {tid}: slow-kept partial trace carries no "
                    f"queue stage span")
            continue
        missing = _chain_missing(spans)
        if missing:
            violations.append(
                f"trace {tid}: ok root but incomplete stage chain — "
                f"missing {', '.join(missing)}")
    return violations


def _exemplar(root: Span, spans: list[Span]) -> dict:
    rel0 = root.atm0
    return {
        "trace_id": root.trace_id,
        "total_ms": round(root.dur_ms, 3),
        "entry_id": root.tags.get("entry_id"),
        "partial": _is_partial(root),
        "breakdown_ms": {k: round(v, 3) for k, v in
                         _trace_breakdown(root, spans).items()},
        "spans": [
            {"name": s.name,
             "start_ms": round((s.atm0 - rel0) * 1e3, 3),
             "dur_ms": round(s.dur_ms, 3),
             "pid": s.pid,
             "parent": s.parent_id,
             "span_id": s.span_id,
             **({"tags": s.tags} if s.tags else {})}
            for s in sorted(spans, key=lambda s: (s.atm0, s.span_id))],
    }


def build_report(result: CollectResult, top_k: int = 5) -> dict:
    """The attribution report benches embed in their JSON: per-stage
    p50/p95/p99/p99.9 over complete ok traces, top-k slowest exemplars
    (partial ones included — tail exemplars are why they were kept),
    the per-process clock report, and the completeness verdict."""
    ok_complete: list[tuple[Span, list[Span]]] = []
    ok_partial: list[tuple[Span, list[Span]]] = []
    n_error = 0
    for spans in result.traces.values():
        roots = [s for s in spans if s.parent_id is None]
        if len(roots) != 1:
            continue
        root = roots[0]
        if root.tags.get("outcome") != "ok":
            n_error += 1
            continue
        (ok_partial if _is_partial(root)
         else ok_complete).append((root, spans))
    per_stage: dict[str, list[float]] = {s: [] for s in STAGES}
    per_stage["other"] = []
    totals: list[float] = []
    for root, spans in ok_complete:
        if _chain_missing(spans):
            # an ok trace with a hole in its chain (e.g. a worker at
            # "basic" verbosity contributing no spans) must not feed
            # the stage percentiles: its worker time would silently
            # masquerade as transport time. It still counts in
            # traces_ok and surfaces via `incomplete`.
            continue
        bd = _trace_breakdown(root, spans)
        totals.append(bd["total"])
        for stage in per_stage:
            per_stage[stage].append(bd[stage])
    slowest = sorted(ok_complete + ok_partial,
                     key=lambda rs: -rs[0].dur_ms)[:max(top_k, 0)]
    completeness = check_completeness(result)
    return {
        "traces": len(result.traces),
        "traces_ok": len(ok_complete) + len(ok_partial),
        "traces_ok_complete": len(ok_complete),
        "traces_ok_partial": len(ok_partial),
        "traces_error": n_error,
        "spans": result.n_spans,
        "events": result.n_events,
        "files": len(result.files),
        "orphans": len(result.orphans),
        "multi_root": len(result.multi_root),
        "incomplete": len(completeness),
        "completeness_violations": completeness[:50],
        "clock": {str(pid): rep
                  for pid, rep in sorted(result.clock.items())},
        "stage_ms": {"total": _summary(totals),
                     **{s: _summary(v) for s, v in per_stage.items()}},
        "slowest": [_exemplar(r, sp) for r, sp in slowest],
    }
