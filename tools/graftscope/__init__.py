"""graftscope — fleet-wide trace collection and tail attribution.

The serving stack spreads one request's life across the router process
and N worker processes, each appending v2 span events (trace_id /
span_id / parent_span_id + monotonic stamps — telemetry/schema.py) to
its own ``telemetry-p*-*.jsonl`` file. graftscope merges those files
back into per-request span trees and answers the question ROADMAP
item 3 (hedging, SLO classes, autoscale) is blocked on: *for one slow
request, where did the time go?*

Three layers, importable separately:

- ``collect``  — merge every telemetry JSONL (rotation ``.partN``
  parts included) under one ``--telemetry_dir`` into per-trace span
  trees; align each worker's monotonic clock to the router's via the
  request/response bounding pairs (a child span must lie inside its
  cross-process parent — the intersection over pairs gives a bounded
  skew estimate per process, reported, never assumed); REFUSE loudly
  on orphan spans (a parent id that resolves nowhere) instead of
  silently dropping them.
- ``report``   — per-stage critical-path breakdown (router queue,
  transport, worker queue, pack, dispatch, compute, complete) at
  p50/p95/p99/p99.9, the top-k slowest exemplar traces inline, and
  the completeness verdict fleet_bench/stream_bench exit-code-assert
  (every ok root: exactly one root, a full stage chain).
- ``export``   — Chrome/Perfetto trace-event JSON (load in
  ui.perfetto.dev) on the aligned clock.

CLI: ``python -m tools.graftscope --telemetry_dir DIR`` — exit 0 clean,
1 on orphans / failed completeness assertion, 2 on usage errors.
Schema + semantics: docs/OBSERVABILITY.md "Distributed request
tracing".
"""

from tools.graftscope.collect import (CollectError, OrphanSpanError,
                                      Span, collect)
from tools.graftscope.export import chrome_trace_events
from tools.graftscope.report import build_report

__all__ = ["collect", "build_report", "chrome_trace_events", "Span",
           "CollectError", "OrphanSpanError"]
