"""One-off on-chip sweep: how does cached-chunk step throughput respond to
(a) tighter node/edge budgets, (b) scan_chunk, (c) bf16 activations?

Informs the bucketed-budget design (ROUND3.md future work). Not part of
the driver bench; run manually: python benchmarks/sweep_r3.py
"""

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import itertools

    import jax
    import jax.numpy as jnp
    import optax

    from bench import build_workload
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_chunk_iter, create_train_state,
                                        make_train_chunk)

    ds, cfg = build_workload(3000)
    base_budget = ds.budget
    print("base budget:", base_budget)

    def ceiling(cfg, budget, scan_chunk):
        ds2 = dataclasses.replace(ds, budget=budget)
        cfg2 = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, scan_chunk=scan_chunk))
        model = make_model(cfg2.model, ds.num_ms, ds.num_entries,
                           ds.num_interfaces, ds.num_rpctypes)
        tx = optax.adam(cfg2.train.lr)
        host = list(itertools.islice(ds2.batches("train"), scan_chunk))
        graphs = sum(int(b.graph_mask.sum()) for b in host)
        chunk_batch = next(_chunk_iter(iter(host), scan_chunk))
        b0 = jax.tree.map(lambda a: jnp.asarray(a[0]), chunk_batch)
        state = create_train_state(model, tx, b0, cfg2.train.seed)
        chunk = make_train_chunk(model, cfg2, tx)
        state, m = chunk(state, chunk_batch)
        jax.block_until_ready(m["qloss_sum"])
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            s = state
            for _ in range(max(1, 48 // scan_chunk)):
                s, mm = chunk(s, chunk_batch)
            jax.block_until_ready(mm["qloss_sum"])
            dt = time.perf_counter() - t0
            best = max(best, max(1, 48 // scan_chunk) * graphs / dt)
        return best

    rows = []
    b = base_budget
    tight = dataclasses.replace(
        b, max_nodes=(int(b.max_nodes * 0.55) + 127) // 128 * 128,
        max_edges=(int(b.max_edges * 0.55) + 127) // 128 * 128)
    half_graphs = dataclasses.replace(b, max_graphs=b.max_graphs // 2)
    for name, budget in [("base", b), ("tight55", tight),
                         ("g85", half_graphs)]:
        for sc in (16, 64):
            v = ceiling(cfg, budget, sc)
            rows.append({"budget": name, "scan_chunk": sc,
                         "graphs_per_s": round(v, 1)})
            print(json.dumps(rows[-1]), flush=True)
    # bf16 on base budget
    mcfg = dataclasses.replace(cfg.model, bf16_activations=True)
    cfg_bf = dataclasses.replace(cfg, model=mcfg)
    for sc in (16, 64):
        v = ceiling(cfg_bf, b, sc)
        rows.append({"budget": "base+bf16", "scan_chunk": sc,
                     "graphs_per_s": round(v, 1)})
        print(json.dumps(rows[-1]), flush=True)


if __name__ == "__main__":
    main()
