"""Round-3 budget study. Two parts:

1. `utilization()` (host-only, runs anywhere): padded-slot utilization of
   a shuffled epoch under (a) the derived budget at various headrooms and
   (b) 2-3 quantile-BUCKETED budgets — the measurement behind
   `derive_budget`'s headroom-1.1 default and the bucketing rejection
   (batching/pack.py docstring; ROUND3.md). Run:
       python benchmarks/sweep_r3.py --utilization
2. `main()` (on-chip): cached-chunk step throughput vs tighter budgets,
   scan_chunk, and bf16 activations.

Not part of the driver bench; run manually.
"""

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def utilization():
    """Node/edge padded-slot utilization of one shuffled epoch, single
    tight budgets vs quantile buckets (pure host work, no accelerator)."""
    import numpy as np

    from bench import build_workload
    from pertgnn_tpu.batching.arena import assign_batches
    from pertgnn_tpu.batching.pack import (BatchBudget, _round_up,
                                           derive_budget)

    ds, cfg = build_workload(1000)
    sp = ds.splits["train"]
    arena = ds.arena()
    order = np.random.default_rng(0).permutation(len(sp))
    ents = sp.entry_ids[order].astype(np.int64)
    cn, ce = arena.node_count[ents], arena.edge_count[ents]
    mixes = {int(e): ds.mixtures[int(e)] for e in np.unique(ents)}

    def waste(cn, ce, budget):
        bi, _, _, _ = assign_batches(cn, ce, budget)
        nb = int(bi[-1]) + 1 if len(bi) else 0
        return nb, cn.sum() / (nb * budget.max_nodes), \
            ce.sum() / (nb * budget.max_edges)

    rows = []
    for h in (1.3, 1.1, 1.0, 0.9):
        b = derive_budget(mixes, ents, cfg.data.batch_size, headroom=h)
        nb, un, ue = waste(cn, ce, b)
        rows.append({"scheme": f"single headroom={h}", "batches": nb,
                     "node_util": round(float(un), 2),
                     "edge_util": round(float(ue), 2)})
        print(json.dumps(rows[-1]), flush=True)
    for k in (2, 3):
        qs = np.quantile(cn, np.linspace(0, 1, k + 1)[1:-1])
        bucket = np.searchsorted(qs, cn, "right")
        tot = dict(nb=0, pn=0, pe=0, rn=0, re=0)
        for bk in range(k):
            m = bucket == bk
            bn, be = cn[m], ce[m]
            # same 128-lane alignment derive_budget applies to the single
            # budget, so both schemes pay identical TPU padding
            bud = BatchBudget(
                cfg.data.batch_size,
                _round_up(max(int(bn.mean() * cfg.data.batch_size * 1.1),
                              int(bn.max()) + 1)),
                _round_up(max(int(be.mean() * cfg.data.batch_size * 1.1),
                              int(be.max()) + 1)))
            nb, _, _ = waste(bn, be, bud)
            tot["nb"] += nb
            tot["pn"] += nb * bud.max_nodes
            tot["pe"] += nb * bud.max_edges
            tot["rn"] += int(bn.sum())
            tot["re"] += int(be.sum())
        rows.append({"scheme": f"{k} quantile buckets", "batches": tot["nb"],
                     "node_util": round(tot["rn"] / tot["pn"], 2),
                     "edge_util": round(tot["re"] / tot["pe"], 2)})
        print(json.dumps(rows[-1]), flush=True)
    return rows


def main():
    import itertools

    import jax
    import jax.numpy as jnp
    import optax

    from bench import build_workload
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_chunk_iter, create_train_state,
                                        make_train_chunk)

    ds, cfg = build_workload(3000)
    base_budget = ds.budget
    print("base budget:", base_budget)

    def ceiling(cfg, budget, scan_chunk):
        ds2 = dataclasses.replace(ds, budget=budget)
        cfg2 = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, scan_chunk=scan_chunk))
        model = make_model(cfg2.model, ds.num_ms, ds.num_entries,
                           ds.num_interfaces, ds.num_rpctypes)
        tx = optax.adam(cfg2.train.lr)
        host = list(itertools.islice(ds2.batches("train"), scan_chunk))
        graphs = sum(int(b.graph_mask.sum()) for b in host)
        chunk_batch = next(_chunk_iter(iter(host), scan_chunk))
        b0 = jax.tree.map(lambda a: jnp.asarray(a[0]), chunk_batch)
        state = create_train_state(model, tx, b0, cfg2.train.seed)
        chunk = make_train_chunk(model, cfg2, tx)
        state, m = chunk(state, chunk_batch)
        jax.block_until_ready(m["qloss_sum"])
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(max(1, 48 // scan_chunk)):
                # rebind: the chunk donates its state argument
                state, mm = chunk(state, chunk_batch)
            jax.block_until_ready(mm["qloss_sum"])
            dt = time.perf_counter() - t0
            best = max(best, max(1, 48 // scan_chunk) * graphs / dt)
        return best

    rows = []
    b = base_budget
    tight = dataclasses.replace(
        b, max_nodes=(int(b.max_nodes * 0.55) + 127) // 128 * 128,
        max_edges=(int(b.max_edges * 0.55) + 127) // 128 * 128)
    half_graphs = dataclasses.replace(b, max_graphs=b.max_graphs // 2)
    for name, budget in [("base", b), ("tight55", tight),
                         ("g85", half_graphs)]:
        for sc in (16, 64):
            v = ceiling(cfg, budget, sc)
            rows.append({"budget": name, "scan_chunk": sc,
                         "graphs_per_s": round(v, 1)})
            print(json.dumps(rows[-1]), flush=True)
    # bf16 on base budget
    mcfg = dataclasses.replace(cfg.model, bf16_activations=True)
    cfg_bf = dataclasses.replace(cfg, model=mcfg)
    for sc in (16, 64):
        v = ceiling(cfg_bf, b, sc)
        rows.append({"budget": "base+bf16", "scan_chunk": sc,
                     "graphs_per_s": round(v, 1)})
        print(json.dumps(rows[-1]), flush=True)


if __name__ == "__main__":
    if "--utilization" in sys.argv:
        utilization()
    else:
        main()
