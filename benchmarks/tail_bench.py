"""Tail benchmark: the chaos-storm gate for the shielded fleet.

One run, every ISSUE-13 invariant, exit-code-asserted (the
fleet_bench/chaos_bench split: numbers in the JSON, verdict in the
return code). The scenario is the worst hour of a production day
compressed to bench scale, driven OPEN-LOOP so queueing collapse is
visible (fleet/loadgen.py — closed-loop clients would politely
self-throttle and hide it):

- **the storm** — a trace-replay arrival schedule with burst windows
  (several x the base rate), a diurnal envelope, Zipf entry
  popularity, and a mixed SLO population (critical / standard /
  best_effort), deterministic per seed;
- **the stragglers** — an injected `serve.dispatch` DELAY fault
  (testing/faults.py: slow-without-failing) on a fraction of worker
  dispatches, which is exactly what hedged dispatch defends against;
- **the kill** — one base worker SIGKILLed mid-storm (the
  fleet_bench drill, inside the burst);
- **the relief** — the autoscale controller spawning a warm spare off
  the `router.queue_wait` signal and retiring it on cooldown after
  the storm passes.

Gates (all in one run):

1. rc == 0 and ZERO lost futures — every scheduled arrival resolved to
   a prediction or a typed error (the launcher itself also
   exit-asserts `lost_futures == 0`);
2. every served prediction BIT-IDENTICAL to a single-engine in-process
   reference — including hedge winners (first-answer-wins is safe
   because both legs compute the same bits);
3. hedging observed AND useful: `router.hedge_fired > 0`,
   `router.hedge_won > 0`;
4. lowest-class-first shedding only: best_effort sheds happened,
   `critical` sheds did NOT (no top-class request shed while
   best-effort traffic was being admitted);
5. brownout observed: `router.brownout` fired and workers downgraded
   (`serve.brownout_downgrade` in the JSONL);
6. bounded tail for the top class: critical p99/p99.9 under the
   scenario bound (reported either way);
7. autoscale spawn AND cooldown-retire both observed, the spare WARM
   (`compiles == 0`, `arena_warm`, from its own probe body);
8. graftscope collects a complete stage chain for every successful
   future at sample rate 1.0 — zero orphans, one root each, across
   the kill, the hedges, and the spare.

CPU by default. One JSON line on stdout.

    python benchmarks/tail_bench.py [--dryrun]

``--dryrun`` is the CI wiring: a shorter storm, same gates.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from benchmarks.fleet_bench import (Check, build_reference,  # noqa: E402
                                    common_flags, counters_in,
                                    run_graftscope)


def population_csv(ds, tmp: str) -> tuple[str, np.ndarray, np.ndarray]:
    """The loadgen POPULATION: every (entry, ts_bucket) pair of every
    split, seeded-shuffled — the 'real corpus' the Zipf law skews."""
    import pandas as pd

    e = np.concatenate([np.asarray(s.entry_ids, np.int64)
                        for s in ds.splits.values()])
    t = np.concatenate([np.asarray(s.ts_buckets, np.int64)
                        for s in ds.splits.values()])
    perm = np.random.default_rng(0).permutation(len(e))
    e, t = e[perm], t[perm]
    path = os.path.join(tmp, "population.csv")
    pd.DataFrame({"entry_id": e, "ts_bucket": t}).to_csv(path,
                                                         index=False)
    return path, e, t


def straggler_plan() -> str:
    """The armed chaos: a seeded DELAY fault on a fraction of worker
    dispatches (slow-without-failing — the hedging target). Exported
    via $PERTGNN_FAULT_PLAN so every worker (spares included) adopts
    it; the bench parent's reference engine never sees it."""
    from pertgnn_tpu.testing.faults import FaultPlan, FaultSpec

    return FaultPlan([FaultSpec(site="serve.dispatch", kind="delay",
                                delay_s=0.35, p=0.12)],
                     seed=1234).to_json()


def run_storm(tmp: str, pop_csv: str, args) -> dict:
    """One fleet_main --loadgen chaos-storm run; SIGKILLs a base
    worker inside the first burst window. Returns {rc, stats, out_csv,
    killed_pid}."""
    from pertgnn_tpu.fleet.transport import WorkerTransportError, get_probe

    duration = 6.0 if args.dryrun else 20.0
    base_rps = args.base_rps or (120.0 if args.dryrun else 200.0)
    out_csv = os.path.join(tmp, "served_storm.csv")
    tele = os.path.join(tmp, "tele_storm")
    cmd = [sys.executable, "-m", "pertgnn_tpu.cli.fleet_main",
           *common_flags(tmp), "--fresh_init",
           "--num_workers", "2", "--pin_worker_cpus",
           # the storm rides the shared-memory ring: a SIGKILLed worker
           # must surface as RingPeerDead -> requeue, not a stall
           "--transport", "shm",
           "--requests", pop_csv,
           # the storm: open-loop bursts + diurnal + Zipf + SLO mix
           "--loadgen",
           "--loadgen_duration_s", str(duration),
           "--loadgen_base_rps", str(base_rps),
           "--loadgen_burst_factor", "6",
           "--loadgen_burst_every_s", "2.0",
           "--loadgen_burst_len_s", "0.8",
           "--loadgen_diurnal_amp", "0.4",
           "--loadgen_diurnal_period_s", str(duration),
           "--loadgen_zipf_s", "1.1",
           "--loadgen_slo_mix",
           "critical:0.1,standard:0.3,best_effort:0.6",
           "--seed", "0",
           # hedging: fixed threshold well under the injected 350ms
           # straggler delay, well over a healthy dispatch
           "--hedge_quantile_ms", "120",
           # SLO admission pressure: a pending cap the bursts overflow
           "--router_max_pending", "48",
           "--brownout_enter_ratio", "0.3",
           # elastic warm spare off the queue-wait signal
           "--autoscale_max_spares", "1",
           "--autoscale_up_ms", "40", "--autoscale_down_ms", "15",
           "--autoscale_hold_s", "0.3", "--autoscale_cooldown_s", "2",
           "--health_poll_interval_s", "0.3",
           "--router_dispatch_timeout_s", "30",
           "--telemetry_dir", tele, "--telemetry_level", "trace",
           "--trace_sample_rate", "1.0",
           "--out", out_csv]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PERTGNN_FAULT_PLAN": straggler_plan()}
    child = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                             env=env)
    killed_pid = None
    lines: list[str] = []
    timeout_s = 900.0
    try:
        # line 1 is the machine-readable membership (pids + urls)
        first = child.stdout.readline()
        lines.append(first)
        members = json.loads(first)["fleet_workers"]
        victim = members[0]
        deadline = time.monotonic() + timeout_s / 2
        # kill INSIDE the storm: wait for observed traffic on the
        # victim (the fleet_bench discipline — evidence, not a sleep)
        while time.monotonic() < deadline and child.poll() is None:
            try:
                status, body = get_probe(victim["url"], 0.5)
                q = body.get("queue", {})
                if status == 200 and (q.get("depth", 0)
                                      + q.get("inflight", 0)) > 0:
                    break
            except WorkerTransportError:
                pass
            time.sleep(0.02)
        time.sleep(0.5)  # let the storm build before the kill
        killed_pid = victim["pid"]
        print(f"tail_bench: SIGKILL worker {victim['worker_id']} "
              f"(pid {killed_pid}) mid-storm", file=sys.stderr)
        try:
            os.kill(killed_pid, signal.SIGKILL)
        except ProcessLookupError:
            print("tail_bench: victim already gone?!", file=sys.stderr)
        out, _ = child.communicate(timeout=timeout_s)
        lines += out.splitlines()
    except subprocess.TimeoutExpired:
        child.kill()
        raise SystemExit(f"storm run hung past {timeout_s}s")
    stats = {}
    for line in lines:
        if line.startswith("{") and '"metric"' in line:
            stats = json.loads(line)
    return {"rc": child.returncode, "stats": stats, "out_csv": out_csv,
            "killed_pid": killed_pid, "tele": tele}


def shed_events_violations(tele_dir: str) -> tuple[int, int, int]:
    """(bad_rejects, bad_evicts, total shed events) over the run's
    ``router.shed_by_class`` events. A REJECT of a critical request is
    legitimate only when its ``lowest_queued`` tag says the queue held
    nothing lower at that moment; an EVICT must never name a critical
    victim at all."""
    from pertgnn_tpu.telemetry import load_events

    bad_rejects = bad_evicts = total = 0
    for fname in os.listdir(tele_dir):
        if not fname.endswith(".jsonl"):
            continue
        for ev in load_events(os.path.join(tele_dir, fname)):
            if ev["name"] != "router.shed_by_class":
                continue
            total += 1
            tags = ev.get("tags") or {}
            if tags.get("slo") != "critical":
                continue
            if tags.get("mode") == "evict":
                bad_evicts += 1
            elif tags.get("lowest_queued") != "critical":
                bad_rejects += 1
    return bad_rejects, bad_evicts, total


def cooldown_retires(tele_dir: str) -> int:
    """autoscale.retired events whose reason is the NATURAL cooldown —
    the stats total also counts close()-time force-retires, which must
    not satisfy the 'cooldown-retire observed' acceptance gate."""
    from pertgnn_tpu.telemetry import load_events

    n = 0
    for fname in os.listdir(tele_dir):
        if not fname.endswith(".jsonl"):
            continue
        for ev in load_events(os.path.join(tele_dir, fname)):
            if (ev["name"] == "autoscale.retired"
                    and (ev.get("tags") or {}).get("reason")
                    == "cooldown"):
                n += 1
    return n


def check_bit_identical_served(check: Check, out_csv: str,
                               engine) -> int:
    """Every SERVED row (finite y_pred, no error) must match the
    single-engine reference bit-for-bit — hedge winners, requeued
    retries, downgraded rungs, and spare-served rows included (padding
    invariance + identical seeded state make all of them the same
    bits). Rows with a typed error are the shed/expired population and
    carry no prediction to compare."""
    import pandas as pd

    df = pd.read_csv(out_csv)
    served = df[np.isfinite(df["y_pred"].to_numpy(np.float32))]
    uniq: dict[tuple[int, int], float] = {}
    n_bad = 0
    for eid, tsb, pred in zip(served["entry_id"], served["ts_bucket"],
                              served["y_pred"].to_numpy(np.float32)):
        key = (int(eid), int(tsb))
        if key not in uniq:
            uniq[key] = np.float32(engine.predict_microbatch(
                [key[0]], [key[1]])[0])
        if pred != uniq[key]:
            n_bad += 1
    check.expect(n_bad == 0,
                 f"{n_bad}/{len(served)} served prediction(s) not "
                 f"bit-identical to the single-engine reference")
    # a row with neither prediction nor error is a lost future
    if "error" in df.columns:
        lost = int((~np.isfinite(df["y_pred"].to_numpy(np.float32))
                    & df["error"].isna()).sum())
        check.expect(lost == 0,
                     f"{lost} row(s) with neither prediction nor typed "
                     f"error — lost futures")
    return len(served)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dryrun", action="store_true",
                   help="CI mode: shorter storm, same gates")
    p.add_argument("--base_rps", type=float, default=0.0,
                   help="override the scenario's base offered rate")
    args = p.parse_args(argv)

    check = Check()
    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="tail_bench_")
    ds, engine = build_reference(tmp)
    pop_csv, _e, _t = population_csv(ds, tmp)

    r = run_storm(tmp, pop_csv, args)
    st = r["stats"]
    check.expect(r["rc"] == 0,
                 f"storm run exited rc={r['rc']} (zero lost futures is "
                 f"exit-asserted launcher-side)")
    lg = st.get("loadgen", {})
    router = st.get("router", {})
    scale = st.get("autoscale", {})

    # 1. zero lost futures, end to end
    check.expect(lg.get("lost_futures", -1) == 0,
                 f"loadgen reported {lg.get('lost_futures')} lost "
                 f"future(s)")
    check.expect(lg.get("unresolved", -1) == 0,
                 f"{lg.get('unresolved')} future(s) unresolved at the "
                 f"tail wait")
    check.expect(st.get("served", 0) > 0, "nothing was served at all")

    # 2. bit-identical served predictions (incl. hedge winners)
    n_served = check_bit_identical_served(check, r["out_csv"], engine)

    # 3. hedging fired and won
    check.expect(router.get("hedge_fired", 0) > 0,
                 "no hedge ever fired (stragglers were injected — the "
                 "hedger is dead or the threshold never armed)")
    check.expect(router.get("hedge_won", 0) > 0,
                 "no hedge ever WON (wins are how hedging pays; the "
                 "race may be broken)")

    # 4. lowest-class-first shedding only: best_effort shed under the
    # storm, and every critical shed happened ONLY when the queue held
    # nothing lower (the per-event `lowest_queued` evidence tag) — no
    # top-class request was shed while best-effort was being admitted.
    # Eviction is lowest-class-by-construction; the gate also pins that
    # no eviction ever chose a critical victim.
    shed_by_class = router.get("shed_by_class", {})
    check.expect(shed_by_class.get("best_effort", 0) > 0,
                 f"the storm never shed best_effort traffic "
                 f"(shed_by_class={shed_by_class}) — the overload "
                 f"scenario is too gentle to gate on")
    bad_rejects, bad_evicts, n_shed_events = shed_events_violations(
        r["tele"])
    check.expect(bad_rejects == 0,
                 f"{bad_rejects} CRITICAL request(s) shed while "
                 f"lower-class traffic was queued — lowest-class-first "
                 f"is broken")
    check.expect(bad_evicts == 0,
                 f"{bad_evicts} CRITICAL request(s) EVICTED — eviction "
                 f"must only ever pick a strictly lower class")
    check.expect(n_shed_events > 0,
                 "no shed_by_class events in the JSONL at all")

    # 5. brownout + worker-side downgrade observed
    names = counters_in(r["tele"])
    check.expect("router.brownout" in names,
                 "router.brownout never fired (occupancy never crossed "
                 "the enter ratio?)")
    check.expect("serve.brownout_downgrade" in names,
                 "no worker ever served a downgraded best-effort batch")

    # 6. bounded tail for the top class
    crit = lg.get("latency_by_class", {}).get("critical", {})
    p99_bound = 8000.0 if args.dryrun else 5000.0
    check.expect(crit.get("count", 0) > 0,
                 "no critical request was served — the mix is broken")
    check.expect(crit.get("p99_ms", float("inf")) <= p99_bound,
                 f"critical p99 {crit.get('p99_ms')}ms above the "
                 f"{p99_bound:g}ms scenario bound")

    # 7. autoscale up AND cooldown-retire, warm
    check.expect(scale.get("spawned", 0) >= 1,
                 "autoscale never spawned a spare (queue wait never "
                 "crossed the up threshold?)")
    n_cooldown = cooldown_retires(r["tele"])
    check.expect(n_cooldown >= 1,
                 f"no spare was retired on COOLDOWN (retired total "
                 f"{scale.get('retired')} — a close()-time "
                 f"force-retire does not count)")
    check.expect(scale.get("spares") == [],
                 f"spares still live at exit: {scale.get('spares')}")
    for wid, body in st.get("autoscale_workers", {}).items():
        check.expect(body.get("compiles") == 0,
                     f"spare {wid} compiled {body.get('compiles')} "
                     f"rungs (want 0 — it must start WARM)")
        check.expect(bool(body.get("arena_warm")),
                     f"spare {wid} arena store cold (ingest ran)")

    # the base workers started warm too
    for wid, body in st.get("workers_ready", {}).items():
        check.expect(body.get("compiles") == 0,
                     f"worker {wid} compiled {body.get('compiles')} "
                     f"rungs (want 0)")

    # the kill was observed
    check.expect(router.get("worker_lost", 0) >= 1,
                 "the router never noticed the SIGKILLed worker")

    # 8. graftscope: complete stage chain per successful future at
    # sample rate 1.0, across the kill + hedges + spare
    scope = run_graftscope(check, "storm", r["tele"],
                           expect_ok=n_served,
                           perfetto=os.path.join(
                               tmp, "storm.perfetto.json"))

    print(json.dumps({
        "metric": "tail_invariants_ok",
        "value": int(not check.failures),
        "unit": "bool",
        "dryrun": args.dryrun,
        "results": {
            "tmp": tmp,
            "offered": lg.get("offered"),
            "served": n_served,
            "shed_by_class": shed_by_class,
            "hedge_fired": router.get("hedge_fired"),
            "hedge_won": router.get("hedge_won"),
            "requeues": router.get("requeues"),
            "worker_lost": router.get("worker_lost"),
            "killed_pid": r["killed_pid"],
            "autoscale": scale,
            "latency_by_class": lg.get("latency_by_class"),
            "lag_ms_max": lg.get("lag_ms_max"),
            "trace_attribution": scope.get("stage_ms"),
            "traces_ok": scope.get("traces_ok"),
            "trace_orphans": scope.get("orphans"),
        },
        "violations": check.failures,
        "wall_s": round(time.perf_counter() - t0, 1),
        "captured_unix_time": time.time(),
    }))
    return 1 if check.failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
