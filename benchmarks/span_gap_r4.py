"""Root-cause study: the span 20-epoch train-fit gap (VERDICT r4 #5).

Round-3 measured span train-fit ratio 1.134 at 20 epochs (CIs touch),
recovering to 1.043 at 100 epochs, and asserted "convergence-speed
artifact" without isolating a cause. The pert side got exactly this
treatment in r3 (init A/B) and it found a real bug (kernel init). This
script runs the same protocol on span graphs, in two stages:

1. `--lockstep` — UPDATE-RULE isolation: initialize both stacks from the
   SAME weights (bench.transfer_params_to_torch, the mapping pinned to
   2e-4 by the weight-transfer parity test) and train them on the SAME
   batch stream. If per-epoch losses track, the optimizer/BN/loss
   machinery is equivalent and the 20-epoch gap must come from the init
   DISTRIBUTION or batch boundaries; if they diverge, the update rule
   itself differs (bug).

2. `--init_ab` — INIT isolation: N seeds of our span model under
   init_scheme "torch" (zero biases — r3 default) vs "torch_full"
   (+ torch's U(+-1/sqrt(fan_in)) bias init — the one remaining init
   difference vs torch.nn.Linear), against N torch-baseline seeds.

Outputs one JSON line per experiment; run manually (CPU is fine):
    python benchmarks/span_gap_r4.py --lockstep
    python benchmarks/span_gap_r4.py --init_ab --seeds 8
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from pertgnn_tpu.cli.common import apply_platform_env

apply_platform_env()


def _span_setup(init_scheme: str = "torch"):
    """The quality_parity span configuration (benchmarks/run.py)."""
    from benchmarks.run import _dataset, _flagship_cfg

    cfg = _flagship_cfg(init_scheme=init_scheme)
    cfg = cfg.replace(
        graph_type="span",
        data=dataclasses.replace(cfg.data, batch_size=32),
        train=dataclasses.replace(cfg.train, epochs=20, scan_chunk=4,
                                  lr=1e-3))
    ds = _dataset(dict(num_entries=6, traces_per_entry=120, seed=5), cfg)
    return ds, cfg


def _train_fit_mae(ds, cfg, state) -> float:
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import evaluate, make_eval_step

    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                      ds.num_interfaces, ds.num_rpctypes)
    return evaluate(make_eval_step(model, cfg), state,
                    ds.batches("train"))["mae"]


def lockstep(epochs: int = 20) -> dict:
    """Same initial weights, same batches, both update rules; per-epoch
    mean train pinball loss for each stack."""
    import jax
    import jax.numpy as jnp
    import optax
    import torch

    from bench import make_torch_reference, transfer_params_to_torch
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import create_train_state, make_train_step

    ds, cfg = _span_setup()
    sample = next(ds.batches("train"))
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    state = create_train_state(model, tx, sample, cfg.train.seed)

    torch.manual_seed(0)
    tmodel, one_step_t, predict_t, to_torch = make_torch_reference(
        ds, cfg, sample.x.shape[1])
    transfer_params_to_torch(tmodel, state.params,
                             max(2, cfg.model.num_layers))
    opt_t = torch.optim.Adam(tmodel.parameters(), lr=cfg.train.lr)
    tau = cfg.train.tau

    def torch_epoch_loss(batches) -> float:
        tot = n = 0.0
        for b in batches:
            tb = to_torch(b)
            tmodel.train()
            opt_t.zero_grad()
            pred = tmodel(tb)
            e = tb["y"] / cfg.train.label_scale - pred
            mask = tb["graph_mask"].float()
            loss = (torch.maximum(tau * e, (tau - 1) * e)
                    * mask).sum() / mask.sum().clamp_min(1.0)
            loss.backward()
            opt_t.step()
            tot += float(loss) * float(mask.sum())
            n += float(mask.sum())
        return tot / max(n, 1.0)

    step = make_train_step(model, cfg, tx)
    ours_hist, torch_hist = [], []
    for epoch in range(epochs):
        batches = list(ds.batches("train", shuffle=True,
                                  seed=cfg.data.shuffle_seed + epoch))
        sums = {"qloss_sum": 0.0, "count": 0.0}
        for b in batches:
            state, m = step(state, jax.tree.map(jnp.asarray, b))
            sums["qloss_sum"] += float(m["qloss_sum"])
            sums["count"] += float(m["count"])
        # metric sums report qloss in RAW label units; the torch loop's
        # loss is in scaled space — divide ours back for a like comparison
        ours_hist.append(sums["qloss_sum"] / max(sums["count"], 1.0)
                         / cfg.train.label_scale)
        torch_hist.append(torch_epoch_loss(batches))

    ratios = [o / max(t, 1e-9) for o, t in zip(ours_hist, torch_hist)]
    return {
        "experiment": "span_lockstep_trajectory",
        "epochs": epochs,
        "ours_qloss_per_epoch": [round(v, 3) for v in ours_hist],
        "torch_qloss_per_epoch": [round(v, 3) for v in torch_hist],
        "ratio_per_epoch": [round(r, 4) for r in ratios],
        "final_ratio": round(ratios[-1], 4),
        "max_abs_log_ratio": round(
            float(np.max(np.abs(np.log(ratios)))), 4),
        "ours_trainfit_mae": round(_train_fit_mae(ds, cfg, state), 2),
    }


def init_ab(seeds: int = 8, epochs: int = 20) -> dict:
    """Our span model, N seeds per init scheme, vs N torch-baseline
    seeds; train-fit MAE mean +- CI95 per arm."""
    import torch

    from benchmarks.run import _mean_ci95
    from bench import make_torch_reference
    from pertgnn_tpu.train.loop import fit

    out = {"experiment": "span_init_ab", "seeds": seeds, "epochs": epochs}
    for scheme in ("torch", "torch_full"):
        ds, cfg = _span_setup(init_scheme=scheme)
        cfg = cfg.replace(train=dataclasses.replace(cfg.train,
                                                    epochs=epochs))
        fits = []
        for seed in range(seeds):
            c = cfg.replace(train=dataclasses.replace(cfg.train, seed=seed))
            state, _ = fit(ds, c)
            fits.append(_train_fit_mae(ds, c, state))
        mean, ci = _mean_ci95(fits)
        out[scheme] = {"trainfit_mean_mae": round(mean, 1),
                       "ci95": round(ci, 1),
                       "per_seed": [round(f, 1) for f in fits]}

    # torch baseline arm (same protocol as quality_parity)
    ds, cfg = _span_setup()
    sample = next(ds.batches("train"))
    t_fits = []
    for seed in range(seeds):
        torch.manual_seed(seed)
        _, one_step, predict, to_torch = make_torch_reference(
            ds, cfg, sample.x.shape[1])
        for epoch in range(epochs):
            for b in ds.batches("train", shuffle=True,
                                seed=cfg.data.shuffle_seed + epoch):
                one_step(to_torch(b))
        err = n = 0.0
        for b in ds.batches("train"):
            pred = predict(to_torch(b))
            mask = np.asarray(b.graph_mask)
            err += float(np.abs(pred - np.asarray(b.y))[mask].sum())
            n += float(mask.sum())
        t_fits.append(err / max(n, 1.0))
    mean, ci = _mean_ci95(t_fits)
    out["torch_baseline"] = {"trainfit_mean_mae": round(mean, 1),
                             "ci95": round(ci, 1),
                             "per_seed": [round(f, 1) for f in t_fits]}
    for scheme in ("torch", "torch_full"):
        out[f"ratio_{scheme}"] = round(
            out[scheme]["trainfit_mean_mae"]
            / max(out["torch_baseline"]["trainfit_mean_mae"], 1e-9), 3)
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lockstep", action="store_true")
    ap.add_argument("--init_ab", action="store_true")
    ap.add_argument("--seeds", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    rows = []
    t0 = time.time()
    if args.lockstep:
        rows.append(lockstep(epochs=args.epochs))
    if args.init_ab:
        rows.append(init_ab(seeds=args.seeds, epochs=args.epochs))
    for r in rows:
        r["wall_s"] = round(time.time() - t0, 1)
        print(json.dumps(r), flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write("".join(json.dumps(r) + "\n" for r in rows))


if __name__ == "__main__":
    main()
