"""Benchmark suite: the five BASELINE.json configs.

    python benchmarks/run.py --config smoke_cpu|flagship_chip|dp8|\
        deep_wide|giant_dag|ingest_pipeline
    python benchmarks/run.py --all [--out results.jsonl]

Each config prints one JSON line (same shape as bench.py). The driver's
headline bench stays bench.py; this suite covers the full BASELINE matrix
plus a host data-path config:

1. smoke_cpu      — "1-CSV subset CPU smoke test": tiny synthetic corpus
                    through CSV round-trip + full pipeline + short training;
                    reports final test MAE and graphs/s.
2. flagship_chip  — paper-default hparams (hidden 32, batch 170, pert) on
                    the available chip; training throughput (= bench.py).
3. dp8            — data-parallel over an 8-device mesh (virtual CPU devices
                    when only one real chip is visible), global batch x8;
                    reports global graphs/s and per-device efficiency.
4. deep_wide      — 8 layers, 256 hidden, 8 heads (compute stress);
                    training throughput on the chip.
5. giant_dag      — single ~5k-node PERT DAGs per batch (padding/segment-op
                    stress); throughput for segment vs fused-Pallas
                    attention paths.
+  ingest_pipeline — host data path raw spans -> packed batches, traces/s
                    (the reference's "10+ hour" offline build).
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _dataset(spec_kwargs, cfg):
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess

    data = synthetic.generate(synthetic.SyntheticSpec(**spec_kwargs))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    return build_dataset(pre, cfg)


def _flagship_cfg(**model_overrides):
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, TrainConfig)
    model_kwargs = dict(hidden_channels=32, num_layers=3)
    model_kwargs.update(model_overrides)
    return Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=170),
        model=ModelConfig(**model_kwargs),
        train=TrainConfig(lr=3e-4, label_scale=1000.0, scan_chunk=8),
        graph_type="pert",
    )


def _train_throughput(ds, cfg, steps: int = 160) -> float:
    """graphs/s of the scan-fused train step on this backend."""
    import jax
    import jax.numpy as jnp
    import optax

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_chunk_iter, create_train_state,
                                        make_train_chunk)

    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    host = list(itertools.islice(ds.batches("train"),
                                 cfg.train.scan_chunk))
    graphs_per_chunk = sum(int(b.graph_mask.sum()) for b in host)
    chunk_batch = next(_chunk_iter(iter(host), cfg.train.scan_chunk))
    b0 = jax.tree.map(lambda a: jnp.asarray(a[0]), chunk_batch)
    state = create_train_state(model, tx, b0, cfg.train.seed)
    chunk = make_train_chunk(model, cfg, tx)
    state, m = chunk(state, chunk_batch)
    jax.block_until_ready(m["qloss_sum"])
    n_chunks = max(1, steps // cfg.train.scan_chunk)
    t0 = time.perf_counter()
    for _ in range(n_chunks):
        state, m = chunk(state, chunk_batch)
    jax.block_until_ready(m["qloss_sum"])
    return n_chunks * graphs_per_chunk / (time.perf_counter() - t0)


def smoke_cpu() -> dict:
    """Config 1: CSV round-trip + full pipeline + short training (any
    backend; the driver's config names a CPU host)."""
    import tempfile

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.io import load_raw_csvs
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.train.loop import fit

    cfg = _flagship_cfg()
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, batch_size=32),
        train=dataclasses.replace(cfg.train, epochs=5, scan_chunk=4))
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_entries=4, traces_per_entry=60, seed=3))
    with tempfile.TemporaryDirectory() as d:
        synthetic.write_csvs(data, d, shards=3)      # "1-CSV subset" shape
        spans, resources = load_raw_csvs(d)
    pre = preprocess(spans, resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    t0 = time.perf_counter()
    _, history = fit(ds, cfg)
    dt = time.perf_counter() - t0
    last = history[-1]
    return {"metric": "smoke_test_mae", "value": round(last["test_mae"], 3),
            "unit": "ms", "graphs_per_s": round(last["graphs_per_s"], 1),
            "epochs": len(history), "wall_s": round(dt, 1),
            "converged": bool(last["train_qloss"]
                              < history[0]["train_qloss"])}


def flagship_chip() -> dict:
    cfg = _flagship_cfg()
    ds = _dataset(dict(num_microservices=60, num_entries=8,
                       patterns_per_entry=4, traces_per_entry=400, seed=42),
                  cfg)
    gps = _train_throughput(ds, cfg)
    return {"metric": "flagship_train_graphs_per_s", "value": round(gps, 1),
            "unit": "graphs/s", "config": "hidden32 L3 batch170 pert"}


def dp8() -> dict:
    """Config 3: 8-way data parallelism, global batch x8."""
    import jax

    if len(jax.devices()) < 8:
        raise SystemExit(
            "dp8 needs 8 devices; run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "JAX_PLATFORMS=cpu for the virtual-mesh variant")
    import jax.numpy as jnp
    import optax

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.parallel.data_parallel import (
        make_sharded_train_step, shard_batch, stack_batches)
    from pertgnn_tpu.parallel.mesh import batch_shardings, make_mesh
    from pertgnn_tpu.train.loop import create_train_state

    cfg = _flagship_cfg()
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, batch_size=24))
    ds = _dataset(dict(num_microservices=60, num_entries=8,
                       patterns_per_entry=4, traces_per_entry=200, seed=42),
                  cfg)
    mesh = make_mesh(data=8, model=1, devices=jax.devices()[:8])
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    host = list(ds.batches("train"))
    glob = stack_batches((host * 8)[:8])   # 8 shards, repeat if few
    graphs = int(glob.graph_mask.sum())
    state = create_train_state(model, tx, glob, cfg.train.seed)
    step, sh_state = make_sharded_train_step(model, cfg, tx, mesh, state)
    b_sh = batch_shardings(mesh)
    sharded = shard_batch(glob, mesh, b_sh)
    sh_state, m = step(sh_state, sharded)
    jax.block_until_ready(m["qloss_sum"])
    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        sh_state, m = step(sh_state, sharded)
    jax.block_until_ready(m["qloss_sum"])
    gps = iters * graphs / (time.perf_counter() - t0)
    return {"metric": "dp8_global_train_graphs_per_s",
            "value": round(gps, 1), "unit": "graphs/s",
            "devices": 8, "backend": jax.default_backend()}


def deep_wide() -> dict:
    """Config 4: 8 layers, 256 hidden, 8 heads."""
    cfg = _flagship_cfg(hidden_channels=256, num_layers=8, num_heads=8)
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, batch_size=64),
        train=dataclasses.replace(cfg.train, scan_chunk=4))
    ds = _dataset(dict(num_microservices=60, num_entries=8,
                       patterns_per_entry=4, traces_per_entry=200, seed=42),
                  cfg)
    gps = _train_throughput(ds, cfg, steps=40)
    return {"metric": "deep_wide_train_graphs_per_s",
            "value": round(gps, 1), "unit": "graphs/s",
            "config": "hidden256 L8 H8 batch64 pert"}


def giant_dag() -> dict:
    """Config 5: ~5k-node PERT DAGs, one graph per batch; segment vs Pallas
    attention paths."""
    cfg = _flagship_cfg()
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, batch_size=1),
                      train=dataclasses.replace(cfg.train, scan_chunk=2))
    ds = _dataset(dict(num_microservices=1600, num_entries=2,
                       patterns_per_entry=1,
                       pattern_size_range=(1200, 1500),  # pert expands ~4x
                       traces_per_entry=30, seed=7), cfg)
    sample = next(ds.batches("train"))
    nodes, edges = sample.x.shape[0], sample.senders.shape[0]
    out = {"metric": "giant_dag_train_graphs_per_s", "unit": "graphs/s",
           "padded_nodes": nodes, "padded_edges": edges}
    gps = _train_throughput(ds, cfg, steps=16)
    out["value"] = round(gps, 2)
    cfg_p = cfg.replace(model=dataclasses.replace(
        cfg.model, use_pallas_attention=True))
    out["pallas_graphs_per_s"] = round(_train_throughput(ds, cfg_p,
                                                         steps=16), 2)
    return out


def ingest_pipeline() -> dict:
    """Host data-path throughput: raw spans -> preprocess -> graphs ->
    mixtures -> packed batches. The reference's equivalent (offline
    data-list build) takes "10+ hours" for a 100k-trace subsample
    (README.md:12, pert_gnn.py:176-188) ~= 2.8 traces/s of per-trace
    Python loops; this measures the vectorized + native replacement."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.native import bindings

    cfg = _flagship_cfg()
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=120, num_entries=24, patterns_per_entry=5,
        traces_per_entry=800, seed=11))
    n_traces = int(data.spans["traceid"].nunique())
    t0 = time.perf_counter()
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    t_pre = time.perf_counter() - t0
    t0 = time.perf_counter()
    ds = build_dataset(pre, cfg)
    n_batches = sum(1 for split in ("train", "valid", "test")
                    for _ in ds.batches(split))
    t_build = time.perf_counter() - t0
    total = t_pre + t_build
    return {"metric": "ingest_traces_per_s",
            "value": round(n_traces / total, 1), "unit": "traces/s",
            "raw_traces": n_traces, "preprocess_s": round(t_pre, 2),
            "dataset_build_s": round(t_build, 2),
            "native_available": bindings.available(),
            "packed_batches": n_batches,
            "vs_reference_estimate": round((n_traces / total) / 2.8, 1)}


CONFIGS = {
    "ingest_pipeline": ingest_pipeline,
    "smoke_cpu": smoke_cpu,
    "flagship_chip": flagship_chip,
    "dp8": dp8,
    "deep_wide": deep_wide,
    "giant_dag": giant_dag,
}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", choices=sorted(CONFIGS))
    p.add_argument("--all", action="store_true")
    p.add_argument("--out", default="",
                   help="also write the JSON rows to this file (jsonl)")
    args = p.parse_args(argv)
    names = sorted(CONFIGS) if args.all else [args.config]
    if names == [None]:
        p.error("pass --config NAME or --all")
    rows = []
    for name in names:
        try:
            row = CONFIGS[name]()
            row["config_name"] = name
        except SystemExit as e:
            row = {"config_name": name, "skipped": str(e)}
        except Exception as e:  # one failing config must not kill the suite
            row = {"config_name": name,
                   "failed": f"{type(e).__name__}: {e}"}
        rows.append(row)
        print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as f:
            f.write("".join(json.dumps(r) + "\n" for r in rows))


if __name__ == "__main__":
    main()
