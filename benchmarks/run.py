"""Benchmark suite: the five BASELINE.json configs.

    python benchmarks/run.py --config smoke_cpu|flagship_chip|dp8|\
        deep_wide|giant_dag|ingest_pipeline|quality_parity
    python benchmarks/run.py --all [--out results.jsonl]

Each config prints one JSON line (same shape as bench.py). The driver's
headline bench stays bench.py; this suite covers the full BASELINE matrix
plus a host data-path config:

1. smoke_cpu      — "1-CSV subset CPU smoke test": tiny synthetic corpus
                    through CSV round-trip + full pipeline + short training;
                    reports final test MAE and graphs/s.
2. flagship_chip  — paper-default hparams (hidden 32, batch 170, pert) on
                    the available chip; training throughput (= bench.py).
3. dp8            — data-parallel over an 8-device mesh (virtual CPU devices
                    when only one real chip is visible), global batch x8;
                    reports global graphs/s and per-device efficiency.
4. deep_wide      — 8 layers, 256 hidden, 8 heads (compute stress);
                    training throughput on the chip.
5. giant_dag      — single ~5k-node PERT DAGs per batch (padding/segment-op
                    stress); throughput for segment vs fused-Pallas
                    attention paths.
+  ingest_pipeline — host data path raw spans -> packed batches, traces/s
                    (the reference's "10+ hour" offline build).
+  quality_parity  — test MAE, ours vs the torch re-implementation of the
                    reference stack, median over 3 seeds each.
+  scan_chunk_sweep — lax.scan fusion depth {8,16,32,64} on the flagship,
                    cached-chunk replay, interleaved; picks the dispatch-
                    amortization default with on-chip evidence.
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from pertgnn_tpu.cli.common import apply_platform_env

# honor JAX_PLATFORMS=cpu + virtual-device XLA_FLAGS even when a device
# plugin (axon TPU tunnel) would otherwise win (dp8 / edge-sharded configs)
apply_platform_env()


def _dataset(spec_kwargs, cfg):
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess

    data = synthetic.generate(synthetic.SyntheticSpec(**spec_kwargs))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    return build_dataset(pre, cfg)


def _flagship_cfg(**model_overrides):
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, TrainConfig)
    model_kwargs = dict(hidden_channels=32, num_layers=3)
    model_kwargs.update(model_overrides)
    return Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=170),
        model=ModelConfig(**model_kwargs),
        train=TrainConfig(lr=3e-4, label_scale=1000.0, scan_chunk=8),
        graph_type="pert",
    )


def _train_throughput(ds, cfg, steps: int = 160,
                      edge_shard_mesh=None, with_mfu: bool = False):
    """graphs/s of the scan-fused train step on this backend.

    Returns the float, or (with_mfu=True) a dict adding `mfu_pct` and
    `flops_per_graph` from XLA cost analysis (VERDICT r2 #4)."""
    import jax
    import jax.numpy as jnp
    import optax

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_chunk_iter, create_train_state,
                                        make_train_chunk)
    from pertgnn_tpu.utils.flops import (compiled_cost, mbu, mfu,
                                         roofline_graphs_per_s)

    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes,
                       edge_shard_mesh=edge_shard_mesh)
    tx = optax.adam(cfg.train.lr)
    host = list(itertools.islice(ds.batches("train"),
                                 cfg.train.scan_chunk))
    graphs_per_chunk = sum(int(b.graph_mask.sum()) for b in host)
    chunk_batch = next(_chunk_iter(iter(host), cfg.train.scan_chunk))
    b0 = jax.tree.map(lambda a: jnp.asarray(a[0]), chunk_batch)
    state = create_train_state(model, tx, b0, cfg.train.seed)
    chunk = make_train_chunk(model, cfg, tx)
    flops_per_graph = bytes_per_graph = None
    if with_mfu:
        fl, by = compiled_cost(chunk, state, chunk_batch)
        flops_per_graph = (fl / graphs_per_chunk) if fl else None
        bytes_per_graph = (by / graphs_per_chunk) if by else None
    state, m = chunk(state, chunk_batch)
    jax.block_until_ready(m["qloss_sum"])
    n_chunks = max(1, steps // cfg.train.scan_chunk)
    t0 = time.perf_counter()
    for _ in range(n_chunks):
        state, m = chunk(state, chunk_batch)
    jax.block_until_ready(m["qloss_sum"])
    gps = n_chunks * graphs_per_chunk / (time.perf_counter() - t0)
    if not with_mfu:
        return gps
    eff = mfu(gps, flops_per_graph)
    bw_eff = mbu(gps, bytes_per_graph)
    roof = roofline_graphs_per_s(flops_per_graph, bytes_per_graph)
    return {"graphs_per_s": gps,
            "mfu_pct": round(100 * eff, 2) if eff is not None else None,
            "mbu_pct": round(100 * bw_eff, 2) if bw_eff is not None else None,
            "flops_per_graph": (round(flops_per_graph)
                                if flops_per_graph else None),
            "bytes_per_graph": (round(bytes_per_graph)
                                if bytes_per_graph else None),
            "ai_flops_per_byte": (round(flops_per_graph / bytes_per_graph, 1)
                                  if flops_per_graph and bytes_per_graph
                                  else None),
            "roofline_graphs_per_s": (round(roof) if roof else None)}


def smoke_cpu() -> dict:
    """Config 1: CSV round-trip + full pipeline + short training (any
    backend; the driver's config names a CPU host)."""
    import tempfile

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.io import load_raw_csvs
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.train.loop import fit

    cfg = _flagship_cfg()
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, batch_size=32),
        train=dataclasses.replace(cfg.train, epochs=5, scan_chunk=4))
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_entries=4, traces_per_entry=60, seed=3))
    with tempfile.TemporaryDirectory() as d:
        synthetic.write_csvs(data, d, shards=3)      # "1-CSV subset" shape
        spans, resources = load_raw_csvs(d)
    pre = preprocess(spans, resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    t0 = time.perf_counter()
    _, history = fit(ds, cfg)
    dt = time.perf_counter() - t0
    last = history[-1]
    return {"metric": "smoke_test_mae", "value": round(last["test_mae"], 3),
            "unit": "ms", "graphs_per_s": round(last["graphs_per_s"], 1),
            "epochs": len(history), "wall_s": round(dt, 1),
            "converged": bool(last["train_qloss"]
                              < history[0]["train_qloss"])}


def flagship_chip() -> dict:
    cfg = _flagship_cfg()
    ds = _dataset(dict(num_microservices=60, num_entries=8,
                       patterns_per_entry=4, traces_per_entry=400, seed=42),
                  cfg)
    r = _train_throughput(ds, cfg, with_mfu=True)
    return {"metric": "flagship_train_graphs_per_s",
            "value": round(r["graphs_per_s"], 1),
            "unit": "graphs/s", "config": "hidden32 L3 batch170 pert",
            **{k: r[k] for k in ("mfu_pct", "mbu_pct", "flops_per_graph",
                                 "bytes_per_graph", "ai_flops_per_byte",
                                 "roofline_graphs_per_s")}}


def dp8() -> dict:
    """Config 3: 8-way data parallelism, global batch x8."""
    import jax

    if len(jax.devices()) < 8:
        raise SystemExit(
            "dp8 needs 8 devices; run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "JAX_PLATFORMS=cpu for the virtual-mesh variant")
    import optax

    from pertgnn_tpu.batching.materialize import build_device_arenas
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.parallel.data_parallel import (
        compact_batch_shardings, make_sharded_train_step_compact,
        shard_batch, stack_batches, stack_compact_batches)
    from pertgnn_tpu.parallel.mesh import make_mesh, replicated_sharding
    from pertgnn_tpu.train.loop import create_train_state

    cfg = _flagship_cfg()
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, batch_size=24))
    ds = _dataset(dict(num_microservices=60, num_entries=8,
                       patterns_per_entry=4, traces_per_entry=200, seed=42),
                  cfg)
    mesh = make_mesh(data=8, model=1, devices=jax.devices()[:8])
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    # the production SPMD path: O(graphs) compact recipes, shard-local
    # device expansion, global batch materialized from replicated arenas
    cbs = list(ds.compact_batches("train"))
    glob_cb = stack_compact_batches((cbs * 8)[:8])  # 8 shards
    graphs = int(glob_cb.graph_mask.sum())
    init = stack_batches([next(ds.batches("train"))] * 8)
    state = create_train_state(model, tx, init, cfg.train.seed)
    dev = build_device_arenas(ds.arena(), ds.feat_arena(),
                              sharding=replicated_sharding(mesh))
    step, sh_state = make_sharded_train_step_compact(
        model, cfg, tx, mesh, state, dev,
        ds.budget.max_nodes, ds.budget.max_edges)
    sharded = shard_batch(glob_cb, mesh, compact_batch_shardings(mesh))
    sh_state, m = step(sh_state, sharded)
    jax.block_until_ready(m["qloss_sum"])
    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        sh_state, m = step(sh_state, sharded)
    jax.block_until_ready(m["qloss_sum"])
    gps = iters * graphs / (time.perf_counter() - t0)
    return {"metric": "dp8_global_train_graphs_per_s",
            "value": round(gps, 1), "unit": "graphs/s",
            "devices": 8, "path": "compact-SPMD",
            "backend": jax.default_backend()}


def deep_wide(bf16: bool = False) -> dict:
    """Config 4: 8 layers, 256 hidden, 8 heads.

    Besides MFU/MBU from XLA cost analysis, emits an ANALYTIC HBM bound:
    per-step traffic = 8x param bytes (params+grads+Adam m/v, read+write)
    + batch input bytes, assuming activations stay VMEM-resident (one
    (4.3k, 256) f32 activation is 4.25 MiB vs v5e's 128 MiB VMEM). XLA's
    `bytes accessed` multiply-counts every op's operands in the optimized
    HLO (~1.25 GB/step here — more than even spill-everything traffic),
    so a roofline built on it is a gross UNDER-estimate of achievable
    graphs/s; `mbu_pct` computed from it can exceed 100. The r2
    216-256k graphs/s row sits at 44-52% of the analytic bound —
    consistent — which adjudicates the RESULTS.md "5x over roofline"
    suspicion in favor of the measurement (VERDICT r4 #4)."""
    import jax

    cfg = _flagship_cfg(hidden_channels=256, num_layers=8, num_heads=8,
                        bf16_activations=bf16)
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, batch_size=64),
        train=dataclasses.replace(cfg.train, scan_chunk=4))
    ds = _dataset(dict(num_microservices=60, num_entries=8,
                       patterns_per_entry=4, traces_per_entry=200, seed=42),
                  cfg)
    r = _train_throughput(ds, cfg, steps=40, with_mfu=True)

    import optax

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import create_train_state
    from pertgnn_tpu.utils.flops import peak_hbm_bw_per_chip

    sample = next(ds.batches("train"))
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    # eval_shape: parameter COUNT only — no device init, no Adam state
    shapes = jax.eval_shape(
        lambda: create_train_state(model, optax.adam(cfg.train.lr),
                                   sample, cfg.train.seed))
    nparams = sum(int(np.prod(p.shape))
                  for p in jax.tree.leaves(shapes.params))
    graphs = int(sample.graph_mask.sum())
    batch_bytes = sum(np.asarray(getattr(sample, f)).nbytes
                      for f in sample._fields)
    per_graph_analytic = (nparams * 4 * 8 + batch_bytes) / graphs
    bw = peak_hbm_bw_per_chip()
    analytic = (bw / per_graph_analytic) if bw else None
    return {"metric": "deep_wide_train_graphs_per_s",
            "value": round(r["graphs_per_s"], 1), "unit": "graphs/s",
            "config": ("hidden256 L8 H8 batch64 pert"
                       + (" bf16" if bf16 else "")),
            "params_m": round(nparams / 1e6, 2),
            "analytic_hbm_bytes_per_graph": round(per_graph_analytic),
            "analytic_roofline_graphs_per_s": (round(analytic)
                                               if analytic else None),
            "analytic_mbu_pct": (
                round(100 * r["graphs_per_s"] / analytic, 1)
                if analytic else None),
            **{k: r[k] for k in ("mfu_pct", "mbu_pct", "flops_per_graph",
                                 "bytes_per_graph", "ai_flops_per_byte",
                                 "roofline_graphs_per_s")}}


def deep_wide_bf16() -> dict:
    """Config 4 with bf16 activations — the advertised ~2x bytes lever."""
    return deep_wide(bf16=True)


def giant_dag() -> dict:
    """Config 5: ~5k-node PERT DAGs, one graph per batch; segment vs Pallas
    attention paths."""
    cfg = _flagship_cfg()
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, batch_size=1),
                      train=dataclasses.replace(cfg.train, scan_chunk=2))
    ds = _dataset(dict(num_microservices=1600, num_entries=2,
                       patterns_per_entry=1,
                       pattern_size_range=(1200, 1500),  # pert expands ~4x
                       traces_per_entry=30, seed=7), cfg)
    sample = next(ds.batches("train"))
    nodes, edges = sample.x.shape[0], sample.senders.shape[0]
    out = {"metric": "giant_dag_train_graphs_per_s", "unit": "graphs/s",
           "padded_nodes": nodes, "padded_edges": edges}
    r = _train_throughput(ds, cfg, steps=16, with_mfu=True)
    out["value"] = round(r["graphs_per_s"], 2)
    out["mfu_pct"] = r["mfu_pct"]
    out["flops_per_graph"] = r["flops_per_graph"]
    cfg_p = cfg.replace(model=dataclasses.replace(
        cfg.model, use_pallas_attention=True))
    out["pallas_graphs_per_s"] = round(_train_throughput(ds, cfg_p,
                                                         steps=16), 2)
    # edge-sharded ("sequence parallel") path: the layers shard the edge
    # set over an 8-device mesh (graph_shard.sharded_edge_attention)
    import jax

    if len(jax.devices()) >= 8 and edges % 8 == 0:
        from pertgnn_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(data=8, model=1, devices=jax.devices()[:8])
        out["edge_sharded_graphs_per_s"] = round(
            _train_throughput(ds, cfg, steps=16, edge_shard_mesh=mesh), 2)
        out["edge_sharded_devices"] = 8
    else:
        out["edge_sharded"] = ("skipped: needs 8 devices (run under "
                               "XLA_FLAGS=--xla_force_host_platform_device_"
                               "count=8 JAX_PLATFORMS=cpu)")
    return out


def ingest_pipeline() -> dict:
    """Host data-path throughput: raw spans -> preprocess -> graphs ->
    mixtures -> packed batches. The reference's equivalent (offline
    data-list build) takes "10+ hours" for a 100k-trace subsample
    (README.md:12, pert_gnn.py:176-188) ~= 2.8 traces/s of per-trace
    Python loops; this measures the vectorized + native replacement."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.native import bindings

    cfg = _flagship_cfg()
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=120, num_entries=24, patterns_per_entry=5,
        traces_per_entry=800, seed=11))
    n_traces = int(data.spans["traceid"].nunique())
    t0 = time.perf_counter()
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    t_pre = time.perf_counter() - t0
    t0 = time.perf_counter()
    ds = build_dataset(pre, cfg)
    n_batches = sum(1 for split in ("train", "valid", "test")
                    for _ in ds.batches(split))
    t_build = time.perf_counter() - t0
    total = t_pre + t_build
    return {"metric": "ingest_traces_per_s",
            "value": round(n_traces / total, 1), "unit": "traces/s",
            "raw_traces": n_traces, "preprocess_s": round(t_pre, 2),
            "dataset_build_s": round(t_build, 2),
            "native_available": bindings.available(),
            "packed_batches": n_batches,
            "vs_reference_estimate": round((n_traces / total) / 2.8, 1)}


def _mean_ci95(xs) -> tuple[float, float]:
    xs = np.asarray(xs, dtype=np.float64)
    half = 1.96 * xs.std(ddof=1) / np.sqrt(len(xs)) if len(xs) > 1 else 0.0
    return float(xs.mean()), float(half)


def _ratio_ci95(num, den, n_boot: int = 20_000,
                seed: int = 0) -> tuple[float, float]:
    """Percentile-bootstrap 95% CI of mean(num)/mean(den).

    Both arms are independent seed samples, so resample each independently
    (the r3/r4 span saga showed per-seed spread ~±15%; a normal-approx CI
    on the ratio would lean on a delta-method linearization the sample
    sizes here don't justify)."""
    num = np.asarray(num, dtype=np.float64)
    den = np.asarray(den, dtype=np.float64)
    rng = np.random.default_rng(seed)
    i = rng.integers(0, len(num), size=(n_boot, len(num)))
    j = rng.integers(0, len(den), size=(n_boot, len(den)))
    ratios = num[i].mean(axis=1) / np.maximum(den[j].mean(axis=1), 1e-9)
    return (float(np.percentile(ratios, 2.5)),
            float(np.percentile(ratios, 97.5)))


def parity_protocol(epochs: int):
    """The quality-parity training protocol shared by `quality_parity`
    and `lever_r5.py` (so cross-table comparisons can't silently
    diverge): flagship hparams at batch 32 / lr 1e-3 / scan_chunk 4 and
    the fixed 6-entry corpus. Returns (base_cfg, dataset_spec_kwargs)."""
    base = _flagship_cfg()
    base = base.replace(
        data=dataclasses.replace(base.data, batch_size=32),
        train=dataclasses.replace(base.train, epochs=epochs, scan_chunk=4,
                                  lr=1e-3))
    return base, dict(num_entries=6, traces_per_entry=120, seed=5)


def quality_parity(seeds: int | None = None) -> dict:
    """Model-quality parity: our model vs the torch re-implementation of
    the reference's stack (bench.make_torch_reference), trained with the
    same hparams, epochs, and per-epoch shuffled+repacked batch stream,
    compared on held-out test MAE. The reference publishes no quality
    numbers (BASELINE.md), so this is the measurable stand-in.

    Statistics (VERDICT r2 #8): BOTH graph types, `seeds` seeds each side,
    mean +- 95% CI (normal approx). Init schemes differ by framework and
    are part of what each stack ships: flax here uses glorot-uniform for
    attention projections / lecun-normal Dense heads / N(0,1) embeddings;
    torch uses kaiming-uniform(a=sqrt5) Linear and N(0,1) embeddings —
    the seed spread absorbs init variance on both sides."""
    import bench as bench_mod
    import torch

    from pertgnn_tpu.train.loop import fit

    if seeds is None:
        seeds = int(os.environ.get("QUALITY_SEEDS", "10"))
    epochs = int(os.environ.get("QUALITY_EPOCHS", "20"))
    # Seed-shard + graph-type knobs so a 24-seed/arm run (VERDICT r4 #3)
    # can fan out across worker processes; a merge step (quality_merge.py)
    # recomputes the cross-shard statistics from the per-seed arrays.
    seed_offset = int(os.environ.get("QUALITY_SEED_OFFSET", "0"))
    gtypes = tuple(
        g.strip() for g in os.environ.get("QUALITY_GRAPH_TYPES",
                                          "pert,span").split(",") if g.strip())
    bad = set(gtypes) - {"pert", "span"}
    if bad or not gtypes:
        raise SystemExit(f"QUALITY_GRAPH_TYPES must name pert and/or span, "
                         f"got {bad or 'nothing'}")
    base, spec_kwargs = parity_protocol(epochs)
    out = {"metric": "quality_parity_test_mae_ratio",
           "unit": "ours/torch ratio of mean test MAE (lower is better)",
           "epochs": epochs, "seeds_per_side": seeds,
           "seed_offset": seed_offset,
           "init_note": ("flax: glorot-uniform attn / lecun-normal heads; "
                         "torch: kaiming-uniform(a=sqrt5) Linear; both "
                         "N(0,1) embeddings")}
    # TWO measures per graph type:
    # - test MAE: the reference's own protocol — but its POSITIONAL
    #   entry-grouped split (pert_gnn.py:196-210) puts mostly-UNSEEN
    #   entries in the test tail, so test predictions ride on untrained
    #   entry embeddings: structurally noise-dominated (documented in
    #   tests/test_train.py too). Reported with CI, interpreted with care.
    # - train-fit MAE: how well each stack fits the same data — low-noise
    #   and the meaningful head-to-head of the two implementations.
    for gtype in gtypes:
        cfg = base.replace(graph_type=gtype)
        ds = _dataset(spec_kwargs, cfg)
        sample = next(ds.batches("train"))

        def eval_split(predict, to_torch, split):
            err = n = 0.0
            for b in ds.batches(split):
                pred = predict(to_torch(b))
                mask = np.asarray(b.graph_mask)
                err += float(np.abs(pred - np.asarray(b.y))[mask].sum())
                n += float(mask.sum())
            return err / max(n, 1.0)

        ours, ours_fit = [], []
        for seed in range(seed_offset, seed_offset + seeds):
            c = cfg.replace(train=dataclasses.replace(cfg.train, seed=seed))
            state, history = fit(ds, c)
            ours.append(history[-1]["test_mae"])
            # train-fit: eval-mode MAE over the train split
            from pertgnn_tpu.models.pert_model import make_model
            from pertgnn_tpu.train.loop import evaluate, make_eval_step
            model = make_model(c.model, ds.num_ms, ds.num_entries,
                               ds.num_interfaces, ds.num_rpctypes)
            m = evaluate(make_eval_step(model, c), state,
                         ds.batches("train"))
            ours_fit.append(m["mae"])

        torch_maes, torch_fit = [], []
        for seed in range(seed_offset, seed_offset + seeds):
            torch.manual_seed(seed)
            _, one_step, predict, to_torch = bench_mod.make_torch_reference(
                ds, cfg, sample.x.shape[1])
            for epoch in range(epochs):
                # same stream fit() trains on: shuffled + greedily
                # re-packed per epoch (batching/dataset.py)
                for b in ds.batches("train", shuffle=True,
                                    seed=cfg.data.shuffle_seed + epoch):
                    one_step(to_torch(b))
            torch_maes.append(eval_split(predict, to_torch, "test"))
            torch_fit.append(eval_split(predict, to_torch, "train"))

        o_mean, o_ci = _mean_ci95(ours)
        t_mean, t_ci = _mean_ci95(torch_maes)
        of_mean, of_ci = _mean_ci95(ours_fit)
        tf_mean, tf_ci = _mean_ci95(torch_fit)
        r_lo, r_hi = _ratio_ci95(ours_fit, torch_fit)
        out[gtype] = {
            # pre-registered equivalence test (VERDICT r4 #3): the 95%
            # bootstrap CI of the train-fit ratio-of-means must sit inside
            # [0.93, 1.07] for the stacks to be declared quality-equivalent
            "trainfit_ratio_ci95": [round(r_lo, 3), round(r_hi, 3)],
            "trainfit_equivalent_0.93_1.07": bool(r_lo >= 0.93
                                                  and r_hi <= 1.07),
            # the one-sided question the parity claim actually needs:
            # can we reject "ours fits >= 7% worse"?
            "trainfit_noninferior_1.07": bool(r_hi <= 1.07),
            "trainfit_ours_per_seed": [round(m, 1) for m in ours_fit],
            "trainfit_torch_per_seed": [round(m, 1) for m in torch_fit],
            "test_ours_mean_mae": round(o_mean, 1),
            "test_ours_ci95": round(o_ci, 1),
            "test_torch_mean_mae": round(t_mean, 1),
            "test_torch_ci95": round(t_ci, 1),
            "test_ratio_of_means": round(o_mean / max(t_mean, 1e-9), 3),
            "trainfit_ours_mean_mae": round(of_mean, 1),
            "trainfit_ours_ci95": round(of_ci, 1),
            "trainfit_torch_mean_mae": round(tf_mean, 1),
            "trainfit_torch_ci95": round(tf_ci, 1),
            "trainfit_ratio_of_means": round(of_mean / max(tf_mean, 1e-9),
                                             3),
            "test_ours_per_seed": [round(m, 1) for m in ours],
            "test_torch_per_seed": [round(m, 1) for m in torch_maes],
        }
    lead = gtypes[0]
    out["value"] = out[lead]["test_ratio_of_means"]
    if "pert" in out:
        out["trainfit_ratio_pert"] = out["pert"]["trainfit_ratio_of_means"]
    return out


def pallas_crossover() -> dict:
    """Measured crossover table: fused Pallas edge-attention kernel vs the
    XLA sorted-segment path, forward+backward, across average degree and
    hidden size (VERDICT r2 #9 — the kernel's keep/demote evidence).

    Interleaved timing: for each cell, alternating (segment, pallas)
    windows x3, median reported, so tunnel variance hits both alike."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        raise SystemExit("pallas_crossover needs the TPU chip (the kernel "
                         "runs in slow interpret mode elsewhere)")

    from pertgnn_tpu.ops.pallas_attention import edge_attention
    from pertgnn_tpu.ops.segment import segment_edge_attention

    N = 4096
    rows = []
    for deg in (1, 2, 4, 8):
        for hidden in (32, 128):
            E = N * deg
            rng = np.random.default_rng(deg * 1000 + hidden)
            q = jnp.asarray(rng.normal(size=(N, 1, hidden)), jnp.float32)
            k = jnp.asarray(rng.normal(size=(E, 1, hidden)), jnp.float32)
            v = jnp.asarray(rng.normal(size=(E, 1, hidden)), jnp.float32)
            rcv = jnp.asarray(np.sort(rng.integers(0, N, E)), jnp.int32)
            msk = jnp.ones(E, bool)

            def seg_loss(q, k, v):
                return segment_edge_attention(q, k, v, rcv, msk, N).sum()

            def pal_loss(q, k, v):
                return edge_attention(q, k, v, rcv, msk, N,
                                      assume_sorted=True).sum()

            fns = {}
            for name, f in (("segment", seg_loss), ("pallas", pal_loss)):
                g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
                out = g(q, k, v)
                jax.block_until_ready(out[0])  # compile+warm

                def window(g=g):
                    t0 = time.perf_counter()
                    for _ in range(30):
                        out = g(q, k, v)
                    jax.block_until_ready(out[0])
                    return (time.perf_counter() - t0) / 30 * 1e3  # ms

                fns[name] = window
            seg_ms, pal_ms = [], []
            for _ in range(3):  # interleave
                seg_ms.append(fns["segment"]())
                pal_ms.append(fns["pallas"]())
            s, p = float(np.median(seg_ms)), float(np.median(pal_ms))
            rows.append({"avg_degree": deg, "hidden": hidden,
                         "segment_ms": round(s, 3), "pallas_ms": round(p, 3),
                         "pallas_speedup": round(s / p, 2)})
    wins = [r for r in rows if r["pallas_speedup"] > 1.05]
    return {"metric": "pallas_crossover_min_winning_degree",
            "value": min((r["avg_degree"] for r in wins), default=-1),
            "unit": "avg degree where the fused kernel first wins >5%",
            "nodes": N, "table": rows}


def scan_chunk_sweep() -> dict:
    """Scan-fusion depth sweep on the flagship model: how many train steps
    to fuse into one `lax.scan` program per dispatch.

    Per-program dispatch is the dominant per-step overhead on the
    tunnel-attached chip (~300 us dispatch vs ~60 us compute per step —
    RESULTS.md notes; scan fusion at depth 16 took the r1 flagship from
    410k to 2.37M graphs/s on cached chunks). This row measures
    cached-chunk replay graphs/s at scan_chunk in {8, 16, 32, 64} so the
    flagship default is picked with on-chip evidence. Depths are
    interleaved round-robin x3 so tunnel variance hits all alike. Runs on
    any backend (stamped); only the chip rows carry decision weight —
    CPU has no dispatch gap to amortize.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from bench import _window_runner
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_host_chunks, create_train_state,
                                        make_train_chunk)

    depths = (8, 16, 32, 64)
    base = _flagship_cfg()
    ds = _dataset(dict(num_microservices=60, num_entries=8,
                       patterns_per_entry=4, traces_per_entry=3000,
                       seed=42), base)
    # pack the deepest chunk's batches ONCE and slice per depth (the
    # unshuffled train stream is deterministic, so host64[:d] is exactly
    # what a per-depth islice would repack at much more host cost)
    host64 = list(itertools.islice(ds.batches("train"), max(depths)))
    if len(host64) < max(depths):
        # padded filler chunks would bill compute for zero graphs and
        # understate the deep depths — refuse rather than mis-measure
        raise SystemExit(f"scan_chunk_sweep needs {max(depths)} real "
                         f"train batches, got {len(host64)}")
    model = make_model(base.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(base.train.lr)
    runners = {}
    for d in depths:
        cfg = base.replace(train=dataclasses.replace(base.train,
                                                     scan_chunk=d))
        host = host64[:d]
        graphs = sum(int(b.graph_mask.sum()) for b in host)
        chunk_batch = jax.tree.map(jnp.asarray,
                                   next(_host_chunks(iter(host), d)))
        b0 = jax.tree.map(lambda a: jnp.asarray(a[0]), chunk_batch)
        state = create_train_state(model, tx, b0, cfg.train.seed)
        chunk = make_train_chunk(model, cfg, tx)
        runners[d] = _window_runner(chunk, state, chunk_batch, graphs)

    windows = {d: [] for d in depths}
    for _ in range(3):
        for d in depths:
            windows[d].append(runners[d]())
    meds = {d: float(np.median(w)) for d, w in windows.items()}
    best = max(meds, key=meds.get)
    return {"metric": "scan_chunk_sweep_graphs_per_s",
            "value": round(meds[best], 1), "unit": "graphs/s",
            "best_scan_chunk": best,
            "medians": {str(d): round(v, 1) for d, v in meds.items()},
            "windows": {str(d): [round(x, 1) for x in w]
                        for d, w in windows.items()},
            "best_over_chunk8": round(meds[best] / meds[8], 3),
            "chunk16_over_chunk8": round(meds[16] / meds[8], 3)}


CONFIGS = {
    "ingest_pipeline": ingest_pipeline,
    "scan_chunk_sweep": scan_chunk_sweep,
    "quality_parity": quality_parity,
    "smoke_cpu": smoke_cpu,
    "flagship_chip": flagship_chip,
    "dp8": dp8,
    "deep_wide": deep_wide,
    "deep_wide_bf16": deep_wide_bf16,
    "giant_dag": giant_dag,
    "pallas_crossover": pallas_crossover,
}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", choices=sorted(CONFIGS))
    p.add_argument("--all", action="store_true")
    p.add_argument("--out", default="",
                   help="also write the JSON rows to this file (jsonl)")
    args = p.parse_args(argv)
    names = sorted(CONFIGS) if args.all else [args.config]
    if names == [None]:
        p.error("pass --config NAME or --all")
    import subprocess

    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        commit = None
    rows = []
    for name in names:
        try:
            row = CONFIGS[name]()
            row["config_name"] = name
            # every ledger row is backend-honest (VERDICT r4 #6): which
            # backend produced it, at which commit
            if "backend" not in row:
                import jax
                row["backend"] = jax.default_backend()
        except SystemExit as e:
            row = {"config_name": name, "skipped": str(e)}
        except Exception as e:  # lint: allow-silent-except — failure lands in the printed row, one failing config must not kill the suite
            row = {"config_name": name,
                   "failed": f"{type(e).__name__}: {e}"}
        row["commit"] = commit
        rows.append(row)
        print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as f:
            f.write("".join(json.dumps(r) + "\n" for r in rows))


if __name__ == "__main__":
    main()
