"""Adjudicate the round's pre-registered on-chip criteria from capture
artifacts — executable form of the RESULTS.md round-4/5 registrations, so
the verdicts are mechanical the moment data exists.

    python benchmarks/adjudicate.py          # reads the default artifacts

Criteria (registered before any of the data existed):
1. fit_over_ceiling >= 0.9 on the flagship (vs 0.659 at ab21126, the
   pre-staging measurement the staged-recipe fix targets); the
   staged/unstaged A/B in the same capture attributes the change.
2. deep_wide measured graphs/s inside 40-60% of the ANALYTIC HBM bound
   (491k graphs/s; RESULTS.md "Round-4 adjudication") — confirming the
   traffic model over XLA's bytes-accessed roofline. Outside the band,
   the model must be revised in writing.
3. pallas_crossover regenerated on-chip against the current fused
   backward: promote the kernel (auto-enable in its winning region) if
   it wins >=1.1x anywhere real, else it stays demoted (delete remains
   on the table).
4. scan_chunk_sweep: adopt the best depth as the flagship default if it
   beats the current default by >=5% on-chip (else folklore stands).

Exit 0 always (reporting tool); prints one JSON verdict line per
criterion plus a human summary.
"""

from __future__ import annotations

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))
PIN = os.path.join(HERE, "last_good_tpu.json")
ROWS = os.path.join(HERE, "tpu_r5_results.jsonl")

DEEP_WIDE_ANALYTIC_BOUND = 491_000  # graphs/s; RESULTS.md round-4
DEEP_WIDE_BAND = (0.40, 0.60)
FIT_OVER_CEILING_TARGET = 0.90
R3_FIT_OVER_CEILING = 0.659  # bench_r3_tpu.json @ ab21126
SWEEP_ADOPT_MARGIN = 1.05
PALLAS_PROMOTE_MARGIN = 1.10


def _load_rows() -> dict[str, dict]:
    rows: dict[str, dict] = {}
    try:
        with open(ROWS) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                name = d.get("config_name")
                # keep the LAST successful row per config (retries append)
                if name and "failed" not in d and "skipped" not in d:
                    rows[name] = d
    except OSError:
        pass
    return rows


def main() -> None:
    verdicts = []

    import time

    pin = None
    try:
        with open(PIN) as f:
            pin = json.load(f)
    except (OSError, ValueError):
        pass
    # Freshness: unlike the watcher's 24h re-run rule (a live-window
    # decision), adjudication happens whenever the round ends — a pin
    # captured days ago by the auto-commit machinery is still THIS
    # round's data. Only a pin clearly predating the round (>7 days) is
    # rejected; the age and commit are always reported for the reader.
    if pin:
        age_h = (time.time() - pin.get("captured_unix_time", 0)) / 3600
    if pin and age_h >= 7 * 24:
        verdicts.append({
            "criterion": "flagship fit_over_ceiling >= 0.9",
            "verdict": "NO DATA (pin predates the round — "
                       f"captured {age_h:.0f}h ago)",
            "stale_pin_commit": pin.get("commit")})
        pin = None
    elif pin and pin.get("backend") == "tpu":
        pin["captured_age_h"] = round(age_h, 1)
        foc = pin.get("fit_over_ceiling")
        verdicts.append({
            "criterion": "flagship fit_over_ceiling >= 0.9",
            "measured": foc,
            "baseline_r3": R3_FIT_OVER_CEILING,
            "staged_over_unstaged": pin.get("staged_over_unstaged"),
            "partial_capture": bool(pin.get("partial_capture")),
            "commit": pin.get("commit"),
            "captured_age_h": pin.get("captured_age_h"),
            "verdict": (None if foc is None
                        else "PASS" if foc >= FIT_OVER_CEILING_TARGET
                        else "FAIL"),
        })
    else:
        verdicts.append({
            "criterion": "flagship fit_over_ceiling >= 0.9",
            "verdict": "NO DATA (no on-chip pin this round)"})

    rows = _load_rows()

    dw = rows.get("deep_wide")
    if dw and dw.get("backend") == "tpu":
        gps = dw.get("value")
        # the row carries its own analytic bound (live peak-bw + param
        # count); the registered 491k constant is the fallback
        bound = dw.get("analytic_roofline_graphs_per_s") \
            or DEEP_WIDE_ANALYTIC_BOUND
        frac = gps / bound if gps else None
        lo, hi = DEEP_WIDE_BAND
        verdicts.append({
            "criterion": "deep_wide in 40-60% of analytic HBM bound",
            "measured_graphs_per_s": gps,
            "fraction_of_bound": round(frac, 3) if frac else None,
            "band": DEEP_WIDE_BAND,
            "verdict": (None if frac is None else
                        "PASS (traffic model confirmed)" if lo <= frac <= hi
                        else "OUTSIDE BAND — revise the model in writing"),
        })
    else:
        verdicts.append({
            "criterion": "deep_wide in 40-60% of analytic HBM bound",
            "verdict": "NO DATA (no on-chip deep_wide row)"})

    pc = rows.get("pallas_crossover")
    if pc and pc.get("backend") == "tpu":
        cells = pc.get("table") or []
        best = None
        for c in cells:
            r = c.get("pallas_speedup")
            if r and (best is None or r > best[0]):
                best = (r, c)
        verdicts.append({
            "criterion": f"pallas wins >={PALLAS_PROMOTE_MARGIN}x anywhere",
            "best_ratio": round(best[0], 3) if best else None,
            "best_cell": best[1] if best else None,
            "verdict": (None if best is None else
                        "PROMOTE (auto-enable in winning region)"
                        if best[0] >= PALLAS_PROMOTE_MARGIN
                        else "STAY DEMOTED (deletion on the table)"),
        })
    else:
        verdicts.append({
            "criterion": f"pallas wins >={PALLAS_PROMOTE_MARGIN}x anywhere",
            "verdict": "NO DATA (no on-chip crossover row)"})

    sw = rows.get("scan_chunk_sweep")
    if sw and sw.get("backend") == "tpu":
        meds = {int(k): v for k, v in (sw.get("medians") or {}).items()}
        cur = meds.get(16)  # bench.py flagship default
        best_d = max(meds, key=meds.get) if meds else None
        ratio = (meds[best_d] / cur) if (best_d and cur) else None
        verdicts.append({
            "criterion": "adopt best scan_chunk if >=5% over default 16",
            "medians": meds, "best_depth": best_d,
            "best_over_default": round(ratio, 3) if ratio else None,
            "verdict": (None if ratio is None else
                        f"ADOPT scan_chunk={best_d}"
                        if ratio >= SWEEP_ADOPT_MARGIN and best_d != 16
                        else "KEEP 16"),
        })
    else:
        verdicts.append({
            "criterion": "adopt best scan_chunk if >=5% over default 16",
            "verdict": "NO DATA (no on-chip sweep row)"})

    for v in verdicts:
        print(json.dumps(v))
    # a None verdict means an artifact existed but lacked the measured
    # field (e.g. a pre-fit-window salvage) — that is still no data
    n_data = sum(1 for v in verdicts
                 if v["verdict"] is not None
                 and not str(v["verdict"]).startswith("NO DATA"))
    print(f"# {n_data}/{len(verdicts)} criteria have usable on-chip data")


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # `| head` closing the pipe is fine
        pass
