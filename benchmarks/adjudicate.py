"""Adjudicate the round's pre-registered on-chip criteria from capture
artifacts — executable form of the RESULTS.md round-4/5 registrations, so
the verdicts are mechanical the moment data exists.

    python benchmarks/adjudicate.py          # reads the default artifacts

Criteria (registered before any of the data existed):
1. fit_over_ceiling >= 0.9 on the flagship (vs 0.659 at ab21126, the
   pre-staging measurement the staged-recipe fix targets); the
   staged/unstaged A/B in the same capture attributes the change.
2. deep_wide measured graphs/s inside 40-60% of the ANALYTIC HBM bound
   (491k graphs/s; RESULTS.md "Round-4 adjudication") — confirming the
   traffic model over XLA's bytes-accessed roofline. Outside the band,
   the model must be revised in writing.
3. pallas_crossover regenerated on-chip against the current fused
   backward: promote the kernel (auto-enable in its winning region) if
   it wins >=1.1x anywhere real, else it stays demoted (delete remains
   on the table).
4. scan_chunk_sweep: adopt the best depth as the flagship default if it
   beats the current default by >=5% on-chip (else folklore stands).

Exit 0 always (reporting tool); prints one JSON verdict line per
criterion plus a human summary. The default report also reads the
graftprobe capture journal (ISSUE 17) when present: tunnel-availability
statistics (probe attempts, healthy-window count + duration histogram)
and any journaled wedge stages, so "the tunnel never opened" is a
measured claim per round.

    python benchmarks/adjudicate.py --stitch [--journal PATH]

is the journal reader: it assembles one valid interleaved fit/ceiling
measurement out of the journal's <=60 s window fragments
(telemetry/capture.stitch_windows — staleness-bounded, spread over the
union) and prints the result JSON with `stitched: true` + per-window
provenance. Unlike the report mode it exits 1 on a refused stitch
(incompatible commits/configs/backends, too few windows): the watcher
and CI branch on that.
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
PIN = os.path.join(HERE, "last_good_tpu.json")
ROWS = os.path.join(HERE, "tpu_r5_results.jsonl")
JOURNAL = os.environ.get("BENCH_CAPTURE_JOURNAL",
                         os.path.join(HERE, "capture_journal.jsonl"))

DEEP_WIDE_ANALYTIC_BOUND = 491_000  # graphs/s; RESULTS.md round-4
DEEP_WIDE_BAND = (0.40, 0.60)
FIT_OVER_CEILING_TARGET = 0.90
R3_FIT_OVER_CEILING = 0.659  # bench_r3_tpu.json @ ab21126
SWEEP_ADOPT_MARGIN = 1.05
PALLAS_PROMOTE_MARGIN = 1.10


def _load_rows() -> dict[str, dict]:
    rows: dict[str, dict] = {}
    try:
        with open(ROWS) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                name = d.get("config_name")
                # keep the LAST successful row per config (retries append)
                if name and "failed" not in d and "skipped" not in d:
                    rows[name] = d
    except OSError:
        pass
    return rows


def _capture_module():
    """Import pertgnn_tpu.telemetry.capture from the repo checkout
    (same sys.path bootstrap as kernel_bench.py — this script runs as
    `python benchmarks/adjudicate.py`, not as a package module)."""
    repo = os.path.dirname(HERE)
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from pertgnn_tpu.telemetry import capture as cap
    return cap


def _journal_path(argv: list[str]) -> str:
    if "--journal" in argv:
        return argv[argv.index("--journal") + 1]
    return JOURNAL


def stitch_main(argv: list[str]) -> int:
    """`adjudicate.py --stitch`: assemble + print the stitched result
    JSON from the capture journal. Exit 0 with `stitched: true` on
    success; exit 1 with a one-line refusal JSON otherwise."""
    path = _journal_path(argv)
    cap = _capture_module()
    if not os.path.exists(path):
        print(json.dumps({"stitched": False,
                          "refused": f"no capture journal at {path}"}))
        return 1
    journal = cap.CaptureJournal(path)
    records = journal.records()
    try:
        st = cap.stitch_windows(records)
    except cap.StitchRefused as e:
        print(json.dumps({"stitched": False, "refused": str(e),
                          "skipped_journal_lines": journal.skipped_lines}))
        return 1
    import bench
    result = bench._assemble_from_stitch(st)
    if journal.skipped_lines:
        result["skipped_journal_lines"] = journal.skipped_lines
    print(json.dumps(result))
    return 0


def _availability_verdict(path: str) -> dict | None:
    """Tunnel-availability statistics from the journaled probe attempts
    (ISSUE 17 small fix) — None when there is no journal to read. Wedge
    stages ride along: the round report should name exactly where a
    capture died, not just that it did."""
    if not os.path.exists(path):
        return None
    try:
        cap = _capture_module()
        records = cap.CaptureJournal(path).records()
    except Exception as e:  # a broken journal must not kill the report
        print(f"WARNING: capture journal unreadable "
              f"({type(e).__name__}: {e})", file=sys.stderr)
        return {"criterion": "tunnel availability",
                "verdict": f"UNREADABLE journal ({type(e).__name__}: {e})"}
    avail = cap.probe_availability(records)
    out = {"criterion": "tunnel availability", **avail}
    wedges = cap.wedged_stages(records)
    if wedges:
        out["wedged_stages"] = wedges
    if not avail["probe_attempts"]:
        out["verdict"] = "NO DATA (no journaled probe attempts)"
    else:
        out["verdict"] = (f"{avail['availability_pct']}% of "
                          f"{avail['probe_attempts']} probes healthy "
                          f"across {avail['healthy_windows']} window(s)")
    return out


def main() -> None:
    verdicts = []

    import time

    pin = None
    try:
        with open(PIN) as f:
            pin = json.load(f)
    except (OSError, ValueError):
        pass
    # Freshness: unlike the watcher's 24h re-run rule (a live-window
    # decision), adjudication happens whenever the round ends — a pin
    # captured days ago by the auto-commit machinery is still THIS
    # round's data. Only a pin clearly predating the round (>7 days) is
    # rejected; the age and commit are always reported for the reader.
    if pin:
        age_h = (time.time() - pin.get("captured_unix_time", 0)) / 3600
    if pin and age_h >= 7 * 24:
        verdicts.append({
            "criterion": "flagship fit_over_ceiling >= 0.9",
            "verdict": "NO DATA (pin predates the round — "
                       f"captured {age_h:.0f}h ago)",
            "stale_pin_commit": pin.get("commit")})
        pin = None
    elif pin and pin.get("backend") == "tpu":
        pin["captured_age_h"] = round(age_h, 1)
        foc = pin.get("fit_over_ceiling")
        verdicts.append({
            "criterion": "flagship fit_over_ceiling >= 0.9",
            "measured": foc,
            "baseline_r3": R3_FIT_OVER_CEILING,
            "staged_over_unstaged": pin.get("staged_over_unstaged"),
            "partial_capture": bool(pin.get("partial_capture")),
            "commit": pin.get("commit"),
            "captured_age_h": pin.get("captured_age_h"),
            "verdict": (None if foc is None
                        else "PASS" if foc >= FIT_OVER_CEILING_TARGET
                        else "FAIL"),
        })
    else:
        verdicts.append({
            "criterion": "flagship fit_over_ceiling >= 0.9",
            "verdict": "NO DATA (no on-chip pin this round)"})

    rows = _load_rows()

    dw = rows.get("deep_wide")
    if dw and dw.get("backend") == "tpu":
        gps = dw.get("value")
        # the row carries its own analytic bound (live peak-bw + param
        # count); the registered 491k constant is the fallback
        bound = dw.get("analytic_roofline_graphs_per_s") \
            or DEEP_WIDE_ANALYTIC_BOUND
        frac = gps / bound if gps else None
        lo, hi = DEEP_WIDE_BAND
        verdicts.append({
            "criterion": "deep_wide in 40-60% of analytic HBM bound",
            "measured_graphs_per_s": gps,
            "fraction_of_bound": round(frac, 3) if frac else None,
            "band": DEEP_WIDE_BAND,
            "verdict": (None if frac is None else
                        "PASS (traffic model confirmed)" if lo <= frac <= hi
                        else "OUTSIDE BAND — revise the model in writing"),
        })
    else:
        verdicts.append({
            "criterion": "deep_wide in 40-60% of analytic HBM bound",
            "verdict": "NO DATA (no on-chip deep_wide row)"})

    pc = rows.get("pallas_crossover")
    if pc and pc.get("backend") == "tpu":
        cells = pc.get("table") or []
        best = None
        for c in cells:
            r = c.get("pallas_speedup")
            if r and (best is None or r > best[0]):
                best = (r, c)
        verdicts.append({
            "criterion": f"pallas wins >={PALLAS_PROMOTE_MARGIN}x anywhere",
            "best_ratio": round(best[0], 3) if best else None,
            "best_cell": best[1] if best else None,
            "verdict": (None if best is None else
                        "PROMOTE (auto-enable in winning region)"
                        if best[0] >= PALLAS_PROMOTE_MARGIN
                        else "STAY DEMOTED (deletion on the table)"),
        })
    else:
        verdicts.append({
            "criterion": f"pallas wins >={PALLAS_PROMOTE_MARGIN}x anywhere",
            "verdict": "NO DATA (no on-chip crossover row)"})

    sw = rows.get("scan_chunk_sweep")
    if sw and sw.get("backend") == "tpu":
        meds = {int(k): v for k, v in (sw.get("medians") or {}).items()}
        cur = meds.get(16)  # bench.py flagship default
        best_d = max(meds, key=meds.get) if meds else None
        ratio = (meds[best_d] / cur) if (best_d and cur) else None
        verdicts.append({
            "criterion": "adopt best scan_chunk if >=5% over default 16",
            "medians": meds, "best_depth": best_d,
            "best_over_default": round(ratio, 3) if ratio else None,
            "verdict": (None if ratio is None else
                        f"ADOPT scan_chunk={best_d}"
                        if ratio >= SWEEP_ADOPT_MARGIN and best_d != 16
                        else "KEEP 16"),
        })
    else:
        verdicts.append({
            "criterion": "adopt best scan_chunk if >=5% over default 16",
            "verdict": "NO DATA (no on-chip sweep row)"})

    avail = _availability_verdict(JOURNAL)
    if avail is not None:
        verdicts.append(avail)

    for v in verdicts:
        print(json.dumps(v))
    # a None verdict means an artifact existed but lacked the measured
    # field (e.g. a pre-fit-window salvage) — that is still no data
    n_data = sum(1 for v in verdicts
                 if v["verdict"] is not None
                 and not str(v["verdict"]).startswith("NO DATA"))
    print(f"# {n_data}/{len(verdicts)} criteria have usable on-chip data")


if __name__ == "__main__":
    try:
        if "--stitch" in sys.argv[1:]:
            raise SystemExit(stitch_main(sys.argv[1:]))
        main()
    except BrokenPipeError:  # `| head` closing the pipe is fine
        pass
