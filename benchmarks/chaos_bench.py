"""Chaos benchmark: concurrent serving traffic under injected faults.

Drives the full queue+engine stack (pertgnn_tpu/serve/) through each
deterministic fault in pertgnn_tpu/testing/faults.py and EXIT-CODE
ASSERTS the reliability invariants (docs/RELIABILITY.md):

- **dispatch exception** (a persistently poisoned request): bisect
  quarantine pins the failure on the offender; every innocent request's
  prediction is BIT-IDENTICAL to a fault-free run; ``serve.poisoned`` /
  ``serve.quarantined`` land in the telemetry JSONL.
- **device wedge** (a dispatch that stalls past the watchdog timeout):
  the watchdog trips, one rebuild-from-AOT-store recovery retries the
  batch — NO caller loses a prediction to a transient wedge;
  ``serve.watchdog_trip`` / ``serve.recovered`` recorded.
- **NaN output**: the guard quarantines the batch and the bisect retry
  returns real values — garbage NEVER reaches a caller;
  ``serve.nan_outputs`` recorded.
- **overload**: admission control sheds with QueueFull instead of
  growing the pending set without bound; every ADMITTED request still
  resolves bit-identically; ``serve.shed`` recorded.
- **SIGTERM drain** (real serve_main child process): admissions stop,
  in-flight batches flush, the process exits 0 with "drained": true —
  preemption of a serving replica is not a crash. The child's
  --health_port readiness probe is polled to time the signal.

Wall-clock numbers are REPORTED in the JSON; invariants live in the
exit code (same split as coldstart_bench.py). One JSON line on stdout.

CPU by default (deterministic here); faults are seeded and
occurrence-addressed, so the fire pattern is reproducible run to run.

    python benchmarks/chaos_bench.py [--quick] [--skip_drain]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def build_workload(traces_per_entry: int = 120):
    """A heterogeneous-shape synthetic corpus (several ladder rungs) and
    a fresh-init engine — fault semantics are weight-independent."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, ServeConfig, TrainConfig)
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import restore_target_state

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=32),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(label_scale=1000.0),
        serve=ServeConfig(bucket_growth=2.0, max_graphs_per_batch=8,
                          min_bucket_nodes=128, min_bucket_edges=128),
        graph_type="pert",
    )
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=40, num_entries=8, patterns_per_entry=3,
        pattern_size_range=(3, 18), traces_per_entry=traces_per_entry,
        seed=42))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    _model, state = restore_target_state(ds, cfg)
    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    return ds, cfg, state, engine


def request_stream(ds, n: int):
    """(entries, ts_buckets): every split concatenated (entry variety —
    the poison scenario needs innocents) and tiled to n requests."""
    e = np.concatenate([np.asarray(s.entry_ids, np.int64)
                        for s in ds.splits.values()])
    t = np.concatenate([np.asarray(s.ts_buckets, np.int64)
                        for s in ds.splits.values()])
    # splits are entry-ordered; a seeded shuffle keeps a short stream
    # entry-diverse (deterministic: same stream every run)
    perm = np.random.default_rng(0).permutation(len(e))
    e, t = e[perm], t[perm]
    reps = -(-n // len(e))
    e, t = np.tile(e, reps)[:n], np.tile(t, reps)[:n]
    assert len(np.unique(e)) >= 2, "chaos stream needs innocent entries"
    return e, t


def reference_preds(engine, entries, ts_buckets) -> np.ndarray:
    """Fault-free per-request predictions, each served alone. Padding
    invariance (tests/test_serve.py) makes these bit-identical to ANY
    coalescing the queue applies under faults — the comparison anchor."""
    return np.asarray([
        float(engine.predict_microbatch(entries[i:i + 1],
                                        ts_buckets[i:i + 1])[0])
        for i in range(len(entries))], np.float32)


def drive(queue, entries, ts_buckets, concurrency: int = 8,
          timeout: float = 120.0):
    """Concurrent clients over the queue; returns (preds, errors) with
    errors[i] = exception class name (preds[i] NaN) for failed requests.
    Every request RESOLVES within `timeout` — a hang fails the bench."""
    preds = np.full(len(entries), np.nan, np.float32)
    errors: dict[int, str] = {}
    lock = threading.Lock()

    def client(indices):
        for i in indices:
            try:
                preds[i] = queue.predict(int(entries[i]),
                                         int(ts_buckets[i]),
                                         timeout=timeout)
            except Exception as exc:  # lint: allow-silent-except — the outcome IS the record: errors[i] feeds the scenario asserts
                with lock:
                    errors[i] = type(exc).__name__
    threads = [threading.Thread(
        target=client, args=(range(t, len(entries), concurrency),),
        name=f"chaos-client-{t}")
        for t in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return preds, errors


class Check:
    def __init__(self):
        self.failures: list[str] = []

    def expect(self, cond: bool, what: str):
        if not cond:
            self.failures.append(what)
            print(f"CHAOS FAIL: {what}", file=sys.stderr)


def counters_in(telemetry_dir: str) -> set:
    from pertgnn_tpu.telemetry import load_events
    names = set()
    for fname in os.listdir(telemetry_dir):
        if fname.endswith(".jsonl"):
            for ev in load_events(os.path.join(telemetry_dir, fname)):
                names.add(ev["name"])
    return names


def scenario_dispatch_error(ds, engine, ref, entries, tsb, check):
    from pertgnn_tpu.serve.queue import MicrobatchQueue
    from pertgnn_tpu.testing import faults
    from pertgnn_tpu.testing.faults import FaultPlan, FaultSpec

    poison = int(entries[0])
    faults.install(FaultPlan([FaultSpec(site="serve.dispatch",
                                        kind="error", entry_id=poison)]))
    try:
        with MicrobatchQueue(engine, flush_deadline_ms=5,
                             dispatch_timeout_s=30.0,
                             quarantine_threshold=3) as q:
            preds, errors = drive(q, entries, tsb)
            stats = q.stats_dict()
    finally:
        faults.install(None)
    innocent = entries != poison
    check.expect(not np.isnan(preds[innocent]).any(),
                 "dispatch_error: an innocent request lost its prediction")
    check.expect((preds[innocent] == ref[innocent]).all(),
                 "dispatch_error: innocent predictions not bit-identical")
    check.expect(all(np.isnan(preds[i]) for i in range(len(entries))
                     if entries[i] == poison),
                 "dispatch_error: the poisoned entry produced predictions")
    check.expect(stats["poisoned"] >= 1,
                 "dispatch_error: no poisoned-request isolation recorded")
    check.expect(poison in stats["quarantined_entries"],
                 "dispatch_error: repeat offender not quarantined")
    return {"errors": len(errors), "poisoned": stats["poisoned"],
            "quarantined": stats["quarantined_entries"]}


def scenario_wedge(ds, engine, ref, entries, tsb, check):
    from pertgnn_tpu.serve.queue import MicrobatchQueue
    from pertgnn_tpu.testing import faults
    from pertgnn_tpu.testing.faults import FaultPlan, FaultSpec

    faults.install(FaultPlan([FaultSpec(site="serve.dispatch",
                                        kind="wedge", wedge_s=3.0,
                                        nth=(2,))]))
    t0 = time.perf_counter()
    try:
        with MicrobatchQueue(engine, flush_deadline_ms=5,
                             dispatch_timeout_s=0.5) as q:
            preds, errors = drive(q, entries, tsb)
            stats = q.stats_dict()
    finally:
        faults.install(None)
    wall = time.perf_counter() - t0
    check.expect(not errors and not np.isnan(preds).any(),
                 f"wedge: {len(errors)} request(s) lost to a TRANSIENT "
                 f"wedge (watchdog must recover and retry)")
    check.expect((preds == ref).all(),
                 "wedge: surviving predictions not bit-identical")
    check.expect(stats["watchdog_trips"] >= 1,
                 "wedge: watchdog never tripped")
    check.expect(stats["recovered"] >= 1, "wedge: engine never recovered")
    check.expect(engine.healthy, "wedge: engine left unhealthy")
    return {"wall_s": round(wall, 2), **{k: stats[k] for k in
            ("watchdog_trips", "recovered")}}


def scenario_nan(ds, engine, ref, entries, tsb, check):
    from pertgnn_tpu.serve.queue import MicrobatchQueue
    from pertgnn_tpu.testing import faults
    from pertgnn_tpu.testing.faults import FaultPlan, FaultSpec

    nans0 = engine.nan_outputs
    faults.install(FaultPlan([FaultSpec(site="serve.dispatch",
                                        kind="nan", nth=(2,))]))
    try:
        with MicrobatchQueue(engine, flush_deadline_ms=5,
                             dispatch_timeout_s=30.0) as q:
            preds, errors = drive(q, entries, tsb)
    finally:
        faults.install(None)
    check.expect(not errors and not np.isnan(preds).any(),
                 "nan: a caller received garbage or lost its prediction")
    check.expect((preds == ref).all(),
                 "nan: quarantine-retried predictions not bit-identical")
    check.expect(engine.nan_outputs == nans0 + 1,
                 "nan: the output guard never fired")
    return {"nan_outputs": engine.nan_outputs - nans0,
            "errors": len(errors)}


def scenario_overload(ds, engine, ref, entries, tsb, check):
    from pertgnn_tpu.serve.queue import MicrobatchQueue

    with MicrobatchQueue(engine, flush_deadline_ms=20, max_pending=4,
                         dispatch_timeout_s=30.0) as q:
        preds, errors = drive(q, entries, tsb, concurrency=16)
        stats = q.stats_dict()
    # the shed error is Shed (a QueueFull subclass) since the SLO-class
    # admission landed; pre-SLO "QueueFull" accepted for old captures
    shed = [i for i, name in errors.items()
            if name in ("QueueFull", "Shed")]
    check.expect(len(shed) == len(errors),
                 f"overload: non-shed errors {set(errors.values())}")
    check.expect(stats["shed"] >= 1,
                 "overload: admission control never shed under pressure")
    admitted = np.ones(len(entries), bool)
    admitted[shed] = False
    check.expect(not np.isnan(preds[admitted]).any(),
                 "overload: an ADMITTED request lost its prediction")
    check.expect((preds[admitted] == ref[admitted]).all(),
                 "overload: admitted predictions not bit-identical")
    return {"shed": stats["shed"], "admitted": int(admitted.sum()),
            "requests": len(entries)}


def scenario_drain(check, quick: bool) -> dict:
    """Real serve_main child: train a tiny checkpoint, start serving a
    long stream, poll /healthz until ready, SIGTERM, assert exit 0 +
    drained:true + all in-flight futures resolved."""
    from pertgnn_tpu.cli import train_main

    tmp = tempfile.mkdtemp(prefix="chaos_drain_")
    ckpt = os.path.join(tmp, "ckpt")
    art = os.path.join(tmp, "art")
    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5", "--label_scale", "1000",
              "--artifact_dir", art, "--checkpoint_dir", ckpt]
    train_main.main([*common, "--epochs", "1"])
    # a stream long enough that the child cannot finish before SIGTERM
    n_req = 5_000 if quick else 50_000
    req_csv = os.path.join(tmp, "req.csv")
    import pandas as pd
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import Config, IngestConfig, TrainConfig
    from pertgnn_tpu.ingest.io import load_artifacts
    pre, table = load_artifacts(art)
    child_cfg = Config(ingest=IngestConfig(min_traces_per_entry=5),
                       train=TrainConfig(label_scale=1000.0))
    child_ds = build_dataset(pre, child_cfg, table)
    s = child_ds.splits["train"]
    eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
    pd.DataFrame({"entry_id": [eid] * n_req,
                  "ts_bucket": [tsb] * n_req}).to_csv(req_csv, index=False)
    port = 18000 + (os.getpid() % 2000)
    child = subprocess.Popen(
        [sys.executable, "-m", "pertgnn_tpu.cli.serve_main", *common,
         "--requests", req_csv, "--concurrency", "2",
         "--flush_deadline_ms", "5", "--health_port", str(port),
         "--out", os.path.join(tmp, "served.csv")],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    ready = False
    deadline = time.monotonic() + 600
    url = f"http://127.0.0.1:{port}/healthz"
    while time.monotonic() < deadline and child.poll() is None:
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                if resp.status == 200:
                    ready = True
                    break
        except OSError:
            time.sleep(0.5)
    check.expect(ready, "drain: /healthz never answered 200")
    time.sleep(1.0)  # let it serve mid-stream before preemption
    child.send_signal(signal.SIGTERM)
    try:
        out, _ = child.communicate(timeout=300)
        rc = child.returncode
    except subprocess.TimeoutExpired:
        child.kill()
        out, rc = "", -9
    check.expect(rc == 0, f"drain: serve_main exited {rc}, not 0")
    stats = {}
    for line in out.strip().splitlines():
        if line.startswith("{"):
            stats = json.loads(line)
    check.expect(bool(stats.get("drained")),
                 "drain: child did not report drained:true (finished "
                 "before the signal? raise the request count)")
    served = stats.get("served", 0)
    check.expect(0 < served < n_req,
                 f"drain: served={served} of {n_req} — expected a "
                 f"mid-stream preemption")
    return {"rc": rc, "served": served, "requests": n_req,
            "health_probe": ready}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true",
                   help="smaller request streams (CI-sized)")
    p.add_argument("--skip_drain", action="store_true",
                   help="skip the subprocess SIGTERM scenario")
    p.add_argument("--requests", type=int, default=0,
                   help="requests per in-process scenario (0 = auto)")
    args = p.parse_args(argv)

    from pertgnn_tpu import telemetry

    tele_dir = tempfile.mkdtemp(prefix="chaos_tele_")
    telemetry.configure(tele_dir, level="trace",
                        run_meta={"bench": "chaos"})
    check = Check()
    t0 = time.perf_counter()
    ds, cfg, state, engine = build_workload()
    n = args.requests or (48 if args.quick else 160)
    entries, tsb = request_stream(ds, n)
    ref = reference_preds(engine, entries, tsb)

    results = {}
    results["dispatch_error"] = scenario_dispatch_error(
        ds, engine, ref, entries, tsb, check)
    results["wedge"] = scenario_wedge(ds, engine, ref, entries, tsb, check)
    results["nan"] = scenario_nan(ds, engine, ref, entries, tsb, check)
    results["overload"] = scenario_overload(ds, engine, ref, entries, tsb,
                                            check)
    telemetry.get_bus().flush()
    names = counters_in(tele_dir)
    for counter in ("serve.shed", "serve.poisoned", "serve.quarantined",
                    "serve.watchdog_trip", "serve.recovered",
                    "serve.nan_outputs"):
        check.expect(counter in names,
                     f"telemetry: {counter} missing from the JSONL")
    if not args.skip_drain:
        results["drain"] = scenario_drain(check, args.quick)
    telemetry.shutdown()

    print(json.dumps({
        "metric": "chaos_invariants_ok",
        "value": int(not check.failures),
        "unit": "bool",
        "requests_per_scenario": n,
        "scenarios": results,
        "violations": check.failures,
        "wall_s": round(time.perf_counter() - t0, 1),
        "telemetry_dir": tele_dir,
        "captured_unix_time": time.time(),
    }))
    return 1 if check.failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
