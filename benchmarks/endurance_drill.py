"""Endurance run with a crash-resume drill (VERDICT r4 #5).

The reference loses ALL training progress on a crash — no state_dict
save anywhere in its driver (/root/reference/pert_gnn.py; SURVEY.md
§5.3/5.4). This drill proves our recovery story end to end, the rude
way:

1. CONTROL: an uninterrupted `fit()` for --epochs with per-epoch orbax
   checkpoints, history streamed to disk.
2. CRASH: the identical run in a fresh directory is `kill -9`ed the
   moment its history shows --kill-after-epoch done (so it dies mid-
   epoch, async checkpoint possibly in flight).
3. RESUME: the same command is relaunched; `CheckpointManager.
   maybe_restore` must pick up at (latest saved epoch + 1).

Asserts: the resumed history starts exactly one past the last committed
checkpoint, reaches the final epoch, and the crashed+resumed final
train qloss matches the control within --rtol (per-epoch shuffle is
seeded, so the only tolerated divergence is checkpoint-roundtrip float
noise). Prints one JSON line.

    python benchmarks/endurance_drill.py                  # CPU scale
    python benchmarks/endurance_drill.py --scale full     # chip scale
    python benchmarks/endurance_drill.py --worker ...     # (internal)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(args) -> None:
    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, TrainConfig)
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.train.checkpoint import CheckpointManager
    from pertgnn_tpu.train.loop import fit

    tpe = {"cpu": 400, "full": 12_000}[args.scale]
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=170),
        model=ModelConfig(hidden_channels=32, num_layers=3),
        train=TrainConfig(lr=3e-4, label_scale=1000.0, scan_chunk=8,
                          epochs=args.epochs),
        graph_type="pert",
    )
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=60, num_entries=8, patterns_per_entry=4,
        traces_per_entry=tpe, seed=42))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)

    ckpt = CheckpointManager(args.ckpt_dir, keep=3)
    hist_path = args.history

    def hook(epoch: int, row: dict) -> None:
        with open(hist_path, "a") as f:
            f.write(json.dumps({"epoch": epoch,
                                "train_qloss": row["train_qloss"],
                                "test_mae": row["test_mae"]}) + "\n")
            f.flush()
            os.fsync(f.fileno())

    fit(ds, cfg, checkpoint_manager=ckpt, profile_hook=hook)
    ckpt.close()


def _read_history(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _spawn(scale: str, epochs: int, ckpt_dir: str, history: str):
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--scale", scale, "--epochs", str(epochs),
         "--ckpt-dir", ckpt_dir, "--history", history],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=dict(os.environ), stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)


def _run_to_completion(scale, epochs, ckpt_dir, history, timeout_s):
    p = _spawn(scale, epochs, ckpt_dir, history)
    deadline = time.monotonic() + timeout_s
    while p.poll() is None:
        if time.monotonic() > deadline:
            p.kill()
            raise RuntimeError("worker timed out")
        time.sleep(1)
    if p.returncode != 0:
        raise RuntimeError(f"worker failed rc={p.returncode}")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--scale", choices=("cpu", "full"), default="cpu")
    ap.add_argument("--epochs", type=int, default=100)
    ap.add_argument("--kill-after-epoch", type=int, default=None,
                    help="SIGKILL once this epoch appears in the history "
                         "(default: epochs // 3)")
    ap.add_argument("--rtol", type=float, default=1e-3)
    ap.add_argument("--timeout", type=float, default=7200)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--history", default="")
    args = ap.parse_args()
    if args.worker:
        _worker(args)
        return

    kill_after = (args.epochs // 3 if args.kill_after_epoch is None
                  else args.kill_after_epoch)
    root = tempfile.mkdtemp(prefix="endurance_")
    ctrl_hist = os.path.join(root, "control.jsonl")
    crash_hist = os.path.join(root, "crash.jsonl")
    t0 = time.perf_counter()

    # 1) control
    _run_to_completion(args.scale, args.epochs,
                       os.path.join(root, "ckpt_control"), ctrl_hist,
                       args.timeout)
    control = _read_history(ctrl_hist)
    assert control and control[-1]["epoch"] == args.epochs - 1, control[-3:]

    # 2) crash: kill -9 once epoch `kill_after` is logged
    crash_ckpt = os.path.join(root, "ckpt_crash")
    p = _spawn(args.scale, args.epochs, crash_ckpt, crash_hist)
    deadline = time.monotonic() + args.timeout
    while True:
        if p.poll() is not None:
            raise RuntimeError(
                f"worker exited rc={p.returncode} before the kill point")
        hist = _read_history(crash_hist)
        if hist and hist[-1]["epoch"] >= kill_after:
            os.kill(p.pid, signal.SIGKILL)
            p.wait()
            break
        if time.monotonic() > deadline:
            p.kill()
            raise RuntimeError("crash-phase worker timed out")
        time.sleep(0.2)
    pre_kill = _read_history(crash_hist)
    killed_at = pre_kill[-1]["epoch"]

    # The last COMMITTED checkpoint (async saves may trail the history).
    # Read the directory layout directly: orbax commits a step by
    # renaming its tmp dir to the bare step number, so a numeric dir
    # without an uncommitted marker == committed. Deliberately NOT via
    # orbax in this parent: importing it touches jax backends, and the
    # axon plugin dials the (possibly wedged) relay from any process
    # without the config-update protection — the exact hang this drill's
    # first run died of.
    steps = []
    for name in os.listdir(crash_ckpt):
        full = os.path.join(crash_ckpt, name)
        if (name.isdigit() and os.path.isdir(full)
                and not any(m.startswith(("tmp", ".orbax"))
                            for m in os.listdir(full))):
            steps.append(int(name))
    latest_saved = max(steps, default=None)
    assert latest_saved is not None, "no checkpoint committed before kill"

    # 3) resume: same command, same dirs
    _run_to_completion(args.scale, args.epochs, crash_ckpt, crash_hist,
                       args.timeout)
    full = _read_history(crash_hist)
    # The resumed segment is everything appended after the kill. Split
    # by the line count captured at kill time — NOT by looking for a
    # non-increasing epoch: when the killed epoch's async checkpoint
    # committed before SIGKILL landed, resume starts at killed_at+1 and
    # the epoch sequence never decreases at the boundary.
    resumed = full[len(pre_kill):]
    assert resumed, "resumed run appended no history"
    resume_start = resumed[0]["epoch"]
    final = resumed[-1]

    ok_resume = resume_start == latest_saved + 1
    ok_final = final["epoch"] == args.epochs - 1
    ctrl_final = control[-1]["train_qloss"]
    rel = abs(final["train_qloss"] - ctrl_final) / max(abs(ctrl_final), 1e-9)
    ok_parity = rel <= args.rtol

    result = {
        "metric": "endurance_crash_resume_drill",
        "value": bool(ok_resume and ok_final and ok_parity),
        "unit": "pass",
        "scale": args.scale, "epochs": args.epochs,
        "killed_after_epoch": killed_at,
        "latest_committed_checkpoint": latest_saved,
        "resume_started_at_epoch": resume_start,
        "resume_contract_ok": ok_resume,
        "reached_final_epoch": ok_final,
        "final_train_qloss_resumed": round(final["train_qloss"], 6),
        "final_train_qloss_control": round(ctrl_final, 6),
        "rel_diff": round(rel, 8), "rtol": args.rtol,
        "parity_ok": ok_parity,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    print(json.dumps(result))
    sys.exit(0 if result["value"] else 1)


if __name__ == "__main__":
    main()
