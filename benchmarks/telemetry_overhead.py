"""Micro-bench: the no-op telemetry bus must be free — and sampled
request tracing must fit the same budget.

Instrumentation stays in the hot paths unconditionally (train chunk
dispatch, serve request loop, the packer), so the disabled-bus cost is a
per-step tax on EVERY untelemetered run. This bench measures it against
a real CPU train step and asserts the ratio stays under 1%:

- `step_ms`   — mean wall time of one jit'd train step (tiny synthetic
  model, CPU) — the unit of work the tax is paid per;
- `noop_ms`   — mean wall time of the per-step instrumentation bundle as
  fit() actually emits it (one level-2 span enter/exit + the host/device
  perf_counter bookkeeping), measured on the NoopBus over many reps;
- `overhead_pct` = 100 * noop_ms / step_ms — asserted < 1.0.

Distributed tracing (ISSUE 12) adds a second budget line: the
PER-REQUEST tracing bundle as the fleet front door emits it
(start_trace head sampling + three stage spans + the root finish) is
measured on a REAL trace-level bus at sample rates 0.0 / 0.1 (the
TelemetryConfig default) / 1.0, and the default-rate bundle is asserted
under the same 1% of a train step — so turning tracing on at the
shipped rate cannot silently tax the serve path. Rate 0.0 exercises
the None-context fast path; 1.0 prices a fully-written trace.

Prints ONE JSON line in the BENCH_r0*.json schema family; exits 1 on a
bound violation so CI can gate on it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_step():
    """One jit'd CPU train step over a small synthetic workload (the
    serve-bench corpus builder, batch-sized down)."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import Config, DataConfig, IngestConfig
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (create_train_state, make_train_step,
                                        make_tx)

    cfg = Config(ingest=IngestConfig(min_traces_per_entry=5),
                 data=DataConfig(max_traces=500, batch_size=16))
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=30, num_entries=4, patterns_per_entry=2,
        traces_per_entry=60, seed=3))
    ds = build_dataset(preprocess(data.spans, data.resources, cfg.ingest),
                      cfg)
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = make_tx(cfg)
    sample = next(ds.batches("train"))
    state = create_train_state(model, tx, sample, 0)
    step = make_train_step(model, cfg, tx)
    import jax
    import jax.numpy as jnp
    batch = jax.tree.map(jnp.asarray, sample)
    state, _ = step(state, batch)  # compile outside the timed region
    return step, state, batch


def time_step(step, state, batch, iters: int) -> float:
    """Mean seconds per train step (donated state threaded through)."""
    import jax
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    jax.block_until_ready(m)
    return (time.perf_counter() - t0) / iters


def time_noop_bundle(iters: int) -> float:
    """Mean seconds of fit()'s per-step telemetry work on the NoopBus:
    the level-2 chunk span plus the two perf_counter samples of the
    host/device split bookkeeping."""
    from pertgnn_tpu.telemetry import NOOP_BUS

    bus = NOOP_BUS
    t_host = 0.0
    t0 = time.perf_counter()
    for i in range(iters):
        t1 = time.perf_counter()
        with bus.span("train.chunk", level=2, epoch=0, step=i):
            pass
        t_host += time.perf_counter() - t1
    total = time.perf_counter() - t0
    assert t_host >= 0
    return total / iters


def time_trace_bundle(directory: str, rate: float, slow_ms: float,
                      iters: int) -> float:
    """Mean seconds of one traced-request lifecycle on a REAL
    trace-level bus at the given head-sample rate: the router-side
    bundle (start_trace + router_queue/transport/complete stage spans +
    root finish). At rate 0 this is the None-context fast path; between
    0 and 1 the unsampled majority pays buffer appends that the
    under-slow-threshold finish drops; at 1 every span hits the
    line-buffered writer."""
    import time as _time

    from pertgnn_tpu.telemetry import MetricsWriter, TelemetryBus

    writer = MetricsWriter(os.path.join(directory, f"rate_{rate:g}"))
    bus = TelemetryBus(writer, level="trace", trace_sample_rate=rate,
                       trace_slow_ms=slow_ms)
    tm = _time.monotonic()
    t0 = _time.perf_counter()
    for i in range(iters):
        ctx = bus.start_trace()
        bus.trace_span("trace.router_queue", ctx, tm, tm, worker="w0")
        bus.trace_span("trace.transport", ctx, tm, tm, worker="w0",
                       outcome="ok")
        bus.trace_span("trace.complete", ctx, tm, tm)
        bus.finish_trace("trace.request", ctx, tm, tm, outcome="ok",
                         entry_id=i)
    dt = (_time.perf_counter() - t0) / iters
    bus.close()
    return dt


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--step_iters", type=int, default=50)
    ap.add_argument("--noop_iters", type=int, default=200_000)
    ap.add_argument("--trace_iters", type=int, default=20_000)
    ap.add_argument("--max_overhead_pct", type=float, default=1.0)
    ap.add_argument("--out", default="",
                    help="also write the JSON record here")
    args = ap.parse_args()

    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()
    import jax

    from pertgnn_tpu import telemetry
    assert not telemetry.get_bus().enabled, \
        "default process-wide bus must be the no-op"

    step, state, batch = build_step()
    step_s = time_step(step, state, batch, args.step_iters)
    noop_s = time_noop_bundle(args.noop_iters)
    overhead_pct = 100.0 * noop_s / step_s

    # sampled request tracing against the same unit of work, at the
    # config default rate plus the two extremes
    import tempfile

    from pertgnn_tpu.config import TelemetryConfig
    default_rate = TelemetryConfig.trace_sample_rate
    slow_ms = TelemetryConfig.trace_slow_ms
    trace_us = {}
    # the rate-1.0 pass writes ~5 span lines per iteration — scratch
    # JSONL that must not accumulate across bench runs
    with tempfile.TemporaryDirectory(prefix="tele_overhead_") as td:
        for rate in (0.0, default_rate, 1.0):
            trace_us[f"{rate:g}"] = time_trace_bundle(
                td, rate, slow_ms, args.trace_iters) * 1e6
    trace_overhead_pct = (trace_us[f"{default_rate:g}"] / 1e6 / step_s
                          * 100.0)
    record = {
        "metric": "telemetry_noop_overhead_pct",
        "value": overhead_pct,
        "unit": "%",
        "step_ms": step_s * 1e3,
        "noop_us": noop_s * 1e6,
        "step_iters": args.step_iters,
        "noop_iters": args.noop_iters,
        "max_overhead_pct": args.max_overhead_pct,
        "trace_bundle_us_by_rate": {k: round(v, 3)
                                    for k, v in trace_us.items()},
        "trace_default_rate": default_rate,
        "trace_overhead_pct": trace_overhead_pct,
        "trace_iters": args.trace_iters,
        "backend": jax.default_backend(),
        "captured_unix_time": time.time(),
    }
    out = json.dumps(record)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    rc = 0
    if overhead_pct >= args.max_overhead_pct:
        print(f"FAIL: no-op telemetry bundle is {overhead_pct:.3f}% of a "
              f"CPU train step (bound {args.max_overhead_pct}%)",
              file=sys.stderr)
        rc = 1
    if trace_overhead_pct >= args.max_overhead_pct:
        print(f"FAIL: default-rate ({default_rate:g}) tracing bundle is "
              f"{trace_overhead_pct:.3f}% of a CPU train step (bound "
              f"{args.max_overhead_pct}%)", file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
