"""Micro-bench: the no-op telemetry bus must be free.

Instrumentation stays in the hot paths unconditionally (train chunk
dispatch, serve request loop, the packer), so the disabled-bus cost is a
per-step tax on EVERY untelemetered run. This bench measures it against
a real CPU train step and asserts the ratio stays under 1%:

- `step_ms`   — mean wall time of one jit'd train step (tiny synthetic
  model, CPU) — the unit of work the tax is paid per;
- `noop_ms`   — mean wall time of the per-step instrumentation bundle as
  fit() actually emits it (one level-2 span enter/exit + the host/device
  perf_counter bookkeeping), measured on the NoopBus over many reps;
- `overhead_pct` = 100 * noop_ms / step_ms — asserted < 1.0.

Prints ONE JSON line in the BENCH_r0*.json schema family; exits 1 on a
bound violation so CI can gate on it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_step():
    """One jit'd CPU train step over a small synthetic workload (the
    serve-bench corpus builder, batch-sized down)."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import Config, DataConfig, IngestConfig
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (create_train_state, make_train_step,
                                        make_tx)

    cfg = Config(ingest=IngestConfig(min_traces_per_entry=5),
                 data=DataConfig(max_traces=500, batch_size=16))
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=30, num_entries=4, patterns_per_entry=2,
        traces_per_entry=60, seed=3))
    ds = build_dataset(preprocess(data.spans, data.resources, cfg.ingest),
                      cfg)
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = make_tx(cfg)
    sample = next(ds.batches("train"))
    state = create_train_state(model, tx, sample, 0)
    step = make_train_step(model, cfg, tx)
    import jax
    import jax.numpy as jnp
    batch = jax.tree.map(jnp.asarray, sample)
    state, _ = step(state, batch)  # compile outside the timed region
    return step, state, batch


def time_step(step, state, batch, iters: int) -> float:
    """Mean seconds per train step (donated state threaded through)."""
    import jax
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    jax.block_until_ready(m)
    return (time.perf_counter() - t0) / iters


def time_noop_bundle(iters: int) -> float:
    """Mean seconds of fit()'s per-step telemetry work on the NoopBus:
    the level-2 chunk span plus the two perf_counter samples of the
    host/device split bookkeeping."""
    from pertgnn_tpu.telemetry import NOOP_BUS

    bus = NOOP_BUS
    t_host = 0.0
    t0 = time.perf_counter()
    for i in range(iters):
        t1 = time.perf_counter()
        with bus.span("train.chunk", level=2, epoch=0, step=i):
            pass
        t_host += time.perf_counter() - t1
    total = time.perf_counter() - t0
    assert t_host >= 0
    return total / iters


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--step_iters", type=int, default=50)
    ap.add_argument("--noop_iters", type=int, default=200_000)
    ap.add_argument("--max_overhead_pct", type=float, default=1.0)
    ap.add_argument("--out", default="",
                    help="also write the JSON record here")
    args = ap.parse_args()

    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()
    import jax

    from pertgnn_tpu import telemetry
    assert not telemetry.get_bus().enabled, \
        "default process-wide bus must be the no-op"

    step, state, batch = build_step()
    step_s = time_step(step, state, batch, args.step_iters)
    noop_s = time_noop_bundle(args.noop_iters)
    overhead_pct = 100.0 * noop_s / step_s
    record = {
        "metric": "telemetry_noop_overhead_pct",
        "value": overhead_pct,
        "unit": "%",
        "step_ms": step_s * 1e3,
        "noop_us": noop_s * 1e6,
        "step_iters": args.step_iters,
        "noop_iters": args.noop_iters,
        "max_overhead_pct": args.max_overhead_pct,
        "backend": jax.default_backend(),
        "captured_unix_time": time.time(),
    }
    out = json.dumps(record)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if overhead_pct >= args.max_overhead_pct:
        print(f"FAIL: no-op telemetry bundle is {overhead_pct:.3f}% of a "
              f"CPU train step (bound {args.max_overhead_pct}%)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
