"""graftmemo benchmark: the read-mostly path, gated end to end.

ONE run exit-code-asserts every ISSUE-20 acceptance criterion
(pertgnn_tpu/fleet/memo.py, lens/canon.py, fleet/search.py; docs/GUIDE
§17); CI runs --dryrun on every push:

1. **Hit ratio + bit-identity** — a Zipf replay (the real loadgen
   arrival law) over a small hot population through a memo'd
   FleetRouter on the BINARY transport, with aggressive hedging, lands
   a cache hit ratio >= 0.5; EVERY served prediction — misses, hits,
   hedge winners, and what-if variants (including a pair of equivalent
   edit scripts that must share one cache entry via the canonical
   form) — is bit-identical to the uncached single-process engine
   reference.
2. **Zero stale reads across a LIVE blue/green rollout** — traffic
   keeps flowing while a RolloutController swaps the fleet from the v1
   to the v2 checkpoint: the memo's hit counter is FROZEN from the
   retire (drain start) until after the new generation installs (old-
   generation hits drop to zero at the flip, by construction), every
   answer served at any point is bit-identical to v1 or v2 (never a
   blend), answers resolved after the install match v2 only, and the
   post-flip warm cache serves v2 bits.
3. **Cached-hit p50 < uncached binary-transport p50** — the same
   requests through the same router, hit vs miss pass.
4. **Counterfactual search** — fleet/search.py over the hot entry
   returns the argmin of everything it evaluated, with ZERO fresh
   engine compiles across the whole search and memo misses bounded by
   the unique-canonical-request count (the search dedups by the memo's
   own key).

Run off-TPU it auto-falls back to CPU like the sibling benches.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# The quantile head the whole bench serves: the search minimizes the
# LAST column, so 0.99 makes "minimize predicted p99" literal.
MEMO_TAUS = (0.5, 0.99)
HIT_RATIO_FLOOR = 0.5


def build_corpus(traces_per_entry: int, seed: int = 42):
    from pertgnn_tpu.ingest import synthetic

    return synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=60, num_entries=12, patterns_per_entry=3,
        pattern_size_range=(3, 24), traces_per_entry=traces_per_entry,
        seed=seed))


def memo_config(epochs: int):
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, ServeConfig,
                                    TrainConfig)

    return Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=64),
        model=ModelConfig(hidden_channels=32, num_layers=2,
                          quantile_taus=MEMO_TAUS),
        train=TrainConfig(label_scale=1000.0, epochs=epochs, lr=1e-3),
        serve=ServeConfig(bucket_growth=2.0, max_graphs_per_batch=8),
        graph_type="pert",
    )


def _population(ds, n: int):
    """The hot request population: the first `n` DISTINCT
    (entry, ts_bucket) pairs of the test split."""
    s = ds.splits["test"]
    seen, pop = set(), []
    for e, t in zip(s.entry_ids, s.ts_buckets):
        key = (int(e), int(t))
        if key not in seen:
            seen.add(key)
            pop.append(key)
        if len(pop) >= n:
            break
    return pop


def _ckey(edits):
    """The hashable reference-map key for an edit script — the memo's
    own canonical lens key, so equivalent scripts share one row."""
    from pertgnn_tpu.lens.canon import canonical_lens_key

    if not edits:
        return None
    return canonical_lens_key({"edits": [dict(e) for e in edits]})


def _reference(queue, pop, whatif_rows) -> dict:
    """The uncached engine answers through the single-process front
    door (proven bit-identical to direct engine dispatch by
    lens_bench), keyed by (entry, bucket, canonical-edits-or-None)."""
    from pertgnn_tpu.lens.request import LensRequest

    ref = {}
    for eid, tsb in pop:
        ref[(eid, tsb, None)] = np.asarray(
            queue.submit(eid, tsb).result(300), np.float32)
    for eid, tsb, edits in whatif_rows:
        ref[(eid, tsb, _ckey(edits))] = np.asarray(
            queue.submit(eid, tsb,
                         lens=LensRequest(edits=edits)).result(300),
            np.float32)
    return ref


def _equiv_scripts(mix):
    """Two syntactically different, canonically EQUAL edit scripts
    (both drop original edges {0, 1}) — they must share one memo
    entry."""
    a = ({"op": "drop_edge", "edge": 0}, {"op": "drop_edge", "edge": 0})
    b = ({"op": "drop_edge", "edge": 1}, {"op": "drop_edge", "edge": 0})
    return (a, b) if mix.num_edges >= 2 else (None, None)


def gate_read_mostly(ds, cfg, engine, args) -> dict:
    """Criteria 1 + 3 + 4: hit ratio, bit-identity (hedge winners and
    what-if variants included), hit-vs-miss p50, and the counterfactual
    search — all against ONE memo'd two-worker binary fleet."""
    from pertgnn_tpu.config import FleetConfig
    from pertgnn_tpu.fleet import loadgen
    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.fleet.search import CounterfactualSearch, SearchSpec
    from pertgnn_tpu.fleet.transport import WorkerServer
    from pertgnn_tpu.lens.request import LensRequest
    from pertgnn_tpu.serve.buckets import make_bucket_ladder
    from pertgnn_tpu.serve.queue import MicrobatchQueue

    pop = _population(ds, 12 if args.dryrun else 24)
    whatif_rows = []
    equiv_pairs = []
    for eid, tsb in pop:
        a, b = _equiv_scripts(ds.mixtures[eid])
        if a is not None:
            # the reference is keyed by script A; script B must HIT A's
            # cache entry (canonical equality) and match A's bits
            whatif_rows.append((eid, tsb, a))
            equiv_pairs.append((eid, tsb, a, b))
    top = make_bucket_ladder(ds.budget, cfg.serve)[-1]

    def size(eid):
        m = ds.mixtures[int(eid)]
        return m.num_nodes, m.num_edges

    record: dict = {}
    queue = MicrobatchQueue(engine)
    servers = []
    try:
        ref = _reference(queue, pop, whatif_rows)
        # two wire surfaces over ONE queue/engine (the test_fleet
        # hedging pattern): hedged legs go to distinct workers, answers
        # are identical by determinism — the hedge-winner bits are
        # checked against the same reference as everything else
        servers = [WorkerServer(engine, queue), WorkerServer(engine, queue)]
        urls = {f"w{i}": f"http://127.0.0.1:{s.port}"
                for i, s in enumerate(servers)}
        fleet_cfg = FleetConfig(
            transport="binary", memo_capacity_bytes=1 << 20,
            hedge_quantile_ms=0.05, health_poll_interval_s=0.2)
        with FleetRouter(urls, size,
                         (top.max_graphs, top.max_nodes, top.max_edges),
                         cfg=fleet_cfg) as router:
            memo = router.memo
            memo.set_generation(checkpoint_epoch=0,
                                arena_fingerprint="bench-v1",
                                taus=MEMO_TAUS)

            def ask(eid, tsb, edits=None):
                lens = (LensRequest(edits=edits) if edits else None)
                t0 = time.perf_counter()
                got = np.asarray(
                    router.submit(eid, tsb, lens=lens).result(300),
                    np.float32)
                dt = time.perf_counter() - t0
                want = ref[(eid, tsb, _ckey(edits))]
                if not np.array_equal(got, want):
                    raise AssertionError(
                        f"served bits diverged from the uncached "
                        f"reference for entry {eid} bucket {tsb} "
                        f"edits {edits}: {got} != {want}")
                return dt

            # -- criterion 3 setup: miss pass, then hit pass ------------
            miss_lat = [ask(e, t) for e, t in pop]
            miss_lat += [ask(e, t, a) for e, t, a, _b in equiv_pairs]
            hits0 = memo.hits
            hit_lat = [ask(e, t) for e, t in pop]
            # the EQUIVALENT script B must hit A's entry, bit-identical
            # to A's reference
            hit_lat += [ask(e, t, b) for e, t, _a, b in equiv_pairs]
            n_hit_pass = len(pop) + len(equiv_pairs)
            if memo.hits - hits0 != n_hit_pass:
                raise AssertionError(
                    f"hit pass expected {n_hit_pass} hits, got "
                    f"{memo.hits - hits0} — keying or canon broke")
            p50_miss = float(np.percentile(miss_lat, 50) * 1e3)
            p50_hit = float(np.percentile(hit_lat, 50) * 1e3)
            if p50_hit >= p50_miss:
                raise AssertionError(
                    f"cached-hit p50 {p50_hit:.3f}ms is not under the "
                    f"uncached binary-transport p50 {p50_miss:.3f}ms")

            # -- criterion 1: open-loop Zipf replay over the warm cache -
            entries = np.asarray([e for e, _t in pop], np.int64)
            buckets = np.asarray([t for _e, t in pop], np.int64)
            spec = loadgen.LoadSpec(
                duration_s=1.0 if args.dryrun else 3.0,
                base_rps=150.0, zipf_s=1.1, seed=7)
            schedule = loadgen.generate_schedule(spec, entries, buckets)
            result = loadgen.replay(router.submit, schedule,
                                    vector_width=len(MEMO_TAUS))
            served = result.served_mask()
            if result.lost_futures() or not served.all():
                raise AssertionError(
                    f"replay lost futures={result.lost_futures()} "
                    f"unserved={int((~served).sum())} "
                    f"errors={result.error_counts()}")
            for i in range(len(schedule)):
                want = ref[(int(schedule.entry_ids[i]),
                            int(schedule.ts_buckets[i]), None)]
                if not np.array_equal(result.preds[i], want):
                    raise AssertionError(
                        f"replay row {i} diverged from the uncached "
                        f"reference: {result.preds[i]} != {want}")
            hit_ratio = memo.hits / max(memo.hits + memo.misses, 1)
            if hit_ratio < HIT_RATIO_FLOOR:
                raise AssertionError(
                    f"hit ratio {hit_ratio:.3f} under the "
                    f"{HIT_RATIO_FLOOR} floor "
                    f"({memo.hits} hits / {memo.misses} misses)")

            # -- criterion 4: counterfactual search ---------------------
            hot_eid, hot_tsb = pop[0]
            mix = ds.mixtures[hot_eid]
            compiles0 = engine.compiles
            misses0 = memo.misses
            search = CounterfactualSearch(router.submit, SearchSpec(
                entry_id=hot_eid, ts_bucket=hot_tsb,
                num_nodes=int(mix.num_nodes),
                num_edges=int(mix.num_edges),
                beam_width=3, max_depth=2,
                budget=48 if args.dryrun else 96,
                sub_ms_ids=tuple(int(m) for m in
                                 np.unique(np.asarray(mix.ms_id))[:3]),
                max_drop_candidates=6, max_sub_nodes=2))
            sres = search.run()
            if engine.compiles != compiles0:
                raise AssertionError(
                    f"search compiled: {compiles0} -> "
                    f"{engine.compiles} — the zero-fresh-compile "
                    f"construction broke")
            best_seen = min(o for _e, o in sres.evaluated)
            if sres.best_objective != best_seen:
                raise AssertionError(
                    f"search best {sres.best_objective} is not the "
                    f"argmin of its evaluated set ({best_seen})")
            search_misses = memo.misses - misses0
            # the search dedups by the memo's own canonical key, so its
            # submissions ARE its unique-canonical-request count
            if search_misses > sres.requests:
                raise AssertionError(
                    f"search drove {search_misses} memo misses for "
                    f"{sres.requests} unique canonical requests")
            router_stats = router.stats_dict()
            if router_stats["hedge_fired"] == 0:
                raise AssertionError(
                    "no hedge ever fired — the hedge-winner "
                    "bit-identity claim would be vacuous")
            record.update({
                "population": len(pop),
                "whatif_variants": len(equiv_pairs) * 2,
                "hit_ratio": round(float(hit_ratio), 4),
                "replay_arrivals": int(len(schedule)),
                "p50_uncached_ms": round(p50_miss, 3),
                "p50_cached_ms": round(p50_hit, 3),
                "hedge_fired": router_stats["hedge_fired"],
                "hedge_won": router_stats["hedge_won"],
                "memo": memo.stats_dict(),
                "search": sres.to_dict(),
                "search_memo_misses": int(search_misses),
            })
    finally:
        queue.close()
        for s in servers:
            s.close()
    return record


def gate_live_rollout(ds, cfg, engine_v1, engine_v2, v2_epoch,
                      args) -> dict:
    """Criterion 2: live traffic across a real blue/green rollout —
    the memo's hits freeze at the retire and stay frozen until the new
    generation installs; no served byte is ever stale."""
    from pertgnn_tpu.config import FleetConfig
    from pertgnn_tpu.fleet.rollout import RolloutController, RolloutWorker
    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.fleet.transport import WorkerServer
    from pertgnn_tpu.serve.buckets import make_bucket_ladder
    from pertgnn_tpu.serve.errors import ServeError
    from pertgnn_tpu.serve.queue import MicrobatchQueue

    pop = _population(ds, 8)
    # uncached references for BOTH checkpoint versions, computed before
    # any fleet exists (the queues close again immediately)
    with MicrobatchQueue(engine_v1) as q:
        ref1 = _reference(q, pop, [])
    with MicrobatchQueue(engine_v2) as q:
        ref2 = _reference(q, pop, [])
    differs = [k for k in ref1 if not np.array_equal(ref1[k], ref2[k])]
    if not differs:
        raise AssertionError(
            "v1 and v2 answer identically on every population row — "
            "the stale-read gate cannot distinguish the versions")

    top = make_bucket_ladder(ds.budget, cfg.serve)[-1]

    def size(eid):
        m = ds.mixtures[int(eid)]
        return m.num_nodes, m.num_edges

    slot = {"queue": MicrobatchQueue(engine_v1)}
    slot["server"] = WorkerServer(
        engine_v1, slot["queue"],
        extra_fn=lambda: {"checkpoint_epoch": 0})
    port = slot["server"].port
    url = f"http://127.0.0.1:{port}"
    marks: dict = {}

    def stop_worker(_w):
        # drain: retire already ran (the controller flips first) — pin
        # the hit counter HERE; it must not move again until install
        marks["hits_at_drain"] = memo.hits
        slot["server"].close()
        slot["queue"].close()

    def _spawn(engine, epoch):
        slot["queue"] = MicrobatchQueue(engine)
        slot["server"] = WorkerServer(
            engine, slot["queue"], port=port,
            extra_fn=lambda: {"checkpoint_epoch": epoch})
        return slot["server"]

    fleet_cfg = FleetConfig(transport="binary",
                            memo_capacity_bytes=1 << 20,
                            health_poll_interval_s=0.1)
    record: dict = {}
    outcomes: list = []          # (t_resolved, key, pred or None)
    stop = threading.Event()
    try:
        with FleetRouter({"w1": url}, size,
                         (top.max_graphs, top.max_nodes, top.max_edges),
                         cfg=fleet_cfg) as router:
            memo = router.memo
            memo.set_generation(checkpoint_epoch=0,
                                arena_fingerprint="bench-arena",
                                taus=MEMO_TAUS)
            # warm: every row cached and bit-identical to v1
            for eid, tsb in pop:
                router.submit(eid, tsb).result(300)
            for eid, tsb in pop:
                got = np.asarray(router.submit(eid, tsb).result(300),
                                 np.float32)
                if not np.array_equal(got, ref1[(eid, tsb, None)]):
                    raise AssertionError(
                        f"pre-rollout cached answer diverged from v1 "
                        f"for {(eid, tsb)}")
            if memo.hits < len(pop):
                raise AssertionError("warm cache never hit")

            def traffic():
                i = 0
                while not stop.is_set():
                    eid, tsb = pop[i % len(pop)]
                    i += 1
                    try:
                        fut = router.submit(eid, tsb)
                        pred = np.asarray(fut.result(60), np.float32)
                    except ServeError:
                        # availability wobble mid-swap is allowed; only
                        # WRONG BYTES fail this gate
                        time.sleep(0.02)
                        continue
                    except Exception as exc:
                        # anything NOT a typed serve error mid-swap is
                        # unexpected: tolerated for availability (the
                        # gate is about bytes), but never silent
                        print(f"cache_bench: live-traffic stray error: "
                              f"{type(exc).__name__}: {exc}")
                        time.sleep(0.02)
                        continue
                    outcomes.append((time.perf_counter(),
                                     (eid, tsb, None), pred))
                    time.sleep(0.01)

            th = threading.Thread(target=traffic, name="live-traffic")
            th.start()
            controller = RolloutController(
                [RolloutWorker("w1", url, handle=slot["server"])],
                stop_worker=stop_worker,
                spawn_new=lambda w: _spawn(engine_v2, v2_epoch),
                spawn_old=lambda w: _spawn(engine_v1, 0),
                verify=lambda body: (
                    None if body.get("checkpoint_epoch") == v2_epoch
                    else f"checkpoint_epoch {body.get('checkpoint_epoch')}"
                         f", wanted {v2_epoch}"),
                ready_timeout_s=120.0, poll_interval_s=0.1,
                memo=memo,
                new_generation=dict(checkpoint_epoch=v2_epoch,
                                    arena_fingerprint="bench-arena",
                                    taus=MEMO_TAUS))
            summary = controller.run()
            t_install = time.perf_counter()
            hits_at_install = memo.hits
            # let post-flip traffic flow, then stop the injector
            time.sleep(0.5)
            stop.set()
            th.join(timeout=60)

            # the flip froze the hit counter for the WHOLE window
            if hits_at_install != marks["hits_at_drain"]:
                raise AssertionError(
                    f"{hits_at_install - marks['hits_at_drain']} cache "
                    f"hits were served mid-rollout — stale reads")
            # every live answer is bit-identical to v1 or v2; answers
            # resolved after the install are v2 only
            n_v1 = n_v2 = 0
            for t_res, key, pred in outcomes:
                is1 = np.array_equal(pred, ref1[key])
                is2 = np.array_equal(pred, ref2[key])
                if not (is1 or is2):
                    raise AssertionError(
                        f"live answer for {key} matches NEITHER "
                        f"checkpoint version: {pred}")
                if t_res > t_install and not is2:
                    raise AssertionError(
                        f"answer for {key} resolved after the "
                        f"generation install but carries v1 bits")
                n_v1 += is1 and not is2
                n_v2 += is2
            # post-flip: the cache re-warms with v2 bits
            hits0 = memo.hits
            for eid, tsb in pop:
                router.submit(eid, tsb).result(300)
            for eid, tsb in pop:
                got = np.asarray(router.submit(eid, tsb).result(300),
                                 np.float32)
                if not np.array_equal(got, ref2[(eid, tsb, None)]):
                    raise AssertionError(
                        f"post-rollout cached answer diverged from v2 "
                        f"for {(eid, tsb)}")
            if memo.hits <= hits0:
                raise AssertionError("post-flip cache never re-warmed")
            gen = memo.stats_dict()["generation"]
            if gen is None or gen["checkpoint_epoch"] != v2_epoch:
                raise AssertionError(
                    f"post-rollout generation is {gen}, wanted epoch "
                    f"{v2_epoch}")
            record.update({
                "rollout": summary,
                "live_answers": len(outcomes),
                "live_v1_answers": int(n_v1),
                "live_v2_answers": int(n_v2),
                "rows_where_versions_differ": len(differs),
                "hits_frozen_through_window": True,
                "rollout_memo": memo.stats_dict(),
            })
    finally:
        stop.set()
        import contextlib
        with contextlib.suppress(Exception):
            slot["server"].close()
        with contextlib.suppress(Exception):
            slot["queue"].close()
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dryrun", action="store_true",
                    help="CI scale: small corpus, short fine-tune")
    ap.add_argument("--traces_per_entry", type=int, default=0,
                    help="0 = per-mode default")
    ap.add_argument("--epochs", type=int, default=0,
                    help="0 = per-mode default")
    ap.add_argument("--out", default="",
                    help="also write the JSON record here")
    args = ap.parse_args()

    from pertgnn_tpu.cli.common import (apply_platform_env,
                                        probe_backend_or_fallback)
    fallback = probe_backend_or_fallback()
    apply_platform_env()

    import jax

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import fit, restore_target_state

    traces = args.traces_per_entry or (60 if args.dryrun else 300)
    epochs = args.epochs or (3 if args.dryrun else 10)

    t0 = time.perf_counter()
    corpus = build_corpus(traces)
    cfg = memo_config(epochs)
    pre = preprocess(corpus.spans, corpus.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    # v1 = the fresh-init checkpoint, v2 = the trained one: two real,
    # distinct, deterministic engines for the rollout gate
    _model, state_v1 = restore_target_state(ds, cfg)
    state_v2, _history = fit(ds, cfg)
    engine_v1 = InferenceEngine.from_dataset(ds, cfg, state_v1).warmup()
    engine_v2 = InferenceEngine.from_dataset(ds, cfg, state_v2).warmup()

    record = {
        "metric": "pert_memo_gates",
        "value": 1.0,
        "unit": "pass",
        "taus": list(MEMO_TAUS),
        "dryrun": bool(args.dryrun),
    }
    record.update(gate_read_mostly(ds, cfg, engine_v1, args))
    record.update(gate_live_rollout(ds, cfg, engine_v1, engine_v2,
                                    epochs, args))

    record["backend"] = jax.default_backend()
    record["backend_fallback"] = fallback
    record["total_s"] = time.perf_counter() - t0
    record["captured_unix_time"] = time.time()
    out = json.dumps(record)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
