"""Streaming benchmark: delta merge, warm-restart fine-tune, rollout.

Exit-code-asserts the ISSUE-11 invariants in ONE run (wall-clock numbers
ride the JSON, the verdict lives in the return code — the
fleet_bench/chaos_bench split):

- **delta merge** — a corpus sliced into base + 2 time-window shards,
  ingested through the delta arena store (stream/store.py) and merged
  (stream/merge.py), must pack BIT-IDENTICAL batches to a from-scratch
  batch build over the concatenated raw shards — in EITHER delta order.
- **warm restart** — a FRESH process runs one continual fine-tune round
  (stream/continual.py) and must reach its first train step with ZERO
  shard ingests (every shard a `stream.shard_cache_hit`; the shard
  frame callbacks are armed to raise) and ZERO AOT store misses
  (`aot.cache_miss` absent from its telemetry) — restart-to-first-step
  rides the ttfs_s it reports.
- **rollout** — a 2-worker fleet (cli/fleet_main.py worker role + the
  in-process FleetRouter) serves live closed-loop traffic while
  fleet/rollout.py swaps each worker from the base checkpoint to the
  fine-tuned one: ZERO lost Futures (every request resolves to a
  prediction), p99 bounded, every prediction bit-identical to the v1 or
  v2 single-engine reference, and every post-rollout prediction
  bit-identical to v2.
- **telemetry** — the `stream.*` and `rollout.*` counters land in the
  JSONL (docs/OBSERVABILITY.md).
- **tracing** — the rollout serves at `--trace_sample_rate 1.0`;
  tools/graftscope merges the router's and every (v1 and replacement
  v2) worker's telemetry files and must find EVERY successful Future
  as exactly one root span with a complete stage chain, zero orphans —
  trace completeness ACROSS a blue/green rollout (ISSUE 12).

CPU by default. One JSON line on stdout.

    python benchmarks/stream_bench.py [--dryrun] [--skip_rollout]

``--dryrun`` is the CI smoke (tiny corpus, short streams, all four
assertions).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


class Check:
    def __init__(self):
        self.failures: list[str] = []

    def expect(self, cond: bool, what: str):
        if not cond:
            self.failures.append(what)
            print(f"STREAM FAIL: {what}", file=sys.stderr)


# -- shared corpus / config construction ----------------------------------

def corpus_spec(dryrun: bool) -> dict:
    span = 9 * 60 * 1000
    return {"num_microservices": 14, "num_entries": 3,
            "patterns_per_entry": 3,
            "traces_per_entry": 30 if dryrun else 90,
            "seed": 11, "time_span_ms": span,
            "missing_resource_frac": 0.0,
            "ensure_pattern_coverage_before_ms": span // 3,
            "bounds": [span // 3, 2 * span // 3]}


def make_corpus(spec: dict):
    """(shards, spans, resources): the raw corpus sliced into base + 2
    time-window delta shards (boundary-crossing traces dropped by the
    slicer, so the union IS the concatenation)."""
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.stream import shard_frames_by_window

    gen_spec = {k: v for k, v in spec.items() if k != "bounds"}
    synth = synthetic.generate(synthetic.SyntheticSpec(**gen_spec))
    shards = shard_frames_by_window(synth.spans, synth.resources,
                                    spec["bounds"])
    import pandas as pd

    spans = pd.concat([s[0] for s in shards], ignore_index=True)
    resources = pd.concat([s[1] for s in shards], ignore_index=True)
    return shards, spans, resources


def make_cfg(tmp: str, budget=None):
    """The ONE Config both processes and every phase share. The packer
    budget is pinned once the base derives it, so the fine-tune and
    rollout programs keep the base's abstract signature (AOT replay
    instead of recompile — the point of the warm-restart phase)."""
    import dataclasses

    from pertgnn_tpu.config import (CompileCacheConfig, Config, DataConfig,
                                    IngestConfig, ModelConfig, StreamConfig,
                                    TrainConfig)

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=8),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(label_scale=1000.0, epochs=2,
                          device_materialize=False,
                          checkpoint_dir=os.path.join(tmp, "ckpt_v1")),
        stream=StreamConfig(delta_store_dir=os.path.join(tmp, "delta"),
                            window_shards=2, finetune_epochs=2),
        aot=CompileCacheConfig(cache_dir=os.path.join(tmp, "aot")),
        graph_type="pert",
    )
    if budget is not None:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, max_nodes_per_batch=budget[0],
            max_edges_per_batch=budget[1]))
    return cfg


def shard_fingerprint(spec: dict, i: int) -> dict:
    """Deterministic per-shard fingerprint both processes agree on (the
    CLI path would use cli/common.raw_input_fingerprint over the
    shard's files; the bench keys the generator spec + window index)."""
    return {"kind": "stream_bench", "spec": {k: spec[k] for k in
            sorted(spec) if k != "bounds"},
            "bounds": list(spec["bounds"]), "window": i}


def ingest_all(tmp: str, spec: dict, cfg, shards):
    """(base, deltas, (pre, table)) through the delta store."""
    from pertgnn_tpu.ingest.assemble import assemble
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.stream import DeltaArenaStore

    store = DeltaArenaStore(cfg.stream.delta_store_dir)
    holder: dict = {}

    def pre_table():
        pre = preprocess(shards[0][0], shards[0][1], cfg.ingest)
        table = assemble(pre, cfg.ingest)
        holder["pre_table"] = (pre, table)
        return pre, table

    base = store.load_or_ingest_base(cfg, shard_fingerprint(spec, 0),
                                     pre_table)
    deltas = [store.load_or_ingest_delta(
        cfg, shard_fingerprint(spec, i),
        (lambda i=i: (shards[i][0], shards[i][1])), base)
        for i in (1, 2)]
    if "pre_table" not in holder:
        # re-run against a warm --workdir: the store answered, rebuild
        # the base artifacts in-process for the oracle/dataset phases
        from pertgnn_tpu.ingest.assemble import assemble
        from pertgnn_tpu.ingest.preprocess import preprocess

        pre = preprocess(shards[0][0], shards[0][1], cfg.ingest)
        holder["pre_table"] = (pre, assemble(pre, cfg.ingest))
    return base, deltas, holder["pre_table"]


# -- phase: merge equality -------------------------------------------------

def check_merge_equality(check: Check, cfg, base, deltas, shards) -> dict:
    import pandas as pd

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.stream import merge_shards

    spans_u = pd.concat([s[0] for s in shards], ignore_index=True)
    res_u = pd.concat([s[1] for s in shards], ignore_index=True)
    t0 = time.perf_counter()
    pre_u = preprocess(spans_u, res_u, cfg.ingest)
    oracle = build_dataset(pre_u, cfg)
    rebuild_s = time.perf_counter() - t0

    def equal(a, b, tag):
        ok = True
        if a.budget != b.budget:
            check.expect(False, f"{tag}: budget {a.budget} != {b.budget}")
            return False
        for name in a.splits:
            for i, (ba, bb) in enumerate(zip(a.batches(name),
                                             b.batches(name))):
                for f in ba._fields:
                    if not np.array_equal(getattr(ba, f), getattr(bb, f)):
                        check.expect(False, f"{tag}: {name} batch {i} "
                                            f"field {f} differs")
                        ok = False
        vocab_a = (a.num_ms, a.num_entries, a.num_interfaces,
                   a.num_rpctypes)
        vocab_b = (b.num_ms, b.num_entries, b.num_interfaces,
                   b.num_rpctypes)
        check.expect(vocab_a == vocab_b,
                     f"{tag}: vocab sizes {vocab_a} != {vocab_b}")
        return ok and vocab_a == vocab_b

    merges = {}
    for tag, order in (("merge_fwd", deltas), ("merge_rev", deltas[::-1])):
        t0 = time.perf_counter()
        merged, info = merge_shards(base, list(order), cfg)
        merges[tag] = time.perf_counter() - t0
        equal(merged, oracle, tag)
    return {"rebuild_s": round(rebuild_s, 3),
            "merge_s": {k: round(v, 3) for k, v in merges.items()},
            "oracle_traces": sum(len(s) for s in oracle.splits.values())}


# -- phase: warm-restart fine-tune (fresh process) -------------------------

def run_finetune_child(args) -> None:
    """--finetune_child entry: the FRESH process proving warm restart.
    Shard frame callbacks are armed to raise — any delta-store miss is
    a loud failure, not a silent re-ingest."""
    from pertgnn_tpu import telemetry
    from pertgnn_tpu.config import TelemetryConfig
    from pertgnn_tpu.stream import (DeltaArenaStore, finetune_round,
                                    merge_shards)

    tmp = args.workdir
    spec = corpus_spec(args.dryrun)
    with open(os.path.join(tmp, "budget.json")) as f:
        saved = json.load(f)
    cfg = make_cfg(tmp, budget=(saved["max_nodes"], saved["max_edges"]))
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, checkpoint_dir=os.path.join(tmp, "ckpt_v2")))
    telemetry.configure_from_config(
        TelemetryConfig(telemetry_dir=os.path.join(tmp, "tele_finetune"),
                        telemetry_level="trace"),
        run_meta={"cli": "stream_bench_finetune"})
    from pertgnn_tpu.aot import enable_compile_cache
    enable_compile_cache(cfg.aot)
    store = DeltaArenaStore(cfg.stream.delta_store_dir)

    def cold(_what):
        raise AssertionError(
            f"warm child hit a COLD delta-store path ({_what}) — the "
            f"warm-restart contract is broken")

    base = store.load_or_ingest_base(cfg, shard_fingerprint(spec, 0),
                                     lambda: cold("base"))
    deltas = [store.load_or_ingest_delta(
        cfg, shard_fingerprint(spec, i), (lambda i=i: cold(f"delta{i}")),
        base) for i in (1, 2)]
    merged, info = merge_shards(base, deltas, cfg)
    from pertgnn_tpu.batching.dataset import Split
    frozen = {k: Split(entry_ids=np.asarray(v["entry_ids"], np.int64),
                       ts_buckets=np.asarray(v["ts_buckets"], np.int64),
                       ys=np.asarray(v["ys"], np.float32))
              for k, v in (("valid", saved["frozen_valid"]),
                           ("test", saved["frozen_test"]))}
    window = info.window_split(cfg.stream.window_shards)
    state, history = finetune_round(
        merged, window, frozen, cfg,
        cfg.train.checkpoint_dir,
        baseline_qloss=saved["baseline_qloss"],
        checkpoint_vocab=saved["checkpoint_vocab"])
    telemetry.get_bus().flush()
    print(json.dumps({
        "finetune_ok": True,
        "epochs": [h["epoch"] for h in history],
        "ttfs_s": history[0].get("ttfs_s") if history else None,
        "valid_qloss": history[-1]["valid_qloss"] if history else None,
        "window_examples": len(window),
    }), flush=True)


def telemetry_names(tele_dir: str) -> dict:
    from pertgnn_tpu.telemetry import load_events

    counts: dict[str, int] = {}
    if not os.path.isdir(tele_dir):
        return counts
    for fname in os.listdir(tele_dir):
        if fname.endswith(".jsonl"):
            for ev in load_events(os.path.join(tele_dir, fname)):
                counts[ev["name"]] = counts.get(ev["name"], 0) + 1
    return counts


def check_finetune(check: Check, tmp: str, dryrun: bool) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--finetune_child",
           "--workdir", tmp] + (["--dryrun"] if dryrun else [])
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=900,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    if proc.returncode != 0:
        check.expect(False, f"finetune child exited {proc.returncode}: "
                            f"{proc.stderr[-2000:]}")
        return {"rc": proc.returncode}
    row = {}
    for line in proc.stdout.splitlines():
        if line.startswith("{") and "finetune_ok" in line:
            row = json.loads(line)
    check.expect(bool(row.get("finetune_ok")),
                 "finetune child produced no result row")
    names = telemetry_names(os.path.join(tmp, "tele_finetune"))
    check.expect(names.get("stream.shard_cache_hit", 0) >= 3,
                 f"finetune child: expected 3 shard cache hits, saw "
                 f"{names.get('stream.shard_cache_hit', 0)}")
    check.expect("stream.shard_cache_miss" not in names,
                 "finetune child: a shard MISSED the delta store "
                 "(fresh ingest in the warm path)")
    check.expect("aot.cache_miss" not in names,
                 "finetune child: an AOT store MISS — the fine-tune "
                 "recompiled instead of replaying")
    check.expect(names.get("aot.cache_hit", 0) >= 1,
                 "finetune child: no AOT store hits recorded")
    check.expect("stream.qloss_drift" in names,
                 "finetune child: stream.qloss_drift gauge missing")
    return {"rc": 0, **row,
            "aot_hits": names.get("aot.cache_hit", 0),
            "shard_hits": names.get("stream.shard_cache_hit", 0)}


# -- phase: blue/green rollout under live traffic --------------------------

def write_raw_csvs(spans, resources, out_dir: str) -> None:
    cg = os.path.join(out_dir, "MSCallGraph")
    rs = os.path.join(out_dir, "MSResource")
    os.makedirs(cg, exist_ok=True)
    os.makedirs(rs, exist_ok=True)
    spans.to_csv(os.path.join(cg, "MSCallGraph_0.csv"))
    resources.to_csv(os.path.join(rs, "MSResource_0.csv"), index=False)


def worker_argv(tmp: str, budget, ckpt_dir: str, wid: str,
                port: int) -> list[str]:
    return [sys.executable, "-m", "pertgnn_tpu.cli.fleet_main",
            "--role", "worker", "--worker_id", wid,
            "--worker_port", str(port),
            # same telemetry dir as the parent (which runs the router):
            # graftscope merges router + worker files into one request
            # tree per trace, across the v1 AND replacement v2 workers
            "--telemetry_dir", os.path.join(tmp, "tele_parent"),
            "--telemetry_level", "trace",
            "--trace_sample_rate", "1.0",
            "--data_dir", os.path.join(tmp, "raw_base"),
            "--artifact_dir", os.path.join(tmp, "art_base"),
            "--arena_cache_dir", os.path.join(tmp, "arena"),
            "--compile_cache_dir", os.path.join(tmp, "aot"),
            "--checkpoint_dir", ckpt_dir,
            "--min_traces_per_entry", "5", "--label_scale", "1000",
            "--graph_type", "pert", "--hidden_channels", "16",
            "--num_layers", "2", "--batch_size", "8",
            "--max_nodes_per_batch", str(budget[0]),
            "--max_edges_per_batch", str(budget[1]),
            "--no_device_materialize",
            "--max_graphs_per_batch", "8"]


def _await_200(url: str, timeout_s: float) -> dict:
    from pertgnn_tpu.fleet.transport import (WorkerTransportError,
                                             get_probe)

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            status, body = get_probe(url, 1.5)
            if status == 200:
                return body
        except WorkerTransportError:
            pass
        time.sleep(0.2)
    raise SystemExit(f"worker at {url} not ready after {timeout_s:.0f}s")


def check_rollout(check: Check, tmp: str, cfg, base_ds, budget,
                  v1_epoch: int, v2_epoch: int, dryrun: bool) -> dict:
    from pertgnn_tpu.fleet.rollout import (RolloutController, RolloutWorker)
    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.serve.buckets import make_bucket_ladder
    from pertgnn_tpu.serve.errors import ServeError
    from pertgnn_tpu.utils.profiling import LatencyRecorder

    # reference predictions per checkpoint version, from in-process
    # engines over the SAME base dataset the workers serve
    refs = {}
    for tag, ckpt in (("v1", "ckpt_v1"), ("v2", "ckpt_v2")):
        from pertgnn_tpu.serve.engine import InferenceEngine
        from pertgnn_tpu.train.checkpoint import CheckpointManager
        from pertgnn_tpu.train.loop import restore_target_state

        c = cfg.replace(train=dataclasses.replace(
            cfg.train, checkpoint_dir=os.path.join(tmp, ckpt)))
        _m, state = restore_target_state(base_ds, c)
        state, _ = CheckpointManager(
            os.path.join(tmp, ckpt)).maybe_restore(state)
        eng = InferenceEngine.from_dataset(base_ds, c, state).warmup()
        uniq: dict[tuple[int, int], float] = {}
        for s in base_ds.splits.values():
            for eid, tsb in zip(s.entry_ids, s.ts_buckets):
                key = (int(eid), int(tsb))
                if key not in uniq:
                    uniq[key] = float(eng.predict_microbatch(
                        [key[0]], [key[1]])[0])
        refs[tag] = uniq
    versions_differ = any(refs["v1"][k] != refs["v2"][k]
                          for k in refs["v1"])
    check.expect(versions_differ,
                 "rollout: v1 and v2 predict identically — the "
                 "fine-tune produced no observable new version, the "
                 "rollout proves nothing")

    # spawn the v1 fleet
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [free_port(), free_port()]
    workers = []
    for i, port in enumerate(ports):
        argv = worker_argv(tmp, budget, os.path.join(tmp, "ckpt_v1"),
                           f"w{i}", port)
        proc = subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                                env={**os.environ,
                                     "JAX_PLATFORMS": "cpu"})
        workers.append(RolloutWorker(worker_id=f"w{i}",
                                     url=f"http://127.0.0.1:{port}",
                                     handle=proc))
    t_ready0 = time.perf_counter()
    for w in workers:
        body = _await_200(w.url, 300.0)
        check.expect(body.get("checkpoint_epoch") == v1_epoch,
                     f"rollout: {w.worker_id} starts at checkpoint "
                     f"{body.get('checkpoint_epoch')}, wanted {v1_epoch}")
    ready_s = time.perf_counter() - t_ready0

    top = make_bucket_ladder(base_ds.budget, cfg.serve)[-1]

    def request_size(eid: int):
        m = base_ds.mixtures[int(eid)]
        return m.num_nodes, m.num_edges

    req_keys = sorted({(int(e), int(t))
                       for s in base_ds.splits.values()
                       for e, t in zip(s.entry_ids, s.ts_buckets)})
    rng = np.random.default_rng(0)

    stop = threading.Event()
    lat = LatencyRecorder()
    lock = threading.Lock()
    bad: list[str] = []
    n_served = [0]

    def client(router, tid):
        order = rng.permutation(len(req_keys))
        i = 0
        while not stop.is_set():
            eid, tsb = req_keys[order[i % len(order)]]
            i += 1
            t0 = time.perf_counter()
            try:
                pred = router.predict(eid, tsb, timeout=120)
            except ServeError as exc:
                with lock:
                    bad.append(f"typed error {type(exc).__name__}: {exc}")
                continue
            except BaseException as exc:  # lint: allow-silent-except — surfaced via the check below
                with lock:
                    bad.append(f"{type(exc).__name__}: {exc}")
                continue
            lat.record_s(time.perf_counter() - t0)
            if pred not in (refs["v1"][(eid, tsb)],
                            refs["v2"][(eid, tsb)]):
                with lock:
                    bad.append(f"prediction for {(eid, tsb)} matches "
                               f"NEITHER version: {pred}")
            with lock:
                n_served[0] += 1

    results: dict = {}
    with FleetRouter({w.worker_id: w.url for w in workers}, request_size,
                     (top.max_graphs, top.max_nodes, top.max_edges),
                     cfg=cfg.fleet) as router:
        threads = [threading.Thread(target=client, args=(router, t),
                                    daemon=True,
                                    name=f"stream-client-{t}")
                   for t in range(8)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # traffic flowing before the first drain
        pre_p99 = None

        # -- the blue/green rollout, mid-traffic -----------------------
        def stop_worker(w: RolloutWorker):
            proc = w.handle
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()

        def spawn(ckpt):
            def _spawn(w: RolloutWorker):
                port = int(w.url.rsplit(":", 1)[1])
                return subprocess.Popen(
                    worker_argv(tmp, budget, os.path.join(tmp, ckpt),
                                w.worker_id, port),
                    stdout=subprocess.DEVNULL,
                    env={**os.environ, "JAX_PLATFORMS": "cpu"})
            return _spawn

        def verify(body: dict):
            got = body.get("checkpoint_epoch")
            if got != v2_epoch:
                return f"checkpoint_epoch {got}, wanted {v2_epoch}"
            if body.get("compiles", 1) != 0:
                return f"replacement compiled {body.get('compiles')} " \
                       f"rungs (AOT store cold?)"
            return None

        controller = RolloutController(
            workers, stop_worker=stop_worker,
            spawn_new=spawn("ckpt_v2"), spawn_old=spawn("ckpt_v1"),
            verify=verify, ready_timeout_s=300.0)
        t_roll0 = time.perf_counter()
        summary = controller.run()
        rollout_s = time.perf_counter() - t_roll0
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=180)

        # post-rollout: everything must serve v2 now
        post_bad = 0
        n_post = min(len(req_keys), 40)
        for eid, tsb in req_keys[:n_post]:
            pred = router.predict(eid, tsb, timeout=120)
            if pred != refs["v2"][(eid, tsb)]:
                post_bad += 1
        check.expect(post_bad == 0,
                     f"rollout: {post_bad}/{n_post} post-rollout "
                     f"predictions are not the v2 checkpoint's")
        router_stats = router.stats_dict()
    for w in workers:
        stop_worker(w)

    # graftscope over the shared telemetry dir (the in-process router +
    # every v1/v2 worker): EVERY successful Future across the rollout —
    # drains, requeues, replacement workers — must collect into exactly
    # one root with a complete stage chain, zero orphans (ISSUE 12)
    from pertgnn_tpu import telemetry as _tele
    _tele.get_bus().flush()
    trace_report: dict = {}
    n_expected = n_served[0] + n_post
    from tools.graftscope import OrphanSpanError, build_report, collect
    try:
        trace_report = build_report(
            collect(os.path.join(tmp, "tele_parent")), top_k=3)
    except OrphanSpanError as exc:
        check.expect(False, f"rollout traces: {exc}")
    if trace_report:
        check.expect(trace_report["incomplete"] == 0,
                     f"rollout traces: {trace_report['incomplete']} "
                     f"incomplete ok trace(s); first: "
                     f"{trace_report['completeness_violations'][:1]}")
        check.expect(trace_report["multi_root"] == 0,
                     f"rollout traces: {trace_report['multi_root']} "
                     f"multi-root trace(s)")
        check.expect(trace_report["traces_ok"] == n_expected,
                     f"rollout traces: {trace_report['traces_ok']} ok "
                     f"roots for {n_expected} successful requests")

    check.expect(not bad, f"rollout: {len(bad)} request failure(s)/"
                          f"mismatch(es); first: {bad[0] if bad else ''}")
    check.expect(n_served[0] > 0, "rollout: no requests served at all")
    check.expect(router_stats["failed"] == 0,
                 f"rollout: router failed {router_stats['failed']} "
                 f"future(s) — lost work during the rollout")
    summary_lat = lat.summary_dict()
    p99 = summary_lat.get("p99_ms", float("inf"))
    p50 = summary_lat.get("p50_ms", 0.0)
    p99_bound = max(20.0 * max(p50, 1.0), 2000.0)
    check.expect(p99 <= p99_bound,
                 f"rollout: p99 {p99:.0f}ms not bounded (limit "
                 f"{p99_bound:.0f}ms = max(20 x p50, 2000ms))")
    return {"ready_s": round(ready_s, 1),
            "rollout_s": round(rollout_s, 1),
            "served_during": n_served[0],
            "swapped": summary["swapped"],
            "router": router_stats,
            "client_latency": summary_lat,
            "p99_bound_ms": p99_bound,
            "versions_differ": versions_differ,
            "trace_attribution": trace_report.get("stage_ms"),
            "trace_clock": trace_report.get("clock"),
            "traces_ok": trace_report.get("traces_ok"),
            "trace_orphans": trace_report.get("orphans")}


# -- main ------------------------------------------------------------------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dryrun", action="store_true",
                   help="CI smoke: tiny corpus, short streams, all "
                        "assertions")
    p.add_argument("--skip_rollout", action="store_true")
    p.add_argument("--finetune_child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--workdir", default="")
    args = p.parse_args(argv)

    if args.finetune_child:
        run_finetune_child(args)
        return 0

    from pertgnn_tpu import telemetry
    from pertgnn_tpu.config import TelemetryConfig

    check = Check()
    t0 = time.perf_counter()
    tmp = args.workdir or tempfile.mkdtemp(prefix="stream_bench_")
    os.makedirs(tmp, exist_ok=True)
    tele_dir = os.path.join(tmp, "tele_parent")
    telemetry.configure_from_config(
        TelemetryConfig(telemetry_dir=tele_dir, telemetry_level="trace",
                        trace_sample_rate=1.0),
        run_meta={"cli": "stream_bench"})
    from pertgnn_tpu.aot import enable_compile_cache

    spec = corpus_spec(args.dryrun)
    shards, _spans_all, _res_all = make_corpus(spec)

    # -- base build + base training (checkpoint v1) ---------------------
    cfg0 = make_cfg(tmp)
    enable_compile_cache(cfg0.aot)
    base, deltas, pre_table = ingest_all(tmp, spec, cfg0, shards)
    from pertgnn_tpu.batching import build_dataset

    base_ds0 = build_dataset(pre_table[0], cfg0, pre_table[1])
    budget = (base_ds0.budget.max_nodes, base_ds0.budget.max_edges)
    cfg = make_cfg(tmp, budget=budget)
    base_ds = build_dataset(pre_table[0], cfg, pre_table[1])

    from pertgnn_tpu.train.checkpoint import CheckpointManager
    from pertgnn_tpu.train.loop import fit

    ckpt_v1 = CheckpointManager(os.path.join(tmp, "ckpt_v1"),
                                keep=cfg.train.checkpoint_keep)
    _state, history = fit(base_ds, cfg, epochs=cfg.train.epochs,
                          checkpoint_manager=ckpt_v1)
    ckpt_v1.wait()
    v1_epoch = cfg.train.epochs - 1
    baseline_qloss = history[-1]["valid_qloss"]
    # v2 starts as a copy of v1; the fine-tune child advances it
    shutil.copytree(os.path.join(tmp, "ckpt_v1"),
                    os.path.join(tmp, "ckpt_v2"), dirs_exist_ok=True)

    from pertgnn_tpu.models.pert_model import entry_capacity

    with open(os.path.join(tmp, "budget.json"), "w") as f:
        json.dump({
            "max_nodes": budget[0], "max_edges": budget[1],
            "baseline_qloss": baseline_qloss,
            "checkpoint_vocab": {
                "num_ms": base_ds.num_ms,
                "num_entries": base_ds.num_entries,
                "num_interfaces": base_ds.num_interfaces,
                "num_rpctypes": base_ds.num_rpctypes},
            "frozen_valid": {
                "entry_ids": base_ds.splits["valid"].entry_ids.tolist(),
                "ts_buckets": base_ds.splits["valid"].ts_buckets.tolist(),
                "ys": base_ds.splits["valid"].ys.tolist()},
            "frozen_test": {
                "entry_ids": base_ds.splits["test"].entry_ids.tolist(),
                "ts_buckets": base_ds.splits["test"].ts_buckets.tolist(),
                "ys": base_ds.splits["test"].ys.tolist()},
        }, f)
    # raw CSVs + artifact/arena caches for the fleet workers
    write_raw_csvs(shards[0][0], shards[0][1],
                   os.path.join(tmp, "raw_base"))
    from pertgnn_tpu.cli.common import (build_dataset_cached,
                                        config_from_args)
    from pertgnn_tpu.cli.fleet_main import _parser as fleet_parser

    wargs = fleet_parser().parse_args(
        worker_argv(tmp, budget, os.path.join(tmp, "ckpt_v1"), "seed",
                    0)[3:])
    worker_ds = build_dataset_cached(wargs, config_from_args(wargs))
    check.expect(
        len(worker_ds.splits["valid"]) == len(base_ds.splits["valid"]),
        "worker-path dataset (CSV round-trip) differs from the "
        "in-process base dataset")

    results: dict = {"tmp": tmp,
                     "base_epochs": cfg.train.epochs,
                     "baseline_qloss": baseline_qloss}

    # -- phase: merge equality ------------------------------------------
    results["merge"] = check_merge_equality(check, cfg, base, deltas,
                                            shards)

    # -- phase: warm-restart fine-tune (fresh process) ------------------
    results["finetune"] = check_finetune(check, tmp, args.dryrun)
    v2_epoch = v1_epoch + cfg.stream.finetune_epochs

    # -- phase: rollout under live traffic ------------------------------
    if not args.skip_rollout:
        results["rollout"] = check_rollout(check, tmp, cfg, base_ds,
                                           budget, v1_epoch, v2_epoch,
                                           args.dryrun)

    telemetry.get_bus().flush()
    names = telemetry_names(tele_dir)
    for counter in ("stream.shard_new_entries",
                    "stream.shard_new_topologies",
                    "stream.merged_shards", "stream.merge_seconds",
                    "stream.shard_ingest_seconds"):
        check.expect(counter in names,
                     f"telemetry: {counter} missing from the parent "
                     f"JSONL")
    if not args.skip_rollout:
        for counter in ("rollout.started", "rollout.worker_drained",
                        "rollout.worker_ready", "rollout.completed",
                        "rollout.worker_swap_seconds"):
            check.expect(counter in names,
                         f"telemetry: {counter} missing from the "
                         f"parent JSONL")

    print(json.dumps({
        "metric": "stream_invariants_ok",
        "value": int(not check.failures),
        "unit": "bool",
        "dryrun": args.dryrun,
        "results": results,
        "violations": check.failures,
        "wall_s": round(time.perf_counter() - t0, 1),
        "captured_unix_time": time.time(),
    }))
    return 1 if check.failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
