#!/bin/bash
# Opportunistic on-chip bench capture (VERDICT r3 next-round #1).
#
# The axon relay wedges and recovers on minute-to-hour timescales; a
# single bench invocation at a fixed time can land in a wedged window and
# lose the whole round's chip measurement. This watcher polls a cheap
# probe and, the moment the tunnel answers, runs the full bench — which
# pins the result + commit hash to benchmarks/last_good_tpu.json via
# bench.py::_persist_last_good_tpu.
#
# Usage: nohup bash benchmarks/tpu_watch.sh >> benchmarks/tpu_watch.log &
set -u
cd "$(dirname "$0")/.."
PROBES=${TPU_WATCH_PROBES:-120}
SLEEP=${TPU_WATCH_SLEEP:-240}
for i in $(seq 1 "$PROBES"); do
  if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel healthy (probe $i); running bench"
    BENCH_PROBE_TIMEOUT=75 BENCH_PROBE_TRIES=2 timeout 5400 python bench.py
    rc=$?
    echo "$(date -u +%FT%TZ) bench exited rc=$rc"
    # a wedge can strike mid-bench; only stop once a TPU result is pinned
    if [ $rc -eq 0 ] && [ -f benchmarks/last_good_tpu.json ]; then
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) probe $i wedged"
  fi
  sleep "$SLEEP"
done
echo "$(date -u +%FT%TZ) tunnel never recovered"
exit 1
