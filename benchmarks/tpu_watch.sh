#!/bin/bash
# Opportunistic on-chip bench capture (VERDICT r3 next-round #1).
#
# The axon relay wedges and recovers on minute-to-hour timescales; a
# single bench invocation at a fixed time can land in a wedged window and
# lose the whole round's chip measurement. This watcher polls a cheap
# probe and, the moment the tunnel answers, runs the full bench — which
# pins the result + commit hash to benchmarks/last_good_tpu.json via
# bench.py::_persist_last_good_tpu.
#
# Usage: nohup bash benchmarks/tpu_watch.sh >> benchmarks/tpu_watch.log &
set -u
cd "$(dirname "$0")/.."
PROBES=${TPU_WATCH_PROBES:-170}
SLEEP=${TPU_WATCH_SLEEP:-240}
OUT=${TPU_WATCH_OUT:-benchmarks/tpu_r5_results.jsonl}
# whatever kills the watcher, never leave the paused CPU hogs frozen
trap 'if [ -f benchmarks/cpu_hogs.pid ]; then
        xargs -r kill -CONT -- < benchmarks/cpu_hogs.pid 2>/dev/null; fi' EXIT
for i in $(seq 1 "$PROBES"); do
  if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel healthy (probe $i); running bench"
    # single-core host: pause background CPU hogs (e.g. the 24-seed
    # quality run) so host-side dispatch isn't starved mid-measurement
    if [ -f benchmarks/cpu_hogs.pid ]; then
      xargs -r kill -STOP -- < benchmarks/cpu_hogs.pid 2>/dev/null \
        && echo "$(date -u +%FT%TZ) paused cpu hogs"
    fi
    BENCH_PROBE_TIMEOUT=75 BENCH_PROBE_TRIES=2 timeout 5400 python bench.py
    rc=$?
    echo "$(date -u +%FT%TZ) bench exited rc=$rc"
    # a wedge can strike mid-bench; only stop once a TPU result is pinned
    if [ $rc -eq 0 ] && [ -f benchmarks/last_good_tpu.json ]; then
      # opportunistically capture the on-chip adjudication rows too
      # (VERDICT r4 #4): deep_wide + bf16 lever + giant_dag + crossover.
      # A wedge mid-suite must NOT end the watcher: record each rc and
      # only stop once every config produced a row; otherwise keep
      # polling and retry the whole capture on the next healthy probe.
      suite_ok=1
      for cfgname in flagship_chip deep_wide deep_wide_bf16 giant_dag \
                     pallas_crossover; do
        echo "$(date -u +%FT%TZ) running benchmarks/run.py --config $cfgname"
        timeout 3600 python benchmarks/run.py --config "$cfgname" \
          >> "$OUT"
        crc=$?
        echo "$(date -u +%FT%TZ) $cfgname rc=$crc"
        [ $crc -eq 0 ] || suite_ok=0
      done
      if [ $suite_ok -eq 1 ]; then
        echo "$(date -u +%FT%TZ) TPU suite captured"
        # opportunistic extra (VERDICT r4 #5): chip-backend crash-resume
        # drill — failure here must not void the captured suite
        echo "$(date -u +%FT%TZ) running endurance drill (chip backend)"
        timeout 5400 python benchmarks/endurance_drill.py --scale cpu \
          --epochs 60 >> "$OUT"
        echo "$(date -u +%FT%TZ) endurance drill rc=$?"
        if [ -f benchmarks/cpu_hogs.pid ]; then
          xargs -r kill -CONT -- < benchmarks/cpu_hogs.pid 2>/dev/null
        fi
        exit 0
      fi
      echo "$(date -u +%FT%TZ) TPU suite incomplete; will retry"
    fi
    if [ -f benchmarks/cpu_hogs.pid ]; then
      xargs -r kill -CONT -- < benchmarks/cpu_hogs.pid 2>/dev/null \
        && echo "$(date -u +%FT%TZ) resumed cpu hogs"
    fi
  else
    echo "$(date -u +%FT%TZ) probe $i wedged"
  fi
  sleep "$SLEEP"
done
echo "$(date -u +%FT%TZ) tunnel never recovered"
exit 1
