#!/bin/bash
# Opportunistic on-chip bench capture (VERDICT r3 #1 / r4 #1).
#
# The axon relay wedges and recovers on minute-to-hour timescales; a
# single bench invocation at a fixed time can land in a wedged window and
# lose the whole round's chip measurement. This watcher polls a cheap
# probe and, the moment the tunnel answers, runs whatever of the capture
# is still missing:
#   0. bench.py --precompile (once per round) — populates the on-disk
#      compile cache so the capture window's first step is execute-only
#      (ISSUE 3); its stats row (cache hit/miss split) lands in $OUT.
#   1. bench.py --capture (graftprobe, ISSUE 17) — the journaled stage
#      machine: every completed stage persists to the capture journal,
#      so a window that closes mid-run costs only the in-flight stage
#      and the NEXT healthy window re-enters at the first incomplete
#      stage instead of restarting the bench (rc=3 window closed /
#      rc=4 wedged are resumable, not failures). Pins
#      benchmarks/last_good_tpu.json when the stitched capture is
#      on-chip; `bench.py --finalize-partial` (host-only) additionally
#      folds the journal, so >=3 captured fit windows are never lost.
#      Every probe attempt is journaled too (timestamp/outcome/latency)
#      so adjudicate.py reports measured tunnel availability, and any
#      journaled wedge stage is logged on the next poll.
#   2. the adjudication configs (flagship_chip, deep_wide, deep_wide_bf16,
#      giant_dag, pallas_crossover) — one row each into $OUT, with a
#      .r5_done marker per config so a retry window only runs what's
#      missing.
#   3. a chip-backend crash-resume endurance drill (best-effort extra).
#
# Usage: nohup bash benchmarks/tpu_watch.sh >> benchmarks/tpu_watch.log &
set -u
cd "$(dirname "$0")/.."
PROBES=${TPU_WATCH_PROBES:-170}
SLEEP=${TPU_WATCH_SLEEP:-240}
OUT=${TPU_WATCH_OUT:-benchmarks/tpu_r5_results.jsonl}
PIN=benchmarks/last_good_tpu.json
UPGRADE_TRIES=${TPU_WATCH_UPGRADE_TRIES:-2}
# per-config budget: generous vs a legitimate run (minutes), small vs the
# relay's recovery timescale — a wedged config must not hold a recovered
# tunnel hostage for a full hour before the next retry
CFG_TIMEOUT=${TPU_WATCH_CFG_TIMEOUT:-1800}
JOURNAL=${BENCH_CAPTURE_JOURNAL:-benchmarks/capture_journal.jsonl}

# Journal every probe attempt (ISSUE 17): the timestamp rides the
# record envelope; adjudicate.py turns the sequence into the round's
# tunnel-availability statistics (healthy-window count + duration
# histogram). A journaling failure must never kill the watcher.
journal_probe() {  # $1 = 1|0 (ok), $2 = latency seconds
  python - "$JOURNAL" "$1" "$2" <<'EOF' 2>/dev/null || true
import sys
from pertgnn_tpu.telemetry.capture import journal_probe
journal_probe(sys.argv[1], ok=sys.argv[2] == "1",
              latency_s=float(sys.argv[3]))
EOF
}

last_wedges=0
# On each poll, log any NEWLY journaled wedge stage (graftprobe's
# watchdog / orphan diagnosis): the r5 failure mode was 12+ hours of
# probing with zero hint of WHERE the capture died.
wedge_check() {
  local w n stage
  w=$(python - "$JOURNAL" <<'EOF' 2>/dev/null
import sys
from pertgnn_tpu.telemetry.capture import CaptureJournal, wedged_stages
ws = wedged_stages(CaptureJournal(sys.argv[1]).records())
print(len(ws), ws[-1] if ws else "-")
EOF
) || return 0
  n=${w%% *}; stage=${w#* }
  if [ -n "$n" ] && [ "$n" -gt "$last_wedges" ] 2>/dev/null; then
    echo "$(date -u +%FT%TZ) capture wedged inside stage '$stage'" \
         "($n wedge record(s) journaled)"
    last_wedges=$n
  fi
}

# A pin only suppresses the headline bench if it parses, is on-chip, and
# is fresh (<24 h): a stale or corrupt leftover from an earlier run must
# not silently end this round's capture.
pin_state() {  # prints: missing | full | partial
  python - "$PIN" <<'EOF'
import json, sys, time
try:
    d = json.load(open(sys.argv[1]))
    ok = (d.get("backend") == "tpu"
          and time.time() - d.get("captured_unix_time", 0) < 86400)
    print(("partial" if d.get("partial_capture") else "full")
          if ok else "missing")
except Exception:
    print("missing")
EOF
}

upgrades_used=0

# Capture artifacts are the round's scarcest output: commit whichever of
# them exist so a late-round capture survives even if no human or agent
# ever looks at the watcher again. Pathspec-limited commit of only the
# files that exist; on a failed commit the paths are unstaged again so a
# later unrelated `git commit` can't silently sweep them up. Any step
# hitting a concurrent index.lock just returns — retried next window.
commit_capture() {
  local paths=() p err
  for p in "$PIN" "$OUT" "$JOURNAL"; do [ -f "$p" ] && paths+=("$p"); done
  [ ${#paths[@]} -eq 0 ] && return 0
  # a persistent add failure (ownership, future ignore rule) must be
  # VISIBLE in the log, or the feature can be dead all round unnoticed —
  # and a PARTIAL add must be unstaged, or a later unrelated commit
  # sweeps the staged half up
  if ! err=$(git add -- "${paths[@]}" 2>&1); then
    echo "$(date -u +%FT%TZ) commit_capture: git add failed: $err"
    git reset -q -- "${paths[@]}" 2>/dev/null
    return 0
  fi
  if git commit -m "On-chip capture artifacts (watcher auto-commit)" \
       -- "${paths[@]}" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) capture artifacts committed"
  else
    # unstage so a later unrelated commit can't sweep these up; the
    # reset can hit the same transient index.lock the commit did —
    # retry briefly and LOG if the paths remain staged
    for _ in 1 2 3; do
      git reset -q -- "${paths[@]}" 2>/dev/null && return 0
      sleep 2
    done
    echo "$(date -u +%FT%TZ) commit_capture: WARNING — commit failed and" \
         "paths may still be staged: ${paths[*]}"
  fi
  return 0
}

# a fresh watcher = a fresh round: new code means new HLO and new cache
# keys, so last round's precompile marker must not suppress this one's
rm -f benchmarks/.precompiled_this_round

# whatever kills the watcher, never leave the paused CPU hogs frozen
trap 'if [ -f benchmarks/cpu_hogs.pid ]; then
        xargs -r kill -CONT -- < benchmarks/cpu_hogs.pid 2>/dev/null; fi' EXIT
for i in $(seq 1 "$PROBES"); do
  wedge_check
  p0=$SECONDS
  if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    journal_probe 1 $((SECONDS - p0))
    echo "$(date -u +%FT%TZ) tunnel healthy (probe $i)"
    # single-core host: pause background CPU hogs (e.g. long test or
    # quality runs) so host-side dispatch isn't starved mid-measurement
    if [ -f benchmarks/cpu_hogs.pid ]; then
      xargs -r kill -STOP -- < benchmarks/cpu_hogs.pid 2>/dev/null \
        && echo "$(date -u +%FT%TZ) paused cpu hogs"
    fi
    ran_bench=0; bench_ok=1
    state=$([ -f "$PIN" ] && pin_state || echo missing)
    if [ "$state" = missing ] && [ -f "$PIN" ]; then
      echo "$(date -u +%FT%TZ) discarding stale/corrupt pin"
      mv -f "$PIN" "$PIN.stale"
    fi
    # a partial (wedge-salvaged) pin is kept but upgraded to a full
    # capture while upgrade budget remains
    if [ "$state" = missing ] || { [ "$state" = partial ] \
        && [ "$upgrades_used" -lt "$UPGRADE_TRIES" ]; }; then
      [ "$state" = partial ] && upgrades_used=$((upgrades_used + 1)) \
        && echo "$(date -u +%FT%TZ) upgrading partial pin (try $upgrades_used/$UPGRADE_TRIES)"
      # Cold-start elimination (ISSUE 3): populate the persistent
      # compile cache BEFORE arming the capture window, so the window's
      # first step is execute-only instead of wedging inside XLA. The
      # stats row (per-program seconds + cache hit/miss split) goes
      # into $OUT as evidence; a failed precompile only costs the
      # warm start — the bench still runs.
      if [ ! -f benchmarks/.precompiled_this_round ]; then
        echo "$(date -u +%FT%TZ) running bench.py --precompile"
        BENCH_PROBE_TIMEOUT=75 BENCH_PROBE_TRIES=2 timeout 1800 \
          python bench.py --precompile >> "$OUT"
        prc=$?
        echo "$(date -u +%FT%TZ) precompile rc=$prc"
        # only a SUCCESSFUL precompile is done-for-the-round; a wedged
        # one retries in the next healthy window
        [ $prc -eq 0 ] && touch benchmarks/.precompiled_this_round
      fi
      echo "$(date -u +%FT%TZ) running bench.py --capture"
      ran_bench=1
      bench_out=$(mktemp)
      BENCH_PROBE_TIMEOUT=75 BENCH_PROBE_TRIES=2 timeout 5400 \
        python bench.py --capture | tee "$bench_out"
      rc=${PIPESTATUS[0]}
      echo "$(date -u +%FT%TZ) bench exited rc=$rc"
      if [ $rc -ne 0 ]; then
        bench_ok=0
        # rc=3/4 are graftprobe's RESUMABLE exits — the journal holds
        # every completed stage and the next healthy window re-enters
        # at the first incomplete one (a window closed / wedged stage
        # costs only itself, never the round)
        [ $rc -eq 3 ] && echo "$(date -u +%FT%TZ) capture window closed (journal resumable; will re-enter)"
        [ $rc -eq 4 ] && { echo "$(date -u +%FT%TZ) capture stage wedged (diagnosis journaled; will re-enter)"; wedge_check; }
        # promote whatever windows the dead bench flushed (host-only,
        # cannot dial the wedged tunnel) — the finalizer now also folds
        # the capture journal; an existing partial pin survives if this
        # attempt produced nothing better
        JAX_PLATFORMS=cpu timeout 1800 python bench.py --finalize-partial
        frc=$?
        echo "$(date -u +%FT%TZ) finalize-partial rc=$frc"
      elif ! grep -q '"backend": *"tpu"' "$bench_out"; then
        # rc=0 but the run fell back off-chip: bench.py deliberately
        # keeps a promotable TPU salvage (_discard_partials
        # keep_tpu_salvage) — promote it NOW, or surviving chip windows
        # sit orphaned until some later failing run happens to finalize
        bench_ok=0
        echo "$(date -u +%FT%TZ) bench completed off-chip; finalizing any TPU salvage"
        JAX_PLATFORMS=cpu timeout 1800 python bench.py --finalize-partial
        frc=$?
        echo "$(date -u +%FT%TZ) finalize-partial rc=$frc"
      fi
      rm -f "$bench_out"
    fi
    # Attempt the config suite only in a window where the tunnel is
    # known-healthy: either bench just succeeded here, or bench was
    # already pinned and the probe above just answered.
    if [ -f "$PIN" ] && { [ $ran_bench -eq 0 ] || [ $bench_ok -eq 1 ]; }; then
      suite_ok=1
      for cfgname in flagship_chip deep_wide deep_wide_bf16 giant_dag \
                     pallas_crossover; do
        marker="benchmarks/.r5_done_$cfgname"
        [ -f "$marker" ] && continue
        echo "$(date -u +%FT%TZ) running benchmarks/run.py --config $cfgname"
        tmp_row=$(mktemp)
        timeout "$CFG_TIMEOUT" python benchmarks/run.py --config "$cfgname" \
          > "$tmp_row"
        crc=$?
        cat "$tmp_row" >> "$OUT"
        # run.py exits 0 even when it only emitted a failed/skipped row
        # (it catches per-config exceptions); the marker must mean "a
        # real measurement exists", else a flap permanently skips the
        # config. Gate on the row content, not just the exit code.
        if [ $crc -eq 0 ] && python - "$tmp_row" <<'EOF'
import json, sys
rows = []
for l in open(sys.argv[1]):
    try:
        rows.append(json.loads(l))
    except ValueError:
        pass  # non-JSON progress chatter doesn't decide the outcome
ok = bool(rows) and not any(("failed" in r) or ("skipped" in r)
                            for r in rows)
sys.exit(0 if ok else 1)
EOF
        then touch "$marker"; else suite_ok=0; fi
        echo "$(date -u +%FT%TZ) $cfgname rc=$crc done=$([ -f "$marker" ] && echo yes || echo no)"
        rm -f "$tmp_row"
      done
      if [ $suite_ok -eq 1 ]; then
        echo "$(date -u +%FT%TZ) TPU suite captured"
        commit_capture
        # opportunistic extras — failures here must not void the
        # captured suite: scan-fusion depth sweep (flagship dispatch
        # lever), then a chip-backend crash-resume drill (VERDICT r4 #5)
        echo "$(date -u +%FT%TZ) running scan_chunk_sweep"
        timeout "$CFG_TIMEOUT" python benchmarks/run.py \
          --config scan_chunk_sweep >> "$OUT"
        src=$?
        echo "$(date -u +%FT%TZ) scan_chunk_sweep rc=$src"
        echo "$(date -u +%FT%TZ) running endurance drill (chip backend)"
        timeout 5400 python benchmarks/endurance_drill.py --scale cpu \
          --epochs 60 >> "$OUT"
        drc=$?
        echo "$(date -u +%FT%TZ) endurance drill rc=$drc"
        commit_capture
        if [ -f benchmarks/cpu_hogs.pid ]; then
          xargs -r kill -CONT -- < benchmarks/cpu_hogs.pid 2>/dev/null
        fi
        exit 0
      fi
      echo "$(date -u +%FT%TZ) TPU suite incomplete; will retry"
    fi
    # every healthy window: persist whatever capture artifacts exist by
    # now (a pre-existing pin, partial-suite rows) — not only the
    # bench-ran or full-suite paths
    commit_capture
    if [ -f benchmarks/cpu_hogs.pid ]; then
      xargs -r kill -CONT -- < benchmarks/cpu_hogs.pid 2>/dev/null \
        && echo "$(date -u +%FT%TZ) resumed cpu hogs"
    fi
  else
    journal_probe 0 $((SECONDS - p0))
    echo "$(date -u +%FT%TZ) probe $i wedged"
  fi
  sleep "$SLEEP"
done
echo "$(date -u +%FT%TZ) tunnel never recovered"
exit 1
