"""Merge seed-sharded quality_parity rows into one 24-seed verdict.

    python benchmarks/quality_merge.py shard1.jsonl shard2.jsonl ... \
        [--out merged.json]

Each input line is a `quality_parity` row produced with QUALITY_SEEDS /
QUALITY_SEED_OFFSET / QUALITY_GRAPH_TYPES (benchmarks/run.py). Per-seed
arrays are concatenated per graph type and the cross-shard statistics —
mean ± 95% CI per arm, bootstrap 95% CI of the train-fit ratio-of-means,
and the pre-registered equivalence verdict (CI ⊂ [0.93, 1.07], VERDICT
r4 #3) — are recomputed from scratch.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from run import _mean_ci95, _ratio_ci95  # noqa: E402


def merge(rows: list[dict]) -> dict:
    parity_rows = [r for r in rows
                   if r.get("metric") == "quality_parity_test_mae_ratio"
                   and "failed" not in r and "skipped" not in r]
    if not parity_rows:
        raise SystemExit("no successful quality_parity rows in inputs")
    epochs = {r["epochs"] for r in parity_rows}
    if len(epochs) != 1:
        raise SystemExit(f"refusing to merge mixed epoch counts: {epochs}")
    # Overlapping seed ranges would double-count seeds and fabricate CI
    # precision — refuse, PER GRAPH TYPE (a pert-only and a span-only
    # shard legitimately reuse the same seed range; a row without
    # seed_offset predates sharding and is treated as offset 0).
    for gtype in ("pert", "span"):
        ranges = sorted((r.get("seed_offset", 0),
                         r.get("seed_offset", 0) + r["seeds_per_side"])
                        for r in parity_rows if gtype in r)
        for (a0, a1), (b0, b1) in zip(ranges, ranges[1:]):
            if b0 < a1:
                raise SystemExit(
                    f"overlapping {gtype} shard seed ranges [{a0},{a1}) "
                    f"and [{b0},{b1}) — same seeds would be double-counted")
    out = {"metric": "quality_parity_merged", "epochs": epochs.pop(),
           "shards": len(parity_rows),
           "commits": sorted({r.get("commit") or "?" for r in parity_rows})}
    for gtype in ("pert", "span"):
        shards = [r[gtype] for r in parity_rows if gtype in r]
        if not shards:
            continue
        g = {}
        for key in ("test_ours_per_seed", "test_torch_per_seed",
                    "trainfit_ours_per_seed", "trainfit_torch_per_seed"):
            g[key] = [v for s in shards for v in s[key]]
        n = len(g["trainfit_ours_per_seed"])
        for arm in ("test_ours", "test_torch", "trainfit_ours",
                    "trainfit_torch"):
            mean, ci = _mean_ci95(g[f"{arm}_per_seed"])
            g[f"{arm}_mean_mae"] = round(mean, 1)
            g[f"{arm}_ci95"] = round(ci, 1)
        g["seeds_per_side"] = n
        g["test_ratio_of_means"] = round(
            g["test_ours_mean_mae"] / max(g["test_torch_mean_mae"], 1e-9), 3)
        g["trainfit_ratio_of_means"] = round(
            g["trainfit_ours_mean_mae"]
            / max(g["trainfit_torch_mean_mae"], 1e-9), 3)
        lo, hi = _ratio_ci95(g["trainfit_ours_per_seed"],
                             g["trainfit_torch_per_seed"])
        g["trainfit_ratio_ci95"] = [round(lo, 3), round(hi, 3)]
        g["trainfit_equivalent_0.93_1.07"] = bool(lo >= 0.93 and hi <= 1.07)
        g["trainfit_noninferior_1.07"] = bool(hi <= 1.07)
        out[gtype] = g
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("inputs", nargs="+")
    p.add_argument("--out", default="")
    args = p.parse_args(argv)
    rows = []
    for path in args.inputs:
        with open(path) as f:
            rows.extend(json.loads(line) for line in f if line.strip())
    merged = merge(rows)
    print(json.dumps(merged))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=1)


if __name__ == "__main__":
    main()
