"""Kernel microbenchmark: segment vs pallas vs blocked-dense, per bucket.

Exit-code oracle for the conv hot-op implementations (ISSUE 6): for each
shape bucket it runs every `attention_impl` variant forward AND
backward, asserts numerical parity against the segment reference within
the dtype tolerance, and emits one JSON row per (bucket, variant) —
JSONL on stdout, one final summary line last. A parity failure exits
nonzero: a kernel that is fast but wrong must turn the bench red, never
land in a capture.

Timed numbers are honest about the backend: off-TPU the Pallas variants
run in INTERPRET mode (orders of magnitude slower — correctness rows,
not performance rows; `interpreted: true` marks them), while segment and
blocked_dense compile natively everywhere, so CPU timings for those two
ARE meaningful A/Bs. Each row carries the XLA cost-analysis FLOPs/bytes
and the roofline attribution schema shared with bench.py/serve_bench.py
(utils/flops.variant_attribution) so per-variant mfu/mbu appear the
moment this runs on a chip.

Shape buckets mirror the serve ladder's discipline: small per-topology
graphs padded to 128-aligned (nodes, edges) tiles — exactly the regime
where arXiv:1906.11786's blocked-dense recast should win on systolic
hardware and where `ModelConfig.blocked_dense_max_cells` admits it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# (nodes, edges) per bucket — spanning sub-tile, one-tile and multi-tile
# shapes so block-boundary handling is exercised, not just the happy path
BUCKETS = ((48, 160), (128, 512), (260, 1024))
HEADS, HEAD_DIM, F_IN = 2, 16, 32

# parity tolerance: all variants take f32 inputs and accumulate f32
# internally, so fwd must agree to float rounding (grads get 10x slack
# for the longer reduction chains)
TOL = dict(rtol=1e-4, atol=1e-4)

VARIANTS = ("segment", "pallas", "pallas_fused", "blocked_dense")


def make_case(n, e, seed):
    """One receiver-sorted masked attention case + epilogue operands."""
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(n, HEADS, HEAD_DIM)).astype(np.float32)
    k = rng.normal(size=(e, HEADS, HEAD_DIM)).astype(np.float32)
    v = rng.normal(size=(e, HEADS, HEAD_DIM)).astype(np.float32)
    rcv = rng.integers(0, n, e)
    mask = rng.random(e) > 0.15
    order = np.argsort(np.where(mask, rcv, n), kind="stable")
    x = rng.normal(size=(n, F_IN)).astype(np.float32)
    w = rng.normal(size=(F_IN, HEADS * HEAD_DIM)).astype(np.float32)
    b = rng.normal(size=(HEADS * HEAD_DIM,)).astype(np.float32)
    node_mask = rng.random(n) > 0.1
    return (q, k[order], v[order], rcv[order].astype(np.int32),
            mask[order], x, w, b, node_mask)


def build_fns(variant, n, e):
    """(fwd, loss) for one variant at one shape bucket. fwd returns the
    layer-epilogue output y = attn + x @ w + b for EVERY variant so the
    parity claim covers the full fused surface, not just the attention
    core; loss is a scalar for grad parity."""
    import jax.numpy as jnp

    from pertgnn_tpu.ops import blocked_dense as bd
    from pertgnn_tpu.ops.pallas_attention import edge_attention, fused_epilogue
    from pertgnn_tpu.ops.segment import segment_edge_attention

    def attn(q, k, v, rcv, mask):
        if variant == "segment":
            return segment_edge_attention(q, k, v, rcv, mask, n)
        if variant in ("pallas", "pallas_fused"):
            return edge_attention(q, k, v, rcv, mask, n, assume_sorted=True)
        return bd.blocked_dense_edge_attention(q, k, v, rcv, mask, n)

    def fwd(q, k, v, rcv, mask, x, w, b, node_mask):
        out = attn(q, k, v, rcv, mask)
        if variant == "pallas_fused":
            y, _stats = fused_epilogue(out, x, w, b, node_mask)
            return y
        return out + x @ w + b[None, :]

    def loss(q, k, v, x, w, rcv, mask, b, node_mask):
        return (fwd(q, k, v, rcv, mask, x, w, b, node_mask) ** 2).sum()

    return fwd, loss


def reference_outputs(bucket, case):
    """Segment-reference (fwd, grads) for one bucket — computed ONCE per
    bucket and shared by every variant's parity check."""
    import jax

    n, e = bucket
    q, k, v, rcv, mask, x, w, b, node_mask = case
    ref_fwd, ref_loss = build_fns("segment", n, e)
    ref_y = np.asarray(jax.jit(ref_fwd)(*case))
    ref_g = jax.jit(jax.grad(ref_loss, argnums=tuple(range(5))))(
        q, k, v, x, w, rcv, mask, b, node_mask)
    return ref_y, [np.asarray(g) for g in ref_g]


def bench_variant(variant, bucket, case, ref, reps):
    """One JSON row: parity (fwd + grads wrt q/k/v/x/w) vs the segment
    reference, wall times, cost analysis, roofline attribution."""
    import jax

    from pertgnn_tpu.telemetry import devmem
    from pertgnn_tpu.utils import flops as flops_util

    n, e = bucket
    q, k, v, rcv, mask, x, w, b, node_mask = case
    ref_y, ref_g = ref
    var_fwd, var_loss = build_fns(variant, n, e)

    args_f = (q, k, v, rcv, mask, x, w, b, node_mask)
    jf = jax.jit(var_fwd)  # the ONE wrapper: compile timing + timed loop
    t_fwd = time.perf_counter()
    got_y = np.asarray(jf(*args_f))
    compile_fwd_s = time.perf_counter() - t_fwd
    err_fwd = float(np.abs(got_y - ref_y).max())

    grad_args = (q, k, v, x, w)
    gfn_var = jax.jit(jax.grad(var_loss, argnums=tuple(range(5))))
    got_g = gfn_var(*grad_args, rcv, mask, b, node_mask)
    err_bwd = float(max(np.abs(np.asarray(a) - r).max()
                        for a, r in zip(got_g, ref_g)))

    scale = float(np.abs(ref_y).max())
    gscale = float(max(np.abs(r).max() for r in ref_g))
    ok = (err_fwd <= TOL["atol"] + TOL["rtol"] * scale
          and err_bwd <= 10 * TOL["atol"] + 10 * TOL["rtol"] * gscale)

    t0 = time.perf_counter()
    for _ in range(reps):
        out = jf(*args_f)
    jax.block_until_ready(out)
    fwd_ms = (time.perf_counter() - t0) / reps * 1e3

    t0 = time.perf_counter()
    for _ in range(reps):
        g = gfn_var(*grad_args, rcv, mask, b, node_mask)
    jax.block_until_ready(g)
    fwdbwd_ms = (time.perf_counter() - t0) / reps * 1e3

    f_cost, b_cost = flops_util.compiled_cost(jf, *args_f)
    interpreted = (variant in ("pallas", "pallas_fused")
                   and jax.default_backend() != "tpu")
    row = {
        "metric": "pert_kernel_fwd_ms",
        "variant": variant,
        "bucket": {"nodes": n, "edges": e, "heads": HEADS,
                   "head_dim": HEAD_DIM},
        "value": fwd_ms,
        "unit": "ms",
        "fwd_ms": fwd_ms,
        "fwdbwd_ms": fwdbwd_ms,
        "compile_fwd_s": compile_fwd_s,
        "max_abs_err_fwd": err_fwd,
        "max_abs_err_grad": err_bwd,
        "parity_ok": ok,
        "interpreted": interpreted,
        "reps": reps,
        "roofline": flops_util.variant_attribution(
            attention_impl=variant, dtype="f32",
            graphs_per_s=(1e3 / fwd_ms) if fwd_ms else None,
            flops_per_graph=f_cost, bytes_per_graph=b_cost),
        # post-timing allocator state (ISSUE 17): peak bytes include the
        # timed kernel's live buffers; None off-chip (no memory_stats)
        "mem": devmem.device_memory_stats(),
    }
    return row


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--reps", type=int,
                    default=int(os.environ.get("KERNEL_BENCH_REPS", "3")),
                    help="timed repetitions per variant (post-warmup)")
    ap.add_argument("--out", default="",
                    help="also write the JSONL rows here")
    args = ap.parse_args()

    from pertgnn_tpu.cli.common import (apply_platform_env,
                                        probe_backend_or_fallback)
    fallback = probe_backend_or_fallback()
    apply_platform_env()

    import jax

    from pertgnn_tpu.telemetry import devmem

    rows, failures = [], []
    for bi, bucket in enumerate(BUCKETS):
        case = make_case(*bucket, seed=100 + bi)
        ref = reference_outputs(bucket, case)
        for variant in VARIANTS:
            row = bench_variant(variant, bucket, case, ref, args.reps)
            rows.append(row)
            print(json.dumps(row), flush=True)
            if not row["parity_ok"]:
                failures.append((variant, bucket,
                                 row["max_abs_err_fwd"],
                                 row["max_abs_err_grad"]))
    summary = {
        "metric": "pert_kernel_bench_summary",
        "rows": len(rows),
        "buckets": len(BUCKETS),
        "variants": list(VARIANTS),
        "parity_failures": len(failures),
        "backend": jax.default_backend(),
        "backend_fallback": fallback,
        "device_kind": getattr(jax.devices()[0], "device_kind", "") or "",
        "device_mem": devmem.device_memory_stats(),
        "captured_unix_time": time.time(),
    }
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            for row in rows + [summary]:
                f.write(json.dumps(row) + "\n")
    if failures:
        for variant, bucket, ef, eg in failures:
            print(f"PARITY FAIL: {variant} at {bucket}: fwd err {ef:.3e} "
                  f"grad err {eg:.3e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
