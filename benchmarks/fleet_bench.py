"""Fleet benchmark: scaling, warm start, and worker-death chaos.

Drives REAL fleets (cli/fleet_main.py child processes: one router, N
serve workers over the HTTP transport) and EXIT-CODE ASSERTS the
ISSUE-7 invariants; wall-clock numbers are reported in the JSON, the
verdict lives in the return code (the chaos_bench/coldstart_bench
split):

- **scaling** — the same request stream through N=1 and N=4 worker
  fleets from identical warm caches (workers pinned one-per-core —
  the CPU emulation of one-device-per-worker): N=4 throughput must
  reach >= 2.5x N=1 with p99 bounded. That gate needs >= 4 usable
  cores; on smaller hosts four single-core workers measure scheduler
  thrash, not the fleet, so the gate derates LOUDLY (stderr + JSON)
  to an N=2 PARITY check — the router/transport/requeue layer must
  not materially tax throughput even where it cannot add capacity. A
  silently weakened gate would be worse than an honest derated one.
- **warm start** — every worker of every fleet must report
  ``compiles == 0`` (rung executables deserialized from the shared
  --compile_cache_dir), ``arena_warm == true`` (dataset reconstructed
  from the shared --arena_cache_dir, zero ingest), via its own
  readiness-probe body — cold-to-ready in seconds, asserted.
- **chaos** — SIGKILL one worker of an N=2 fleet MID-TRAFFIC: the run
  must still serve EVERY request (zero lost Futures — the router
  requeues the dead worker's custody to the survivor) and every
  prediction must be BIT-IDENTICAL to a single-engine in-process
  reference (padding invariance + identical seeded state make the
  fleet's answers independent of which worker serves them).
- **telemetry** — the router.* counters (including the
  `router.queue_wait` autoscale gauge) land in the JSONL
  (docs/OBSERVABILITY.md).
- **tracing** — the chaos run serves at `--trace_sample_rate 1.0` and
  tools/graftscope must collect EVERY successful Future into exactly
  one root span with a complete stage chain (router queue → transport
  → worker queue → pack → dispatch → compute → complete), zero
  orphans, across the worker kill — plus the per-stage p99 critical-
  path breakdown embedded in this bench's JSON and a Perfetto export.

CPU by default. One JSON line on stdout.

    python benchmarks/fleet_bench.py [--smoke] [--skip_scaling]

``--smoke`` is the tier-1 wiring (tests/test_fleet.py): N=2, tiny
corpus, warm-start + chaos invariants only (no scaling phase).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


class Check:
    def __init__(self):
        self.failures: list[str] = []

    def expect(self, cond: bool, what: str):
        if not cond:
            self.failures.append(what)
            print(f"FLEET FAIL: {what}", file=sys.stderr)


def common_flags(tmp: str) -> list[str]:
    """The config every process (bench parent, workers, launcher)
    shares — identical flags are what make the AOT/arena cache keys
    line up and the fleet's predictions comparable to the in-process
    reference."""
    # model sized so the WORKERS are the measured resource: with a
    # trivial model the router's Python (one process, GIL) is the
    # ceiling and worker count cannot move throughput — the scaling
    # phase would measure routing overhead, not fleet capacity
    return ["--synthetic", "--synthetic_entries", "6",
            "--synthetic_traces_per_entry", "80",
            "--min_traces_per_entry", "5", "--label_scale", "1000",
            "--graph_type", "pert", "--hidden_channels", "48",
            "--num_layers", "2", "--num_heads", "4",
            "--batch_size", "16", "--max_graphs_per_batch", "8",
            "--artifact_dir", os.path.join(tmp, "art"),
            "--arena_cache_dir", os.path.join(tmp, "arena"),
            "--compile_cache_dir", os.path.join(tmp, "aot")]


def build_reference(tmp: str):
    """Build the corpus + caches IN-PROCESS (so run-1 workers already
    start warm) and return (dataset, engine) — the single-engine
    reference every fleet prediction must match bit-identically."""
    from pertgnn_tpu.cli.common import (build_dataset_cached,
                                        config_from_args,
                                        setup_compile_cache)
    from pertgnn_tpu.cli.fleet_main import _parser
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import restore_target_state

    args = _parser().parse_args([*common_flags(tmp), "--fresh_init"])
    setup_compile_cache(args)
    cfg = config_from_args(args)
    dataset = build_dataset_cached(args, cfg)
    _model, state = restore_target_state(dataset, cfg)
    engine = InferenceEngine.from_dataset(dataset, cfg, state).warmup()
    return dataset, engine


def request_stream(ds, n: int, csv_path: str) -> np.ndarray:
    """Write an n-request CSV tiled from every split (seeded shuffle
    for entry diversity) and return the per-request reference
    predictions, computed once per unique (entry, ts_bucket) pair —
    padding invariance makes solo dispatches the universal anchor."""
    import pandas as pd

    e = np.concatenate([np.asarray(s.entry_ids, np.int64)
                        for s in ds.splits.values()])
    t = np.concatenate([np.asarray(s.ts_buckets, np.int64)
                        for s in ds.splits.values()])
    perm = np.random.default_rng(0).permutation(len(e))
    e, t = e[perm], t[perm]
    reps = -(-n // len(e))
    e, t = np.tile(e, reps)[:n], np.tile(t, reps)[:n]
    pd.DataFrame({"entry_id": e, "ts_bucket": t}).to_csv(csv_path,
                                                         index=False)
    return e, t


def reference_preds(engine, entries, ts_buckets) -> np.ndarray:
    uniq: dict[tuple[int, int], float] = {}
    for eid, tsb in zip(entries, ts_buckets):
        key = (int(eid), int(tsb))
        if key not in uniq:
            uniq[key] = float(engine.predict_microbatch([key[0]],
                                                        [key[1]])[0])
    return np.asarray([uniq[(int(e), int(t))]
                       for e, t in zip(entries, ts_buckets)], np.float32)


def run_fleet(tmp: str, tag: str, num_workers: int, req_csv: str,
              kill_one_after_s: float | None = None,
              timeout_s: float = 900.0,
              telemetry_level: str = "basic",
              extra_flags: list[str] | None = None) -> dict:
    """One fleet_main run; returns {rc, stats, out_csv, killed_pid}.
    With kill_one_after_s, SIGKILLs the first worker that long after
    the bench OBSERVES TRAFFIC on it (queue depth/inflight > 0 in its
    probe body) — "mid-traffic" anchored on evidence, not on a sleep
    racing the stream: on a fast host a fixed post-ready delay can
    land after a short stream has already drained, and the chaos
    phase then asserts against a death nobody witnessed. Scaling runs
    keep telemetry at "basic": per-request trace writes serialize the
    router hot path (measured ~4x on 2 cores) and would gate the
    telemetry's overhead, not the fleet's scaling; the chaos run
    flips to "trace" (+ --trace_sample_rate 1.0 via extra_flags) to
    assert counter and TRACE coverage where no throughput is being
    measured."""
    from pertgnn_tpu.fleet.transport import WorkerTransportError, get_probe

    out_csv = os.path.join(tmp, f"served_{tag}.csv")
    cmd = [sys.executable, "-m", "pertgnn_tpu.cli.fleet_main",
           *common_flags(tmp), "--fresh_init",
           "--num_workers", str(num_workers),
           # one core per worker — the CPU emulation of the fleet's
           # real one-device-per-worker topology; without it a single
           # worker's XLA threadpool grabs every core and the N=1
           # "fleet" silently measures a whole-host baseline
           "--pin_worker_cpus",
           "--requests", req_csv, "--concurrency", "32",
           "--health_poll_interval_s", "0.3",
           "--router_dispatch_timeout_s", "30",
           "--telemetry_dir", os.path.join(tmp, f"tele_{tag}"),
           "--telemetry_level", telemetry_level,
           *(extra_flags or []),
           "--out", out_csv]
    child = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                             env={**os.environ, "JAX_PLATFORMS": "cpu"})
    killed_pid = None
    lines: list[str] = []
    try:
        if kill_one_after_s is not None:
            # line 1 is the machine-readable membership (pids + urls)
            first = child.stdout.readline()
            lines.append(first)
            members = json.loads(first)["fleet_workers"]
            deadline = time.monotonic() + timeout_s / 2
            victim = members[0]
            # watch the VICTIM until it is visibly serving (probe-body
            # load counters — the launcher only opens traffic once the
            # whole fleet is ready, so observed load implies readiness
            # everywhere); a bench-side all-ready pass before watching
            # would itself race a short stream on a fast host. Tight
            # 20 ms polling: the smoke stream can drain in ~1 s
            while time.monotonic() < deadline and child.poll() is None:
                try:
                    status, body = get_probe(victim["url"], 0.5)
                    q = body.get("queue", {})
                    if status == 200 and (q.get("depth", 0)
                                          + q.get("inflight", 0)) > 0:
                        break
                except WorkerTransportError:
                    pass
                time.sleep(0.02)
            time.sleep(kill_one_after_s)
            killed_pid = victim["pid"]
            print(f"fleet_bench: SIGKILL worker {victim['worker_id']} "
                  f"(pid {killed_pid}) mid-traffic", file=sys.stderr)
            try:
                os.kill(killed_pid, signal.SIGKILL)
            except ProcessLookupError:
                print("fleet_bench: victim already gone?!",
                      file=sys.stderr)
        out, _ = child.communicate(timeout=timeout_s)
        lines += out.splitlines()
    except subprocess.TimeoutExpired:
        child.kill()
        raise SystemExit(f"fleet run {tag!r} hung past {timeout_s}s")
    stats = {}
    for line in lines:
        if line.startswith("{") and '"metric"' in line:
            stats = json.loads(line)
    return {"rc": child.returncode, "stats": stats, "out_csv": out_csv,
            "killed_pid": killed_pid}


def check_warm(check: Check, tag: str, stats: dict) -> None:
    for wid, body in stats.get("workers_ready", {}).items():
        check.expect(body.get("compiles") == 0,
                     f"{tag}: worker {wid} compiled "
                     f"{body.get('compiles')} rungs (want 0 — AOT store "
                     f"cold?)")
        check.expect(body.get("deserialized", 0) >= 1,
                     f"{tag}: worker {wid} deserialized nothing")
        check.expect(bool(body.get("arena_warm")),
                     f"{tag}: worker {wid} arena store cold (ingest ran)")
    check.expect(stats.get("ready_s", 1e9) < 120.0,
                 f"{tag}: fleet took {stats.get('ready_s')}s to ready "
                 f"(want seconds, not minutes)")


def check_bit_identical(check: Check, tag: str, out_csv: str,
                        ref: np.ndarray, require_all: bool) -> int:
    import pandas as pd

    served = pd.read_csv(out_csv)["y_pred"].to_numpy(np.float32)
    check.expect(len(served) == len(ref),
                 f"{tag}: CSV rows {len(served)} != requests {len(ref)}")
    ok = np.asarray(served == ref[:len(served)])
    n_served = int(np.isfinite(served).sum())
    if require_all:
        check.expect(bool(np.isfinite(served).all()),
                     f"{tag}: {int((~np.isfinite(served)).sum())} "
                     f"request(s) lost their prediction")
        check.expect(bool(ok.all()),
                     f"{tag}: {int((~ok).sum())} prediction(s) not "
                     f"bit-identical to the single-engine reference")
    else:
        fin = np.isfinite(served)
        check.expect(bool(ok[fin].all()),
                     f"{tag}: {int((~ok[fin]).sum())} SERVED "
                     f"prediction(s) not bit-identical to the reference")
    return n_served


def run_graftscope(check: Check, tag: str, tele_dir: str,
                   expect_ok: int, perfetto: str = "") -> dict:
    """Run the trace collector CLI over a run's shared telemetry dir
    and exit-code-assert trace completeness: zero orphans, one root per
    trace, a full stage chain per successful Future (tools/graftscope).
    Returns the report dict for embedding in the bench JSON."""
    cmd = [sys.executable, "-m", "tools.graftscope",
           "--telemetry_dir", tele_dir, "--assert_complete",
           "--expect_ok", str(expect_ok), "--top_k", "3"]
    if perfetto:
        cmd += ["--perfetto", perfetto]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=300, cwd=_REPO,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    check.expect(proc.returncode == 0,
                 f"{tag}: graftscope exited {proc.returncode} — "
                 f"{proc.stderr[-1000:]}")
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        check.expect(False, f"{tag}: graftscope produced no report "
                            f"JSON (stderr: {proc.stderr[-500:]})")
        return {}


def check_queue_wait_consistency(check: Check, tag: str,
                                 tele_dir: str) -> dict:
    """ISSUE-13 satellite: the ``router.queue_wait`` gauge — THE
    autoscale signal — must be sane against the traces that measure
    the same interval independently. Every gauge value must be
    non-negative, and max(gauge) must dominate the max
    ``trace.router_queue`` span duration: the gauge is the OLDEST
    request's admission->dispatch wait per batch measured since its
    original arrival, while each span covers one request's wait for
    ONE queue residency — so no span can (beyond clock slop) exceed
    the biggest gauge. A violation means the signal the autoscaler
    trusts has drifted from what requests actually experienced."""
    from pertgnn_tpu.telemetry import load_events

    gauges: list[float] = []
    span_max = 0.0
    n_spans = 0
    for fname in os.listdir(tele_dir):
        if not fname.endswith(".jsonl"):
            continue
        for ev in load_events(os.path.join(tele_dir, fname)):
            if (ev["kind"] == "gauge"
                    and ev["name"] == "router.queue_wait"):
                gauges.append(float(ev["value"]))
            elif (ev["kind"] == "span"
                  and ev["name"] == "trace.router_queue"):
                span_max = max(span_max, float(ev["dur_ms"]))
                n_spans += 1
    check.expect(len(gauges) >= 1,
                 f"{tag}: no router.queue_wait gauges in the JSONL "
                 f"(the autoscale signal is dark)")
    check.expect(all(v >= 0.0 for v in gauges),
                 f"{tag}: negative router.queue_wait gauge "
                 f"(min {min(gauges, default=0.0):.3f}ms)")
    if n_spans:
        g_max = max(gauges, default=0.0)
        check.expect(g_max + 1.0 >= 0.95 * span_max,
                     f"{tag}: max router.queue_wait gauge {g_max:.1f}ms "
                     f"inconsistent with max trace.router_queue span "
                     f"{span_max:.1f}ms — the gauge under-reports the "
                     f"wait requests actually saw")
    return {"gauges": len(gauges),
            "gauge_max_ms": round(max(gauges, default=0.0), 3),
            "router_queue_spans": n_spans,
            "span_max_ms": round(span_max, 3)}


def counters_in(tele_dir: str) -> set:
    from pertgnn_tpu.telemetry import load_events

    names = set()
    if not os.path.isdir(tele_dir):
        return names
    for fname in os.listdir(tele_dir):
        if fname.endswith(".jsonl"):
            for ev in load_events(os.path.join(tele_dir, fname)):
                names.add(ev["name"])
    return names


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 mode: N=2, tiny stream, warm-start + "
                        "chaos only (no scaling phase)")
    p.add_argument("--dryrun", action="store_true",
                   help="alias for --smoke (the CI spelling, matching "
                        "stream_bench)")
    p.add_argument("--skip_scaling", action="store_true",
                   help="skip the N=1 vs N=4 scaling phase")
    p.add_argument("--skip_chaos", action="store_true",
                   help="skip the SIGKILL-a-worker scenario")
    p.add_argument("--requests", type=int, default=0,
                   help="scaling-stream length (0 = auto)")
    p.add_argument("--repeats", type=int, default=3,
                   help="alternating repeats per fleet size in the "
                        "scaling phase; throughput gates on the best "
                        "of each (shared hosts showed +-40%% run-to-run "
                        "spread — max-over-repeats estimates capacity "
                        "with interference noise mostly removed)")
    args = p.parse_args(argv)
    args.smoke = args.smoke or args.dryrun

    check = Check()
    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="fleet_bench_")
    ds, engine = build_reference(tmp)

    n_scale = args.requests or (400 if args.smoke else 3000)
    req_csv = os.path.join(tmp, "requests.csv")
    entries, tsb = request_stream(ds, n_scale, req_csv)
    ref = reference_preds(engine, entries, tsb)

    results: dict = {"tmp": tmp}
    cores = os.cpu_count() or 1

    if not args.smoke and not args.skip_scaling:
        # the acceptance gate (N=4 >= 2.5x N=1) presumes >= 4 usable
        # cores: one per worker, the CPU stand-in for one device per
        # worker. Below that, scaling four single-core workers onto
        # two cores measures scheduler thrash, not the fleet (measured
        # here: N=4 on 2 cores COLLAPSES to 0.2x while N=2 runs at
        # parity) — so the gate derates LOUDLY to an N=2 parity check:
        # the fleet layer (router + HTTP + requeue machinery) must not
        # materially tax throughput even when it cannot add capacity.
        if cores >= 4:
            n_hi, target, mode = 4, 2.5, "full"
        else:
            n_hi, target, mode = 2, 0.85, "derated"
            print(f"fleet_bench: NOTE only {cores} usable cores — the "
                  f"2.5x N=4 gate needs >= 4; derated to an N=2 "
                  f"parity gate (>= {0.85:g}x N=1)", file=sys.stderr)
        # ALTERNATING REPEATS, best-of: this workload's CPU hosts (CI
        # containers, shared VMs) showed +-40% run-to-run spread on
        # IDENTICAL commands; the max over repeats estimates each
        # fleet's capacity with the interference noise mostly removed
        # (the correctness gates — rc, warm start, bit-identical —
        # still apply to EVERY run, not just the best)
        runs1: list[dict] = []
        runs_hi: list[dict] = []
        for rep in range(args.repeats):
            runs1.append(run_fleet(tmp, f"n1_r{rep}", 1, req_csv))
            runs_hi.append(run_fleet(tmp, f"n{n_hi}_r{rep}", n_hi,
                                     req_csv))
        for runs, tag in ((runs1, "n1"), (runs_hi, f"n{n_hi}")):
            for rep, r in enumerate(runs):
                check.expect(r["rc"] == 0,
                             f"scaling: {tag} run #{rep} exited "
                             f"{r['rc']}")
                check_warm(check, f"{tag}_r{rep}", r["stats"])
                check_bit_identical(check, f"{tag}_r{rep}",
                                    r["out_csv"], ref,
                                    require_all=True)

        def tput(r):
            return r["stats"].get("throughput_rps", 0.0)

        r1 = max(runs1, key=tput)
        rhi = max(runs_hi, key=tput)
        tput1, tput_hi = tput(r1), tput(rhi)
        ratio = tput_hi / max(tput1, 1e-9)
        check.expect(ratio >= target,
                     f"scaling: N={n_hi} sustained only {ratio:.2f}x "
                     f"the N=1 throughput (target {target:g}x, {mode} "
                     f"gate on {cores} cores)")
        p99_1 = r1["stats"]["client_latency"].get("p99_ms", float("inf"))
        p99_hi = rhi["stats"]["client_latency"].get("p99_ms",
                                                    float("inf"))
        p99_bound = max(3.0 * p99_1, 250.0)
        check.expect(p99_hi <= p99_bound,
                     f"scaling: N={n_hi} p99 {p99_hi:.1f}ms not "
                     f"bounded (limit {p99_bound:.1f}ms = max(3 x N=1 "
                     f"p99, 250ms))")
        results["scaling"] = {
            "cores": cores, "gate": mode, "n_hi": n_hi,
            "target_x": target,
            "throughput_rps_n1": tput1,
            f"throughput_rps_n{n_hi}": tput_hi,
            "ratio": round(ratio, 3), "p99_ms_n1": p99_1,
            f"p99_ms_n{n_hi}": p99_hi, "p99_bound_ms": p99_bound,
            "ready_s_n1": r1["stats"].get("ready_s"),
            f"ready_s_n{n_hi}": rhi["stats"].get("ready_s"),
        }

    if not args.skip_chaos:
        # enough stream that the kill provably lands mid-traffic even
        # on a fast host (the smoke stream used to be 400, which a
        # 2-worker fleet can drain in under a second — the SIGKILL then
        # raced past the end and the chaos gates asserted against a
        # death nobody witnessed)
        n_chaos = 1000 if args.smoke else 2000
        chaos_csv = os.path.join(tmp, "requests_chaos.csv")
        c_entries, c_tsb = request_stream(ds, n_chaos, chaos_csv)
        c_ref = reference_preds(engine, c_entries, c_tsb)
        rc_ = run_fleet(tmp, "chaos", 2, chaos_csv,
                        kill_one_after_s=0.15, telemetry_level="trace",
                        extra_flags=["--trace_sample_rate", "1.0"])
        st = rc_["stats"]
        check.expect(rc_["rc"] == 0,
                     f"chaos: fleet run exited {rc_['rc']} after the "
                     f"SIGKILL (survivors must finish the stream)")
        check_warm(check, "chaos", st)
        check.expect(st.get("served") == n_chaos,
                     f"chaos: served {st.get('served')}/{n_chaos} — a "
                     f"SIGKILLed worker cost requests their Futures")
        router = st.get("router", {})
        check.expect(router.get("worker_lost", 0) >= 1,
                     "chaos: the router never noticed the dead worker")
        check.expect(router.get("members", 2) <= 1,
                     "chaos: membership still counts the dead worker")
        n_served = check_bit_identical(check, "chaos", rc_["out_csv"],
                                       c_ref, require_all=True)
        names = counters_in(os.path.join(tmp, "tele_chaos"))
        for counter in ("router.dispatch", "router.requeue",
                        "router.worker_lost", "router.members",
                        "router.queue_wait"):
            check.expect(counter in names,
                         f"telemetry: {counter} missing from the chaos "
                         f"run's JSONL")
        # graftscope over the chaos run's shared telemetry dir: every
        # successful Future (all of them — served == n_chaos is gated
        # above) must collect into EXACTLY one root with a complete
        # stage chain, zero orphans, ACROSS the worker kill — the
        # ISSUE-12 trace-completeness invariant, exit-code-asserted
        scope = run_graftscope(check, "chaos",
                               os.path.join(tmp, "tele_chaos"),
                               expect_ok=n_served,
                               perfetto=os.path.join(
                                   tmp, "chaos.perfetto.json"))
        # the autoscale-signal gauge vs the spans measuring the same
        # interval (ISSUE-13 satellite; details on the checker)
        qwait = check_queue_wait_consistency(
            check, "chaos", os.path.join(tmp, "tele_chaos"))
        results["chaos"] = {
            "queue_wait_consistency": qwait,
            "requests": n_chaos, "served": n_served,
            "killed_pid": rc_["killed_pid"],
            "worker_lost": router.get("worker_lost"),
            "requeues": router.get("requeues"),
            "ready_s": st.get("ready_s"),
            "trace_attribution": scope.get("stage_ms"),
            "trace_clock": scope.get("clock"),
            "traces_ok": scope.get("traces_ok"),
            "trace_orphans": scope.get("orphans"),
        }
    elif args.smoke:
        # smoke without chaos still needs one live fleet for the
        # warm-start + bit-identical gates
        r2 = run_fleet(tmp, "n2", 2, req_csv)
        check.expect(r2["rc"] == 0, f"smoke: N=2 run exited {r2['rc']}")
        check_warm(check, "n2", r2["stats"])
        check_bit_identical(check, "n2", r2["out_csv"], ref,
                            require_all=True)
        results["smoke_n2"] = {"ready_s": r2["stats"].get("ready_s")}

    print(json.dumps({
        "metric": "fleet_invariants_ok",
        "value": int(not check.failures),
        "unit": "bool",
        "smoke": args.smoke,
        "results": results,
        "violations": check.failures,
        "wall_s": round(time.perf_counter() - t0, 1),
        "captured_unix_time": time.time(),
    }))
    return 1 if check.failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
