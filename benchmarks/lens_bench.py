"""Lens benchmark: distributional, explainable what-if serving, gated.

ONE run exit-code-asserts every ISSUE-15 acceptance criterion
(pertgnn_tpu/lens/, docs/GUIDE.md §13); CI runs --dryrun on every push:

1. **Calibration** — a multi-quantile engine (quantile_taus, trained
   with one pinball term per tau) serves the test split through the
   REAL queue front door; each column's empirical coverage (fraction of
   labels at or under the predicted quantile) must land within the
   pre-registered COVERAGE_BUDGET of its tau. The budget is registered
   HERE, before any capture — the gate is only honest if the threshold
   cannot chase a measured regression. NOTE the calibration workload
   re-splits the corpus by ROW (deterministic permutation) instead of
   the reference's positional split: positional order groups traces by
   entry, so the positional test split holds entries the model never
   trained — an entry-extrapolation question no quantile head can
   answer, not a calibration measurement. The held-out rows stay
   excluded from training; only the grouping changes.
2. **Monotonicity** — every SERVED quantile vector is non-decreasing
   along the tau axis, zero violations. The non-crossing head makes
   this true by construction; the bench proves the property survived
   packing, dispatch, and result plumbing.
3. **Attribution pad-freedom** — top-k root-cause attribution rows
   never name a padded node: every named node indexes a real node of
   its request's mixture, every local value is finite (the pad pin is
   -inf, IN-GRAPH — graftaudit proves it statically on the traced
   program; this is the dynamic witness), and rows come back in
   descending order.
4. **Counterfactual zero-compile** — what-if edits (drop/substitute)
   re-pack through the existing bucket ladder: the engine's compile and
   cache-miss counters are UNCHANGED after serving a stream of edited
   requests (rungs key on shape; edits never grow the graph).
5. **Default-config bit-identity** — with quantile_taus=(0.5,) and no
   lens fields, predictions through BOTH front doors (the single-process
   MicrobatchQueue and a FleetRouter over worker HTTP transports) are
   bit-identical to a direct engine reference — the lens subsystem is
   provably dormant for pre-lens traffic.

Run off-TPU it auto-falls back to CPU like the sibling benches (the
lens machinery is backend-agnostic).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# Pre-registered calibration budget: max |empirical coverage - tau| per
# quantile column on the held-out test split. Registered before any
# capture; lens_bench turns red when a head drifts past it.
COVERAGE_BUDGET = 0.15
# The quantile levels the calibrated workload trains and serves.
LENS_TAUS = (0.5, 0.9)


def build_corpus(traces_per_entry: int, seed: int = 42):
    from pertgnn_tpu.ingest import synthetic

    return synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=60, num_entries=12, patterns_per_entry=3,
        pattern_size_range=(3, 24), traces_per_entry=traces_per_entry,
        seed=seed))


def lens_config(epochs: int):
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    LensConfig, ModelConfig, ServeConfig,
                                    TrainConfig)

    return Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=64),
        # local_loss_weight > 0: attribution from an untrained local
        # head is noise (GUIDE §13) — the lens workload trains it
        model=ModelConfig(hidden_channels=32, num_layers=2,
                          quantile_taus=LENS_TAUS,
                          local_loss_weight=0.1),
        # lr 1e-3: the calibration gate needs a CONVERGED head inside
        # the bench's wall-clock budget (measured: coverage within
        # ~0.02 of tau at 30 epochs on the dryrun corpus)
        train=TrainConfig(label_scale=1000.0, epochs=epochs, lr=1e-3),
        serve=ServeConfig(bucket_growth=2.0, max_graphs_per_batch=8),
        lens=LensConfig(lens_local=True),
        graph_type="pert",
    )


def default_config():
    """The PRE-LENS shape of the same workload: single tau, lens off —
    what criterion 5's bit-identity references."""
    import dataclasses

    from pertgnn_tpu.config import LensConfig, ModelConfig

    cfg = lens_config(epochs=1)
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, quantile_taus=(0.5,),
                                  local_loss_weight=0.0),
        lens=LensConfig())


def interleave_splits(ds, seed: int = 7) -> None:
    """Re-split the dataset's rows by a deterministic permutation
    (60/20/20), IN PLACE, before any lazy cache builds. The positional
    split groups traces by entry (reference parity), which makes the
    positional test split ENTRY-disjoint from training — unanswerable
    for calibration (untrained entry embeddings). A row-level holdout
    is the standard calibration setting: held-out rows never train,
    but their entries do."""
    import numpy as np

    from pertgnn_tpu.batching.dataset import Split

    names = ("train", "valid", "test")
    ent = np.concatenate([ds.splits[s].entry_ids for s in names])
    tsb = np.concatenate([ds.splits[s].ts_buckets for s in names])
    ys = np.concatenate([ds.splits[s].ys for s in names])
    n = len(ys)
    perm = np.random.default_rng(seed).permutation(n)
    b1, b2 = int(0.6 * n), int(0.8 * n)
    parts = {"train": perm[:b1], "valid": perm[b1:b2],
             "test": perm[b2:]}
    ds.splits = {k: Split(ent[i], tsb[i], ys[i])
                 for k, i in parts.items()}


def gate_calibration(ys, vectors) -> dict:
    from pertgnn_tpu.lens.calibrate import (calibration_errors,
                                            monotone_violations)

    crossings = monotone_violations(vectors)
    if crossings:
        raise AssertionError(
            f"{crossings}/{len(vectors)} served quantile vectors are "
            f"non-monotone — the non-crossing guarantee broke in "
            f"serving")
    errs = calibration_errors(ys, vectors, LENS_TAUS)
    fields = {
        "coverage": [float(c) for c in
                     (np.asarray(vectors) >= np.asarray(ys)[:, None])
                     .mean(axis=0)],
        "calibration_errors": [float(e) for e in errs],
        "coverage_budget": COVERAGE_BUDGET,
        "monotone_violations": crossings,
    }
    worst = float(errs.max())
    if worst > COVERAGE_BUDGET:
        raise AssertionError(
            f"calibration error {worst:.3f} exceeds the pre-registered "
            f"budget {COVERAGE_BUDGET} (coverage {fields['coverage']} "
            f"vs taus {LENS_TAUS})")
    return fields


def gate_attribution(ds, engine, queue, rows_n: int) -> dict:
    """Criterion 3: serve attribution requests through the queue and
    verify no row can name padding — plus an engine-internal check that
    the pad lanes of the local output really are pinned to -inf."""
    from pertgnn_tpu.lens.request import LensRequest, LensResult

    s = ds.splits["test"]
    futs, eids = [], []
    for i in range(min(rows_n, len(s.entry_ids))):
        eid, tsb = int(s.entry_ids[i]), int(s.ts_buckets[i])
        futs.append(queue.submit(eid, tsb,
                                 lens=LensRequest(attribute_k=3)))
        eids.append(eid)
    checked = 0
    for eid, f in zip(eids, futs):
        res = f.result(120)
        assert isinstance(res, LensResult), res
        mix = ds.mixtures[eid]
        assert res.attribution, "attribution came back empty"
        assert len(res.attribution) <= min(3, mix.num_nodes)
        locals_ = [r["local"] for r in res.attribution]
        assert locals_ == sorted(locals_, reverse=True), \
            "attribution rows not in descending order"
        for r in res.attribution:
            # THE pad-freedom assertion: a padded row cannot be named —
            # every named node is a real node of this request's mixture
            # and carries a finite local prediction (-inf is the pin)
            assert 0 <= r["node"] < mix.num_nodes, r
            assert np.isfinite(r["local"]), r
            assert r["ms_id"] == int(mix.ms_id[r["node"]]), r
            checked += 1
    return {"attribution_requests": len(futs),
            "attribution_rows_checked": checked}


def gate_pin_witness(ds, engine) -> None:
    """Engine-internal witness, run AFTER the queue closed (direct
    engine calls must not race its worker): one local-variant dispatch;
    the local vector holds -inf on EVERY pad lane and finite values on
    every real lane — the dynamic twin of graftaudit's static pin
    proof."""
    s = ds.splits["test"]
    packed = engine.pack_microbatch([int(s.entry_ids[0])],
                                    [int(s.ts_buckets[0])],
                                    want_local=True)
    engine.complete_microbatch(engine.dispatch_packed(packed))
    nm = np.asarray(packed.batch.node_mask)
    assert np.isfinite(packed.local[nm]).all()
    assert np.isneginf(packed.local[~nm]).all(), \
        "pad lanes of the local output are not pinned to -inf"


def gate_whatif(ds, engine, queue, rows_n: int) -> dict:
    """Criterion 4: a stream of counterfactually edited requests incurs
    ZERO fresh compiles and zero cache misses — plus the refusal path
    stays typed."""
    from pertgnn_tpu.lens.request import LensRequest
    from pertgnn_tpu.serve.errors import WhatIfRefused

    s = ds.splits["test"]
    compiles0, misses0 = engine.compiles, engine.cache_misses
    futs = []
    changed = 0
    base_preds = {}
    for i in range(min(rows_n, len(s.entry_ids))):
        eid, tsb = int(s.entry_ids[i]), int(s.ts_buckets[i])
        mix = ds.mixtures[eid]
        if mix.num_edges == 0:
            continue
        if eid not in base_preds:
            base_preds[eid] = queue.submit(eid, tsb).result(120)
        edits = ({"op": "drop_edge", "edge": i % mix.num_edges},)
        futs.append((eid, queue.submit(eid, tsb,
                                       lens=LensRequest(edits=edits))))
    for eid, f in futs:
        pred = f.result(120)
        if not np.array_equal(np.asarray(pred),
                              np.asarray(base_preds[eid])):
            changed += 1
    if engine.compiles != compiles0 or engine.cache_misses != misses0:
        raise AssertionError(
            f"counterfactual serving compiled: compiles "
            f"{compiles0}->{engine.compiles}, misses "
            f"{misses0}->{engine.cache_misses} — the zero-fresh-compile "
            f"construction broke")
    # the refusal cases stay typed and never occupy a slot
    eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
    try:
        queue.submit(eid, tsb, lens=LensRequest(
            edits=({"op": "drop_edge", "edge": 10 ** 9},)))
        raise AssertionError("out-of-range edit was not refused")
    except WhatIfRefused:
        pass
    return {"whatif_requests": len(futs),
            "whatif_changed_predictions": changed,
            "whatif_compiles": engine.compiles - compiles0}


def gate_default_bit_identity(corpus, rows_n: int) -> dict:
    """Criterion 5: the pre-lens config serves bit-identically to a
    direct engine reference through BOTH front doors."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import FleetConfig
    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.fleet.transport import WorkerServer
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.serve.buckets import make_bucket_ladder
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.serve.queue import MicrobatchQueue
    from pertgnn_tpu.train.loop import restore_target_state

    cfg = default_config()
    pre = preprocess(corpus.spans, corpus.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    _model, state = restore_target_state(ds, cfg)
    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    s = ds.splits["test"]
    n = min(rows_n, len(s.entry_ids))
    ent = np.asarray(s.entry_ids[:n])
    tsb = np.asarray(s.ts_buckets[:n])
    # the reference: one direct single-request dispatch per row (the
    # padding-invariant engine answer, independent of coalescing)
    ref = np.asarray([float(engine.predict_microbatch(ent[i:i + 1],
                                                      tsb[i:i + 1])[0])
                      for i in range(n)], np.float32)
    queue = MicrobatchQueue(engine)
    server = None
    try:
        futs = [queue.submit(int(e), int(t))
                for e, t in zip(ent, tsb)]
        got_queue = np.asarray([float(f.result(120)) for f in futs],
                               np.float32)
        if not np.array_equal(got_queue, ref):
            raise AssertionError(
                "queue front door diverged from the engine reference "
                "under the default config")
        server = WorkerServer(engine, queue)
        top = make_bucket_ladder(ds.budget, cfg.serve)[-1]

        def size(eid):
            m = ds.mixtures[int(eid)]
            return m.num_nodes, m.num_edges

        with FleetRouter(
                {"w1": f"http://127.0.0.1:{server.port}"}, size,
                (top.max_graphs, top.max_nodes, top.max_edges),
                cfg=FleetConfig(health_poll_interval_s=0.2)) as router:
            futs = [router.submit(int(e), int(t))
                    for e, t in zip(ent, tsb)]
            got_fleet = np.asarray([float(f.result(120)) for f in futs],
                                   np.float32)
        if not np.array_equal(got_fleet, ref):
            raise AssertionError(
                "fleet front door diverged from the engine reference "
                "under the default config")
    finally:
        queue.close()
        if server is not None:
            server.close()
    return {"default_rows": int(n), "default_bit_identical": True}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dryrun", action="store_true",
                    help="CI scale: small corpus, short fine-tune")
    ap.add_argument("--traces_per_entry", type=int, default=0,
                    help="0 = per-mode default")
    ap.add_argument("--epochs", type=int, default=0,
                    help="0 = per-mode default")
    ap.add_argument("--out", default="",
                    help="also write the JSON record here")
    args = ap.parse_args()

    from pertgnn_tpu.cli.common import (apply_platform_env,
                                        probe_backend_or_fallback)
    fallback = probe_backend_or_fallback()
    apply_platform_env()

    import jax

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.serve.queue import MicrobatchQueue
    from pertgnn_tpu.train.loop import fit

    traces = args.traces_per_entry or (60 if args.dryrun else 300)
    epochs = args.epochs or (30 if args.dryrun else 40)
    rows_n = 48 if args.dryrun else 200

    t0 = time.perf_counter()
    corpus = build_corpus(traces)
    cfg = lens_config(epochs)
    pre = preprocess(corpus.spans, corpus.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    interleave_splits(ds)
    state, history = fit(ds, cfg)
    train_s = time.perf_counter() - t0

    engine = InferenceEngine.from_dataset(
        ds, cfg, state,
        lens_names=(pre.ms_vocab, pre.interface_vocab)).warmup()
    record = {
        "metric": "pert_lens_gates",
        "value": 1.0,
        "unit": "pass",
        "taus": list(LENS_TAUS),
        "train_s": train_s,
        "train_qloss": history[-1]["train_qloss"],
        "dryrun": bool(args.dryrun),
    }
    with MicrobatchQueue(engine) as queue:
        # 1+2: serve the labeled test split through the queue door
        s = ds.splits["test"]
        n = min(len(s.entry_ids), 400 if args.dryrun else 2000)
        futs = [queue.submit(int(e), int(t))
                for e, t in zip(s.entry_ids[:n], s.ts_buckets[:n])]
        vectors = np.stack([np.asarray(f.result(300)) for f in futs])
        record.update(gate_calibration(
            np.asarray(s.ys[:n], np.float32), vectors))
        record["served_vectors"] = int(len(vectors))
        # 3: attribution pad-freedom
        record.update(gate_attribution(ds, engine, queue, rows_n))
        # 4: counterfactual zero-compile
        record.update(gate_whatif(ds, engine, queue, rows_n))
        if engine.cache_misses:
            raise AssertionError(
                f"{engine.cache_misses} executable-cache misses after "
                "warmup across the lens request stream")
    # 3b: the -inf pad pin, witnessed on the engine directly (queue
    # closed — direct calls must not race its worker)
    gate_pin_witness(ds, engine)
    # 5: the pre-lens default stays bit-identical through both doors
    record.update(gate_default_bit_identity(corpus, rows_n))

    record["backend"] = jax.default_backend()
    record["backend_fallback"] = fallback
    record["total_s"] = time.perf_counter() - t0
    record["captured_unix_time"] = time.time()
    out = json.dumps(record)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
