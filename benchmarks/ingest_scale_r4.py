"""Real-scale ingest proof (VERDICT r4 #6).

The reference ran against the 200 GB+ Alibaba trace
(/root/reference/README.md:4, shard globs preprocess.py:205, 228); this
repo's ingest had only ever seen in-memory synthetic frames (~100k
traces). This harness builds a MULTI-GB on-disk CSV tree in the raw
layout and runs the real CLI (`pertgnn_tpu.cli.preprocess_main`) over it
in a child process while sampling its peak RSS (VmHWM), so the
"per-shard bounded read" claim is a measurement, not an assertion.

Tree construction: one synthetic corpus is generated once, then TILED —
each tile remaps trace ids and shifts all timestamps by the corpus time
span, so entries/patterns recur across tiles (the occurrence filter
keeps them), resource buckets exist for every shifted trace, labels
stay consistent, and no cross-tile duplicate rows arise. This scales
the byte count without the per-trace generation cost.

    python benchmarks/ingest_scale_r4.py --gb 2.5 [--keep-tree DIR]

Prints one JSON line: raw bytes, wall time, traces/s, peak RSS, and the
peak-RSS / raw-bytes ratio. Reduced-scale regression: tests/test_ingest
_scale.py.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd


def build_tree(out_dir: str, target_gb: float, seed: int = 11) -> dict:
    """Write a raw-layout CSV tree of ~target_gb by tiling one corpus."""
    from pertgnn_tpu.ingest import synthetic

    spec = synthetic.SyntheticSpec(
        num_microservices=120, num_entries=16, patterns_per_entry=4,
        traces_per_entry=1000, seed=seed)
    base = synthetic.generate(spec)
    cg = os.path.join(out_dir, "MSCallGraph")
    rs = os.path.join(out_dir, "MSResource")
    os.makedirs(cg, exist_ok=True)
    os.makedirs(rs, exist_ok=True)

    # one tile's byte cost, measured from tile 0
    span_cols = list(base.spans.columns)
    period = int(base.spans["timestamp"].max()) + spec.ts_bucket_ms

    def write_tile(i: int) -> int:
        spans = base.spans.copy()
        spans["traceid"] = f"T{i}_" + spans["traceid"].astype(str)
        spans["timestamp"] = spans["timestamp"] + i * period
        res = base.resources.copy()
        res["timestamp"] = res["timestamp"] + i * period
        sp = os.path.join(cg, f"MSCallGraph_{i}.csv")
        rp = os.path.join(rs, f"MSResource_{i}.csv")
        spans.loc[:, span_cols].to_csv(sp)
        res.to_csv(rp, index=False)
        return os.path.getsize(sp) + os.path.getsize(rp)

    tile_bytes = write_tile(0)
    tiles = max(1, int(target_gb * 2**30 / tile_bytes))
    total = tile_bytes
    for i in range(1, tiles):
        total += write_tile(i)
    return {"tiles": tiles, "raw_bytes": total,
            "traces": tiles * spec.num_entries * spec.traces_per_entry,
            "span_rows_per_tile": len(base.spans)}


def run_cli(data_dir: str, artifact_dir: str,
            stream: bool = False, workers: int = 1) -> dict:
    """Run the preprocess CLI in a child process, sampling VmHWM."""
    import threading

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-m", "pertgnn_tpu.cli.preprocess_main",
         "--data_dir", data_dir, "--artifact_dir", artifact_dir,
         "--min_traces_per_entry", "100"]
        + (["--stream_factorize"] if stream else [])
        + (["--ingest_workers", str(workers)] if workers > 1 else []),
        cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)

    # Drain the pipe in a thread: the CLI logs one line per shard, so at
    # hundreds of shards the merged pipe would fill (~64KB) and deadlock
    # the child exactly at the scale this harness exists to measure.
    chunks: list[str] = []
    drainer = threading.Thread(target=lambda: chunks.append(
        proc.stdout.read()), daemon=True, name="ingest-scale-drain")
    drainer.start()

    peak_kb = 0
    status = f"/proc/{proc.pid}/status"
    while proc.poll() is None:
        try:
            with open(status) as f:
                for line in f:
                    if line.startswith("VmHWM:"):
                        peak_kb = max(peak_kb, int(line.split()[1]))
                        break
        except OSError:
            pass
        time.sleep(0.5)
    wall = time.perf_counter() - t0
    drainer.join(timeout=30)
    out = "".join(chunks)
    return {"rc": proc.returncode, "wall_s": round(wall, 1),
            "peak_rss_bytes": peak_kb * 1024, "tail": out[-800:]}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gb", type=float, default=2.5)
    ap.add_argument("--keep-tree", default=None,
                    help="build/keep the tree here instead of a temp dir")
    ap.add_argument("--stream", action="store_true",
                    help="measure the --stream_factorize loader instead "
                         "of the exact path")
    ap.add_argument("--workers", type=int, default=1,
                    help="--ingest_workers for the child CLI (streaming "
                         "shard fan-out; requires --stream — the exact "
                         "loader is serial and would mislabel the row)")
    args = ap.parse_args()
    if args.workers > 1 and not args.stream:
        ap.error("--workers requires --stream (the exact loader is "
                 "serial; the row would mislabel ingest_workers)")
    root = args.keep_tree or tempfile.mkdtemp(prefix="ingest_scale_",
                                              dir="/tmp")
    data_dir = os.path.join(root, "data")
    art_dir = os.path.join(root, "processed")
    shutil.rmtree(art_dir, ignore_errors=True)
    try:
        t0 = time.perf_counter()
        manifest = os.path.join(data_dir, "tree_manifest.json")
        tree = None
        if args.keep_tree and os.path.isfile(manifest):
            # a kept tree is reused verbatim so exact/stream/workers
            # variants measure the SAME bytes without a ~15 min rebuild —
            # but only if it matches both the request and the disk: a
            # stale manifest would make every ratio in the row relate
            # counts to bytes the child never processed
            with open(manifest) as f:
                tree = json.load(f)
            on_disk = len([f for f in os.listdir(
                os.path.join(data_dir, "MSCallGraph")) if f.endswith(".csv")])
            want_gb_ok = abs(tree["raw_bytes"] / 2**30 - args.gb) \
                / max(args.gb, 1e-9) < 0.2
            if tree["tiles"] != on_disk or not want_gb_ok:
                print(f"kept-tree manifest mismatch (tiles {tree['tiles']} "
                      f"vs {on_disk} on disk, {tree['raw_bytes']/2**30:.2f} "
                      f"GB vs --gb {args.gb}); rebuilding", file=sys.stderr)
                shutil.rmtree(data_dir, ignore_errors=True)
                tree = None
        if tree is None:
            tree = build_tree(data_dir, args.gb)
            with open(manifest, "w") as f:
                json.dump(tree, f)
        build_s = time.perf_counter() - t0
        r = run_cli(data_dir, art_dir, stream=args.stream,
                    workers=args.workers)
        ok = r["rc"] == 0
        result = {
            "metric": ("ingest_scale_peak_rss_over_raw_stream"
                       if args.stream else
                       "ingest_scale_peak_rss_over_raw"),
            "value": (round(r["peak_rss_bytes"] / tree["raw_bytes"], 2)
                      if ok else None),
            "unit": "peak RSS / raw CSV bytes (lower is better)",
            "raw_gb": round(tree["raw_bytes"] / 2**30, 2),
            "tiles": tree["tiles"],
            "raw_traces": tree["traces"],
            "tree_build_s": round(build_s, 1),
            "preprocess_wall_s": r["wall_s"],
            "traces_per_s": (round(tree["traces"] / r["wall_s"], 1)
                             if ok else None),
            "peak_rss_gb": round(r["peak_rss_bytes"] / 2**30, 2),
            "ingest_workers": args.workers,
            "rc": r["rc"],
        }
        if not ok:
            result["tail"] = r["tail"]
        print(json.dumps(result))
        sys.exit(0 if ok else 1)
    finally:
        if not args.keep_tree:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
