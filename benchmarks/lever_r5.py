"""feature_all_stage_copies lever at the reference's FULL length.

Round 4 measured the lever (features on every PERT stage copy vs the
reference's live last-copy-only behavior) as a 1.40x train-fit win at
20 epochs. This re-measures at 100 epochs — the reference's default
(pert_gnn.py:26) — so the beats-the-reference claim carries the same
horizon as the parity tables. Ours-vs-ours: both arms are this
framework, only the featurization flag differs.

    python benchmarks/lever_r5.py [--seeds 8] [--epochs 100]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from pertgnn_tpu.cli.common import apply_platform_env

apply_platform_env()

from run import (_dataset, _mean_ci95, _ratio_ci95,  # noqa: E402
                 parity_protocol)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=100)
    args = ap.parse_args()

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import evaluate, fit, make_eval_step

    base, spec_kwargs = parity_protocol(args.epochs)
    base = base.replace(graph_type="pert")
    arms = {}
    raw = {}
    for name, all_copies in (("last_copy_reference", False),
                             ("all_copies_lever", True)):
        cfg = base.replace(model=dataclasses.replace(
            base.model, feature_all_stage_copies=all_copies))
        ds = _dataset(spec_kwargs, cfg)
        fits = []
        for seed in range(args.seeds):
            c = cfg.replace(train=dataclasses.replace(cfg.train, seed=seed))
            state, _ = fit(ds, c)
            model = make_model(c.model, ds.num_ms, ds.num_entries,
                               ds.num_interfaces, ds.num_rpctypes)
            m = evaluate(make_eval_step(model, c), state,
                         ds.batches("train"))
            fits.append(m["mae"])
        mean, ci = _mean_ci95(fits)
        raw[name] = fits  # statistics from RAW values; round only output
        arms[name] = {"trainfit_mean_mae": round(mean, 1),
                      "ci95": round(ci, 1),
                      "per_seed": [round(v, 1) for v in fits]}
    lo, hi = _ratio_ci95(raw["last_copy_reference"],
                         raw["all_copies_lever"])
    ratio = (float(np.mean(raw["last_copy_reference"]))
             / max(float(np.mean(raw["all_copies_lever"])), 1e-9))
    print(json.dumps({
        "metric": f"feature_all_stage_copies_lever_{args.epochs}ep",
        "value": round(ratio, 3),
        "unit": "reference-faithful MAE / lever MAE (>1 = lever wins)",
        "ratio_ci95": [round(lo, 3), round(hi, 3)],
        "epochs": args.epochs, "seeds": args.seeds, **arms,
    }))


if __name__ == "__main__":
    main()
