"""Wire benchmark: the graftwire data-plane A/B, exit-code-asserted.

Three phases, one verdict (the fleet_bench discipline: numbers in the
JSON, pass/fail in the return code). ISSUE-16 acceptance:

1. **Fleet A/B** — the SAME request stream through three REAL fleets
   (cli/fleet_main.py subprocesses, N=2, hedging armed against an
   injected straggler delay), one per ``--transport`` mode. Every run
   must exit 0, start WARM (``compiles == 0`` per worker — the data
   plane must not perturb the AOT story), and serve EVERY request
   BIT-IDENTICAL to the single-engine in-process reference — hedge
   winners included (``router.hedge_fired >= 1`` is gated so the
   first-answer-wins path is provably exercised on every wire). The
   byte accounting (``transport.bytes_out/bytes_in``) must land in the
   JSONL and every ``trace.transport`` span must be tagged with the
   wire it actually rode (``wire=json|binary|shm`` — a silent fallback
   fails the run).
2. **One worker, three wires** — ONE in-process WorkerServer built
   with ``transport="shm"`` serves a JSON router, a binary router, and
   an shm router IN TURN (capability, not configuration — the mixed
   fleet story), over a lens-enabled multi-quantile engine: quantile
   VECTORS, attribution rows, and what-if counterfactuals must come
   back STRUCT-BIT-IDENTICAL across all three wires, with ZERO fresh
   compiles after the first (burn-in) round — the codec must never
   perturb shapes.
3. **Null-worker latency** — the wire cost ISOLATED: a worker whose
   queue resolves instantly, so ``trace.transport`` span durations
   measure serialization + transport, not compute. Gates:
   ``binary p50 < json p50`` (the codec beats json.dumps/loads) and
   ``shm p99 < binary p99`` (the ring beats TCP where it hurts — the
   tail).

CPU by default. One JSON line on stdout.

    python benchmarks/wire_bench.py [--dryrun]

``--dryrun`` is the CI wiring: smaller streams, same gates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from concurrent.futures import Future

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from benchmarks.fleet_bench import (Check, build_reference,  # noqa: E402
                                    check_bit_identical, check_warm,
                                    common_flags, counters_in,
                                    reference_preds, request_stream,
                                    run_fleet)

MODES = ("json", "binary", "shm")


def straggler_plan() -> str:
    """A seeded DELAY fault on a fraction of worker dispatches — the
    hedging target (the tail_bench chaos, derated): with it armed and
    --hedge_quantile_ms under the delay, every fleet run provably
    exercises the hedge race ON ITS WIRE, and bit-identity then covers
    hedge winners too."""
    from pertgnn_tpu.testing.faults import FaultPlan, FaultSpec

    return FaultPlan([FaultSpec(site="serve.dispatch", kind="delay",
                                delay_s=0.3, p=0.1)],
                     seed=99).to_json()


def transport_spans(tele_dir: str, per_dispatch: bool = False,
                    after: float = 0.0) -> dict[str, list[float]]:
    """wire tag -> [dur_ms] over every ``trace.transport`` span in the
    run's telemetry dir — the per-hop wire evidence phase 1 audits and
    phase 3 measures. Every request row in a flight shares the flight's
    (tm0, dur) stamp, so with ``per_dispatch`` the rows collapse to ONE
    sample per wire round trip — percentiles then weight each dispatch
    equally instead of multiplying the worst batch by its row count.
    ``after`` drops spans whose monotonic tm0 predates it (same-process
    clock: the caller's warmup cut)."""
    from pertgnn_tpu.telemetry import load_events

    spans: dict[str, list[float]] = {}
    seen: set[tuple[str, float, float]] = set()
    if not os.path.isdir(tele_dir):
        return spans
    for fname in os.listdir(tele_dir):
        if not fname.endswith(".jsonl"):
            continue
        for ev in load_events(os.path.join(tele_dir, fname)):
            if ev["kind"] == "span" and ev["name"] == "trace.transport":
                wire = (ev.get("tags") or {}).get("wire", "<untagged>")
                dur = float(ev["dur_ms"])
                tm0 = float(ev.get("tm0", 0.0))
                if tm0 < after:
                    continue
                if per_dispatch:
                    key = (wire, tm0, dur)
                    if key in seen:
                        continue
                    seen.add(key)
                spans.setdefault(wire, []).append(dur)
    return spans


def pct(xs: list[float], q: float) -> float:
    return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")


# -- phase 1: fleet A/B ----------------------------------------------------


def phase_fleet(check: Check, tmp: str, args) -> dict:
    ds, engine = build_reference(tmp)
    n = 300 if args.dryrun else 1500
    req_csv = os.path.join(tmp, "requests.csv")
    entries, tsb = request_stream(ds, n, req_csv)
    ref = reference_preds(engine, entries, tsb)

    results: dict = {}
    os.environ["PERTGNN_FAULT_PLAN"] = straggler_plan()
    try:
        for mode in MODES:
            r = run_fleet(tmp, f"wire_{mode}", 2, req_csv,
                          telemetry_level="trace",
                          extra_flags=["--transport", mode,
                                       "--hedge_quantile_ms", "120",
                                       "--trace_sample_rate", "1.0"])
            st = r["stats"]
            check.expect(r["rc"] == 0,
                         f"fleet[{mode}]: run exited {r['rc']}")
            check_warm(check, f"fleet[{mode}]", st)
            check_bit_identical(check, f"fleet[{mode}]", r["out_csv"],
                                ref, require_all=True)
            router = st.get("router", {})
            check.expect(router.get("hedge_fired", 0) >= 1,
                         f"fleet[{mode}]: no hedge ever fired — the "
                         f"stragglers were injected; bit-identity did "
                         f"not cover hedge winners on this wire")
            tele = os.path.join(tmp, f"tele_wire_{mode}")
            names = counters_in(tele)
            for counter in ("transport.bytes_out", "transport.bytes_in"):
                check.expect(counter in names,
                             f"fleet[{mode}]: {counter} missing from "
                             f"the JSONL — the byte A/B is dark")
            spans = transport_spans(tele)
            check.expect(set(spans) == {mode},
                         f"fleet[{mode}]: trace.transport spans rode "
                         f"{sorted(spans)} (want exactly ['{mode}'] — "
                         f"a silent fallback or a missing wire tag)")
            results[mode] = {
                "served": st.get("served"),
                "throughput_rps": st.get("throughput_rps"),
                "hedge_fired": router.get("hedge_fired"),
                "hedge_won": router.get("hedge_won"),
                "transport_spans": sum(len(v) for v in spans.values()),
            }
    finally:
        os.environ.pop("PERTGNN_FAULT_PLAN", None)
    return results


# -- phase 2: one worker, three wires --------------------------------------


def build_lens_stack():
    """A small lens-enabled multi-quantile serving stack — the traffic
    shapes (vectors, attribution JSON, what-if edits) that stress every
    section of the codec. Deterministic seeded init (no training): the
    gate is cross-wire bit-identity, not model quality."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    LensConfig, ModelConfig, ServeConfig)
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import restore_target_state

    corpus = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=40, num_entries=8, patterns_per_entry=2,
        pattern_size_range=(3, 16), traces_per_entry=30, seed=7))
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(batch_size=32),
        model=ModelConfig(hidden_channels=24, num_layers=2,
                          quantile_taus=(0.5, 0.9),
                          local_loss_weight=0.1),
        serve=ServeConfig(bucket_growth=2.0, max_graphs_per_batch=8),
        lens=LensConfig(lens_local=True),
        graph_type="pert")
    pre = preprocess(corpus.spans, corpus.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    _model, state = restore_target_state(ds, cfg)
    engine = InferenceEngine.from_dataset(
        ds, cfg, state,
        lens_names=(pre.ms_vocab, pre.interface_vocab)).warmup()
    return ds, cfg, engine


def serve_round(ds, cfg, server_url: str, capacity, mode: str,
                rows) -> dict:
    """One router (transport=mode) against THE shared worker: plain
    multi-quantile, attribution, and what-if traffic; returns the raw
    results for cross-wire comparison."""
    from pertgnn_tpu.config import FleetConfig
    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.lens.request import LensRequest

    def size(eid):
        m = ds.mixtures[int(eid)]
        return m.num_nodes, m.num_edges

    out = {"plain": [], "attr": [], "whatif": []}
    with FleetRouter({"w1": server_url}, size, capacity,
                     cfg=FleetConfig(transport=mode,
                                     health_poll_interval_s=0.2)
                     ) as router:
        plain = [router.submit(int(e), int(t)) for e, t in rows]
        lens_f = [router.submit(int(e), int(t),
                                lens=LensRequest(attribute_k=3))
                  for e, t in rows]
        whatif = [router.submit(
            int(e), int(t),
            lens=LensRequest(edits=({"op": "drop_edge", "edge": 0},)))
            for e, t in rows if ds.mixtures[int(e)].num_edges > 0]
        out["plain"] = [np.asarray(f.result(300)) for f in plain]
        for f in lens_f:
            res = f.result(300)
            out["attr"].append((np.asarray(res.pred),
                                tuple(res.attribution)))
        out["whatif"] = [np.asarray(f.result(300)) for f in whatif]
    return out


def phase_inproc(check: Check, args) -> dict:
    from pertgnn_tpu.fleet.transport import WorkerServer
    from pertgnn_tpu.serve.buckets import make_bucket_ladder
    from pertgnn_tpu.serve.queue import MicrobatchQueue

    ds, cfg, engine = build_lens_stack()
    s = ds.splits["test"]
    n = min(12 if args.dryrun else 48, len(s.entry_ids))
    rows = list(zip(s.entry_ids[:n], s.ts_buckets[:n]))
    top = make_bucket_ladder(ds.budget, cfg.serve)[-1]
    capacity = (top.max_graphs, top.max_nodes, top.max_edges)

    queue = MicrobatchQueue(engine)
    # ONE worker, built shm-capable, serving all three wires in turn:
    # capability, not configuration
    server = WorkerServer(engine, queue, transport="shm")
    rounds: dict[str, dict] = {}
    try:
        url = f"http://127.0.0.1:{server.port}"
        rounds["json"] = serve_round(ds, cfg, url, capacity, "json",
                                     rows)
        # burn-in complete: the json round paid any lazy lens-variant
        # compiles; the other wires must add ZERO
        compiles0 = engine.compiles
        rounds["binary"] = serve_round(ds, cfg, url, capacity, "binary",
                                       rows)
        rounds["shm"] = serve_round(ds, cfg, url, capacity, "shm", rows)
        check.expect(engine.compiles == compiles0,
                     f"inproc: serving binary+shm compiled "
                     f"{engine.compiles - compiles0} fresh rung(s) — "
                     f"the wire perturbed shapes")
    finally:
        queue.close()
        server.close()

    base = rounds["json"]
    for mode in ("binary", "shm"):
        got = rounds[mode]
        bad = sum(not np.array_equal(a, b)
                  for a, b in zip(base["plain"], got["plain"]))
        check.expect(bad == 0,
                     f"inproc[{mode}]: {bad}/{len(rows)} quantile "
                     f"vector(s) differ from the json wire")
        bad = sum(not (np.array_equal(a[0], b[0]) and a[1] == b[1])
                  for a, b in zip(base["attr"], got["attr"]))
        check.expect(bad == 0,
                     f"inproc[{mode}]: {bad}/{len(rows)} attribution "
                     f"result(s) differ from the json wire")
        bad = sum(not np.array_equal(a, b)
                  for a, b in zip(base["whatif"], got["whatif"]))
        check.expect(bad == 0,
                     f"inproc[{mode}]: {bad} what-if prediction(s) "
                     f"differ from the json wire")
    return {"rows": n, "whatif_rows": len(base["whatif"]),
            "attr_rows": len(base["attr"])}


# -- phase 3: null-worker latency ------------------------------------------


class _NullEngine:
    """The minimum surface WorkerServer + probe_payload need, with
    instant answers — so trace.transport spans time the WIRE."""

    @property
    def bus(self):
        from pertgnn_tpu import telemetry
        return telemetry.get_bus()

    def health(self) -> dict:
        return {"healthy": True, "reason": None, "warmed": True,
                "executables": 0, "buckets": 0, "rebuilds": 0,
                "nan_outputs": 0}


class _NullQueue:
    """Resolves every submit instantly with a PRECOMPUTED f32-exact
    quantile vector — the traffic shape the codec was built for (raw
    IEEE-754 on the binary wire vs 17-significant-digit decimal strings
    on json), with zero per-call compute polluting the timing."""

    draining = False

    def __init__(self, width: int = 24):
        self._vecs = [[float(np.float32(0.1 + 0.07 * j + r))
                       for j in range(width)] for r in range(13)]

    def probe_dict(self) -> dict:
        return {"depth": 0, "inflight": 0, "errors": {}}

    def submit(self, eid, tsb, trace=None, slo=None, downgrade=False,
               lens=None) -> Future:
        fut: Future = Future()
        fut.set_result(self._vecs[int(eid) % 13])
        return fut


def latency_round(tmp: str, mode: str, rnd: int,
                  n: int) -> tuple[str, float]:
    """One mode's null-worker traffic round under a REAL trace-level
    bus (sample rate 1.0); returns the telemetry dir holding its
    trace.transport spans and the monotonic stamp measurement began
    at. One full warmup wave runs BEFORE that stamp (negotiation,
    ring attach, connection pool fill, first-call code paths), and the
    cyclic GC is parked for the measured waves — a collection pause
    with jax loaded is multi-ms and would land on whichever transport
    happened to be running."""
    import gc

    from pertgnn_tpu import telemetry
    from pertgnn_tpu.config import FleetConfig
    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.fleet.transport import WorkerServer

    tele = os.path.join(tmp, f"tele_null_{mode}_r{rnd}")
    telemetry.configure(tele, level="trace", trace_sample_rate=1.0,
                        jax_monitoring=False)
    server = WorkerServer(_NullEngine(), _NullQueue(), transport="shm")
    wave = 128
    try:
        with FleetRouter({"w1": f"http://127.0.0.1:{server.port}"},
                         lambda eid: (1, 1), (32, 1 << 20, 1 << 20),
                         cfg=FleetConfig(transport=mode,
                                         health_poll_interval_s=5.0)
                         ) as router:
            for f in [router.submit(i, i % 7) for i in range(wave)]:
                f.result(60)                  # warmup, excluded below
            gc.collect()
            gc.disable()
            t_meas = time.monotonic()
            try:
                for lo in range(0, n, wave):
                    futs = [router.submit(i, i % 7)
                            for i in range(lo, min(lo + wave, n))]
                    for f in futs:
                        f.result(60)
            finally:
                gc.enable()
    finally:
        server.close()
        telemetry.shutdown()
    return tele, t_meas


def phase_latency(check: Check, tmp: str, args) -> dict:
    n = 1600 if args.dryrun else 3200
    pooled: dict[str, list[float]] = {m: [] for m in MODES}
    # alternating rounds: host drift lands on every mode evenly
    for rnd in range(6):
        for mode in MODES:
            tele, t_meas = latency_round(tmp, mode, rnd, n)
            spans = transport_spans(tele, per_dispatch=True,
                                    after=t_meas)
            check.expect(set(spans) == {mode},
                         f"latency[{mode}] r{rnd}: spans rode "
                         f"{sorted(spans)} (want exactly ['{mode}'])")
            pooled[mode].extend(spans.get(mode, []))
    for mode in MODES:
        check.expect(len(pooled[mode]) >= n // 32,
                     f"latency[{mode}]: only {len(pooled[mode])} "
                     f"transport dispatches collected")
    stats = {m: {"spans": len(v), "p50_ms": round(pct(v, 50), 4),
                 "p99_ms": round(pct(v, 99), 4)}
             for m, v in pooled.items()}
    check.expect(stats["binary"]["p50_ms"] < stats["json"]["p50_ms"],
                 f"latency: binary p50 {stats['binary']['p50_ms']}ms "
                 f"not under json p50 {stats['json']['p50_ms']}ms — "
                 f"the codec lost to json.dumps/loads")
    check.expect(stats["shm"]["p99_ms"] < stats["binary"]["p99_ms"],
                 f"latency: shm p99 {stats['shm']['p99_ms']}ms not "
                 f"under binary p99 {stats['binary']['p99_ms']}ms — "
                 f"the ring lost to TCP at the tail")
    return stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dryrun", action="store_true",
                   help="CI mode: smaller streams, same gates")
    p.add_argument("--skip_fleet", action="store_true",
                   help="skip the subprocess fleet A/B phase")
    p.add_argument("--skip_inproc", action="store_true",
                   help="skip the one-worker-three-wires phase")
    p.add_argument("--skip_latency", action="store_true",
                   help="skip the null-worker latency phase")
    args = p.parse_args(argv)

    check = Check()
    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="wire_bench_")
    results: dict = {"tmp": tmp}

    if not args.skip_fleet:
        results["fleet"] = phase_fleet(check, tmp, args)
    if not args.skip_inproc:
        results["inproc"] = phase_inproc(check, args)
    if not args.skip_latency:
        results["latency"] = phase_latency(check, tmp, args)

    print(json.dumps({
        "metric": "wire_invariants_ok",
        "value": int(not check.failures),
        "unit": "bool",
        "dryrun": args.dryrun,
        "results": results,
        "violations": check.failures,
        "wall_s": round(time.perf_counter() - t0, 1),
        "captured_unix_time": time.time(),
    }))
    return 1 if check.failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
