"""Serving benchmark: bucketed AOT request path vs naive per-request jit.

Prints ONE JSON line in the BENCH_r0*.json schema family:

  {"metric": "pert_serve_request_latency_ms_p50", "value": ..., "unit":
   "ms", "p50_ms": ..., "p95_ms": ..., "p99_ms": ..., "throughput_rps":
   ..., "pad_waste_ratio": ..., "cache_misses_after_warmup": 0,
   "buckets_used": N, "speedup_vs_naive": ..., ...}

What is measured:
- **bucketed** — a randomized request stream (microbatches of 1..G
  requests over entries with heterogeneous mixture shapes, spanning >= 3
  shape buckets) dispatched through the warmed serving engine
  (serve/engine.py): per-dispatch client latency, throughput, pad waste,
  and the executable-cache counters. Steady-state serving must show ZERO
  cache misses — asserted, not just reported.
- **naive** — the SAME stream through the obvious alternative: a single
  `jax.jit` forward fed each microbatch packed at its EXACT shape. jit
  caches by shape, so every previously-unseen (graphs, nodes, edges)
  signature recompiles on the request path — the tail-latency failure
  mode the bucket ladder exists to remove.
- **speedup_vs_naive** = naive mean latency / bucketed mean latency over
  the identical stream (means, not medians: the naive path's damage IS
  its compile tail, and a median would hide exactly that).

Quantized serve tier (ISSUE 6): `--serve_dtype {f32,bf16,int8}` runs the
SAME stream through an engine whose rung executables bake in the
requested tier (bf16 activations; int8 adds in-graph-dequantized int8
weights, ops/quantize.py). Quality is exit-code-gated, never assumed:
the test-split quantile-loss delta vs an f32 reference engine must stay
inside the PRE-REGISTERED per-dtype threshold below — a quantization
scheme that moves the served quality metric beyond its budget turns the
bench red, it does not ship quietly. The JSON stamps `serve_dtype`,
`attention_impl`, and a roofline-attribution row (mfu/mbu per variant,
utils/flops.py; honestly null off-chip).

Run off-TPU it auto-falls back to CPU like bench.py (the engine is
backend-agnostic; bucket discipline matters on any backend with compiled
static shapes).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# Pre-registered quality budgets for the quantized serve tiers: max
# allowed RELATIVE test-split quantile-loss delta vs the f32 reference
# engine. Registered HERE, before any capture — the gate is only honest
# if the threshold cannot chase a measured regression. f32's budget is
# numerical-noise-only (same graph, same dtype, different dispatch path).
QLOSS_DELTA_BUDGET = {"f32": 1e-6, "bf16": 0.02, "int8": 0.05}


def build_serve_workload(traces_per_entry: int = 300,
                         serve_dtype: str = "f32"):
    """A synthetic corpus with deliberately heterogeneous mixture shapes
    (wide pattern_size_range) so single-request node/edge totals land in
    different ladder rungs."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, ServeConfig, TrainConfig)
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=64),
        model=ModelConfig(hidden_channels=32, num_layers=3),
        train=TrainConfig(label_scale=1000.0),
        serve=ServeConfig(bucket_growth=2.0, max_graphs_per_batch=8,
                          min_bucket_nodes=128, min_bucket_edges=128,
                          serve_dtype=serve_dtype),
        graph_type="pert",
    )
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=60, num_entries=12, patterns_per_entry=3,
        pattern_size_range=(3, 24), traces_per_entry=traces_per_entry,
        seed=42))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    return ds, cfg


def make_request_stream(ds, max_graphs: int, n_batches: int, seed: int = 0):
    """Randomized stream of microbatches: (entry_ids, ts_buckets) tuples
    with 1..max_graphs requests each, entries drawn across the whole
    test split so shapes vary."""
    s = ds.splits["test"]
    rng = np.random.default_rng(seed)
    stream = []
    for _ in range(n_batches):
        k = int(rng.integers(1, max_graphs + 1))
        idx = rng.integers(0, len(s.entry_ids), size=k)
        stream.append((s.entry_ids[idx], s.ts_buckets[idx]))
    return stream


def run_bucketed(engine, stream):
    """The engine path: per-dispatch latency + engine counters. Returns
    (latencies_s, preds per microbatch)."""
    lat, preds = [], []
    for entries, buckets in stream:
        t0 = time.perf_counter()
        p = engine.predict_microbatch(entries, buckets)
        lat.append(time.perf_counter() - t0)
        preds.append(p)
    return np.asarray(lat), preds


def run_naive(ds, cfg, state, stream):
    """The obvious alternative: one jit'd forward, each microbatch packed
    at its EXACT (graphs, nodes, edges) shape — every new signature
    recompiles inside the request's latency budget."""
    import jax

    from pertgnn_tpu.batching.pack import BatchBudget, pack_single
    from pertgnn_tpu.models.pert_model import make_model

    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    scale = cfg.train.label_scale

    @jax.jit
    def step(variables, batch):
        pred, _ = model.apply(variables, batch, training=False)
        return pred * scale

    lat, preds, shapes = [], [], set()
    for entries, buckets in stream:
        t0 = time.perf_counter()
        g = len(entries)
        n = sum(ds.mixtures[int(e)].num_nodes for e in entries)
        e_tot = sum(ds.mixtures[int(e)].num_edges for e in entries)
        shapes.add((g, n, e_tot))
        batch = pack_single(
            ds.mixtures, entries, buckets,
            BatchBudget(max_graphs=g, max_nodes=n, max_edges=e_tot),
            ds.lookup, node_depth_in_x=cfg.model.use_node_depth)
        p = np.asarray(step(variables, batch))[:g]
        lat.append(time.perf_counter() - t0)
        preds.append(p)
    return np.asarray(lat), preds, len(shapes)


def quality_gate(ds, cfg, state, engine):
    """The quantized tier's exit-code oracle: test-split quantile loss of
    the dtype engine vs an f32 reference over the SAME rows. For bf16/int8
    the reference is a fresh f32 engine through the real per-rung AOT
    request path (isolates the dtype); for f32 the reference is the
    OFFLINE forward (same dtype, different dispatch path — comparing the
    engine to itself would make the gate vacuous). Returns the JSON
    fields; raises AssertionError when the relative worsening exceeds the
    pre-registered QLOSS_DELTA_BUDGET for this dtype.

    Multi-quantile heads (ModelConfig.quantile_taus, pertgnn_tpu/lens/)
    are gated PER TAU: each column's pinball loss at its own level vs
    the reference's same column, EVERY delta inside the budget — a
    quantization scheme that only degrades the tail columns cannot hide
    behind a healthy median."""
    import dataclasses

    import jax.numpy as jnp

    from pertgnn_tpu.config import resolve_quantile_taus
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.metrics import quantile_loss
    from pertgnn_tpu.train.predict import predict_split, predict_split_served

    dtype = cfg.serve.serve_dtype
    ys = np.asarray(ds.splits["test"].ys, np.float32)
    pred_d = np.asarray(predict_split_served(ds, cfg, state, "test",
                                             engine=engine))
    if dtype == "f32":
        pred_f = predict_split(ds, cfg, state, "test")
    else:
        cfg_f = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                      serve_dtype="f32"))
        # no warmup(): only the rungs the test split actually hits get
        # compiled (lazily, on the miss path) — a full-ladder warmup of a
        # throwaway reference engine is pure added wall clock
        eng_f = InferenceEngine.from_dataset(ds, cfg_f, state)
        pred_f = predict_split_served(ds, cfg_f, state, "test",
                                      engine=eng_f)
    pred_f = np.asarray(pred_f)
    taus = resolve_quantile_taus(cfg.model, cfg.train.tau)
    if pred_d.ndim == 1:
        pred_d, pred_f = pred_d[:, None], pred_f[:, None]
    budget = QLOSS_DELTA_BUDGET[dtype]
    per_tau = []
    for i, tau in enumerate(taus):
        q_d = float(quantile_loss(jnp.asarray(ys),
                                  jnp.asarray(pred_d[:, i]), tau))
        q_f = float(quantile_loss(jnp.asarray(ys),
                                  jnp.asarray(pred_f[:, i]), tau))
        per_tau.append({"tau": float(tau), "qloss_f32": q_f,
                        "qloss_served": q_d,
                        "delta_rel": (q_d - q_f) / max(abs(q_f), 1e-12)})
    worst = max(per_tau, key=lambda r: r["delta_rel"])
    fields = {
        # the three legacy fields describe ONE measurement: the WORST
        # column (single-tau mode: the only column) — a consumer
        # recomputing the delta from the qloss pair must get
        # qloss_delta_rel back; per-column detail rides qloss_per_tau
        "qloss_f32": worst["qloss_f32"],
        "qloss_served": worst["qloss_served"],
        "qloss_delta_rel": worst["delta_rel"],
        "qloss_delta_budget": budget,
        "qloss_worst_tau": worst["tau"],
        "qloss_per_tau": per_tau,
        "qloss_rows": int(len(ys)),
    }
    if worst["delta_rel"] > budget:
        raise AssertionError(
            f"serve_dtype={dtype} quantile-loss delta "
            f"{worst['delta_rel']:.4%} at tau={worst['tau']:g} exceeds "
            f"the pre-registered budget {budget:.2%} "
            f"(f32 {worst['qloss_f32']:.6g} -> {dtype} "
            f"{worst['qloss_served']:.6g})")
    return fields


def rung_attribution(engine, stats, throughput_rps):
    """Roofline-attribution row for the most-dispatched rung: FLOPs and
    bytes per graph from the rung executable's own XLA cost analysis
    (utils/flops.executable_cost), utilization against chip peaks
    (honestly null off-TPU)."""
    from pertgnn_tpu.config import resolve_attention_impl
    from pertgnn_tpu.utils import flops as flops_util

    impl = resolve_attention_impl(engine._cfg.model)
    hot = max(range(len(engine.ladder)),
              key=lambda i: stats["buckets"][i]["dispatches"])
    f = b = None
    exe = engine._exe.get((hot, False))
    if exe is not None:
        per_dispatch = flops_util.executable_cost(exe)
        g = engine.ladder[hot].max_graphs
        f = per_dispatch[0] / g if per_dispatch[0] else None
        b = per_dispatch[1] / g if per_dispatch[1] else None
    return flops_util.variant_attribution(
        attention_impl=impl, dtype=engine.serve_dtype,
        graphs_per_s=throughput_rps, flops_per_graph=f,
        bytes_per_graph=b)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batches", type=int,
                    default=int(os.environ.get("SERVE_BENCH_BATCHES",
                                               "120")),
                    help="microbatches in the randomized request stream")
    ap.add_argument("--traces_per_entry", type=int, default=300)
    ap.add_argument("--serve_dtype", choices=("f32", "bf16", "int8"),
                    default=os.environ.get("SERVE_BENCH_DTYPE", "f32"),
                    help="quantized serve tier under test; quality is "
                         "exit-code-gated vs an f32 reference engine")
    ap.add_argument("--out", default="",
                    help="also write the JSON record here")
    args = ap.parse_args()

    from pertgnn_tpu.cli.common import (apply_platform_env,
                                        probe_backend_or_fallback)
    fallback = probe_backend_or_fallback()
    apply_platform_env()

    import jax

    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import restore_target_state

    ds, cfg = build_serve_workload(args.traces_per_entry,
                                   serve_dtype=args.serve_dtype)
    # serving perf is independent of the weights; a fresh init (the
    # checkpoint restore target) keeps the bench self-contained
    _model, state = restore_target_state(ds, cfg)

    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    stream = make_request_stream(ds, cfg.serve.max_graphs_per_batch,
                                 args.batches)

    lat_b, preds_b = run_bucketed(engine, stream)
    stats = engine.stats_dict()
    used = [b for b in stats["buckets"] if b["dispatches"]]
    if stats["cache_misses"]:
        raise AssertionError(
            f"{stats['cache_misses']} executable-cache misses after "
            "warmup — the ladder no longer covers the request stream")
    if len(used) < 3:
        raise AssertionError(
            f"request stream only exercised {len(used)} shape buckets "
            "(need >= 3 for a meaningful bucketing claim) — widen "
            "pattern_size_range or the microbatch size range")

    lat_n, preds_n, naive_shapes = run_naive(ds, cfg, state, stream)
    if args.serve_dtype == "f32":
        # bit-level stream parity holds only dtype-to-dtype: the naive
        # oracle is f32, quantized tiers are instead gated on the
        # quantile-loss delta below
        for pb, pn in zip(preds_b, preds_n):
            np.testing.assert_allclose(pb, pn, rtol=1e-4, atol=1e-5)

    quality = quality_gate(ds, cfg, state, engine)

    n_requests = sum(len(e) for e, _ in stream)
    speedup = float(lat_n.mean() / lat_b.mean())
    roofline = rung_attribution(engine, stats,
                                float(n_requests / lat_b.sum()))
    # the same row lands on the telemetry bus as serve.roofline.* gauges
    # (tagged impl/dtype) when --telemetry_dir is configured, so capture
    # JSONLs carry per-variant utilization next to the serve counters
    from pertgnn_tpu import telemetry
    from pertgnn_tpu.utils.flops import publish_attribution

    publish_attribution(telemetry.get_bus(), roofline,
                        prefix="serve.roofline")
    record = {
        "metric": "pert_serve_request_latency_ms_p50",
        "value": float(np.percentile(lat_b * 1e3, 50)),
        "unit": "ms",
        "p50_ms": float(np.percentile(lat_b * 1e3, 50)),
        "p95_ms": float(np.percentile(lat_b * 1e3, 95)),
        "p99_ms": float(np.percentile(lat_b * 1e3, 99)),
        "mean_ms": float(lat_b.mean() * 1e3),
        "throughput_rps": float(n_requests / lat_b.sum()),
        "pad_waste_ratio": stats["pad_waste_ratio"],
        # per-stage request-lifecycle breakdown (engine stage recorders:
        # pack / dispatch / compute; queue stays empty — this bench
        # drives the engine directly). Each value is the shared latency
        # summary schema (utils/profiling.SUMMARY_KEYS).
        "stages": {s: d for s, d in stats["stages"].items() if d["count"]},
        "cache_misses_after_warmup": stats["cache_misses"],
        "cache_hits": stats["cache_hits"],
        "warmup_s": stats["warmup_s"],
        "buckets_total": len(engine.ladder),
        "buckets_used": len(used),
        "microbatches": len(stream),
        "requests": n_requests,
        "naive_p50_ms": float(np.percentile(lat_n * 1e3, 50)),
        "naive_p99_ms": float(np.percentile(lat_n * 1e3, 99)),
        "naive_mean_ms": float(lat_n.mean() * 1e3),
        "naive_distinct_shapes": naive_shapes,
        "speedup_vs_naive": speedup,
        # quantized-tier attribution + quality gate (ISSUE 6): which
        # kernel variant and serve dtype produced these latencies, their
        # roofline utilization, and the exit-code-gated quantile-loss
        # delta vs the f32 reference engine
        "serve_dtype": args.serve_dtype,
        "attention_impl": roofline["attention_impl"],
        "roofline": roofline,
        **quality,
        "backend": jax.default_backend(),
        "backend_fallback": fallback,
        "captured_unix_time": time.time(),
    }
    out = json.dumps(record)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if speedup < 5.0:
        print(f"WARNING: bucketed speedup vs naive is {speedup:.1f}x "
              "(< 5x acceptance threshold)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
