"""Non-circular L0-L2 parity: run the ACTUAL reference preprocessing.

Every parity number in this repo so far was measured against the builder's
own re-implementations (VERDICT r3 "What's missing" #1: self-certified
spec). This harness breaks that circularity for the data pipeline: it
writes synthetic raw CSVs in the reference's expected on-disk layout,
runs `/root/reference/preprocess.py` VERBATIM as a subprocess (pandas +
torch + joblib only — no torch_geometric needed for L0-L2), and compares
its saved artifacts field-by-field against this repo's
preprocess/assemble/build_runtime_graphs on the same bytes.

torch_geometric itself (the L4 model) cannot be installed here — zero
egress; see pyg_install_attempt.log in this directory — so the MODEL
remains pinned by the dense-numpy + torch-scatter oracles; the DATA
pipeline (entry detection, 5-stage sanitizer, span/PERT construction,
runtime identity, mixture weights, labels) is now pinned by the
reference's own code.

Alignment strategy
------------------
The reference factorizes ids by first appearance over its row order
(preprocess.py:216-221), so the harness reads the CSVs EXACTLY as
`get_df` does (concat in os.listdir order with index_col=0,
replace(nan, "nan"), drop_duplicates, sort_values("timestamp") —
preprocess.py:203-213) and feeds that frame to this repo's
`preprocess()` (whose stable re-sort of the pre-sorted frame is a
no-op). Row order — and therefore every factorize code except the
microservice ids — is then identical on both sides, and the ms
relabeling (the reference builds ms2int from an unordered Python set,
preprocess.py:248-251; we sort ours) is recovered from the aligned
um/dm columns and verified to be a bijection.

Graphs are compared on canonical node labels — (ms, stage-occurrence)
tuples — because node NUMBERING depends on the ms labeling (span:
torch.unique over ms ints, misc.py:196; pert: value_counts order,
misc.py:240), which legitimately differs between the two sides.

Run:  python benchmarks/parity/reference_crosscheck.py [--traces 120]
Exit status 0 iff every check passes; JSON verdict on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pandas as pd

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
REFERENCE = os.environ.get("PERTGNN_REFERENCE_DIR", "/root/reference")
sys.path.insert(0, REPO)


def make_sandbox(root: str, traces_per_entry: int, seed: int = 7) -> dict:
    """Write synthetic raw CSVs in the reference's ./data layout."""
    from pertgnn_tpu.ingest import synthetic

    spec = synthetic.SyntheticSpec(
        num_microservices=30, num_entries=3, patterns_per_entry=3,
        traces_per_entry=traces_per_entry, seed=seed,
        # keep the coverage filter exercised but below the 0.6 threshold
        # for only a handful of traces
        missing_resource_frac=0.15)
    data = synthetic.generate(spec)
    synthetic.write_csvs(data, os.path.join(root, "data"), shards=3)
    os.makedirs(os.path.join(root, "processed"), exist_ok=True)
    return {"spec": spec}


def fingerprint_corpus(root: str) -> str:
    """sha1 over the sandbox's raw CSV bytes (sorted relative paths) —
    proof in the verdict that a given --seed produced a distinct corpus,
    so a dropped seed pass-through can't silently collapse the sweep
    back to one golden input."""
    import hashlib

    h = hashlib.sha1()
    data_root = os.path.join(root, "data")
    for dirpath, _dirs, files in sorted(os.walk(data_root)):
        for name in sorted(files):
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, data_root).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def run_reference(root: str) -> subprocess.CompletedProcess:
    """Run the reference's preprocess.py untouched, in the sandbox cwd.

    The reference predates pandas 3: (a) CSV strings now come back as
    arrow-backed `str` columns that refuse int assignment
    (preprocess.py:92 raises TypeError), and (b) legacy whole-column
    `.loc` assignment used to coerce object columns to int64, which the
    downstream torch.tensor(...) calls depend on (misc.py:178). The shim
    restores exactly those two legacy dtype semantics — object strings
    at read time, int64 after factorization (via .infer_objects()) —
    and then runs the reference's own functions untouched."""
    ref_path = os.path.join(REFERENCE, "preprocess.py")
    shim = os.path.join(root, "_run_reference_shim.py")
    with open(shim, "w") as f:
        f.write(f"""\
import pandas as pd
pd.set_option('future.infer_string', False)
src = open({ref_path!r}).read()
ns = {{'__name__': 'reference_preprocess', '__file__': {ref_path!r}}}
exec(compile(src, {ref_path!r}, 'exec'), ns)
_orig = ns['map_consecutive_ids']
def _compat(df, cols):
    df, uniques = _orig(df, cols)
    return df.infer_objects(), uniques
ns['map_consecutive_ids'] = _compat
ns['main']()
""")
    env = dict(os.environ, PYTHONPATH=REFERENCE, PYTHONHASHSEED="0",
               JAX_PLATFORMS="")  # no jax in the reference; drop axon too
    return subprocess.run(
        [sys.executable, shim],
        cwd=root, env=env, capture_output=True, text=True, timeout=1800)


def read_like_reference(root: str) -> tuple[pd.DataFrame, pd.DataFrame]:
    """The reference's exact CSV read (preprocess.py:203-236), so both
    pipelines see the same rows in the same order. This is deliberate
    line-for-line BEHAVIORAL mirroring of ~8 lines of IO glue — the
    alignment shim the whole comparison rests on."""
    cg = os.path.join(root, "data", "MSCallGraph")
    df = pd.concat(
        (pd.read_csv(os.path.join(cg, f), index_col=0, engine="pyarrow")
         .replace(np.nan, "nan")
         for f in os.listdir(cg) if f.endswith(".csv")),
        ignore_index=True).drop_duplicates()
    df = df.sort_values(by=["timestamp"])
    rs = os.path.join(root, "data", "MSResource")
    resource_df = pd.concat(
        pd.read_csv(os.path.join(rs, f), engine="pyarrow")
        .loc[:, ["timestamp", "msname", "instance_cpu_usage",
                 "instance_memory_usage"]]
        for f in os.listdir(rs) if f.endswith(".csv"))
    return df, resource_df


class Check:
    def __init__(self):
        self.results: dict[str, bool] = {}
        self.notes: dict[str, str] = {}

    def ok(self, name: str, cond: bool, note: str = ""):
        self.results[name] = bool(cond)
        if note and not cond:
            self.notes[name] = note
        return cond

    @property
    def all_ok(self) -> bool:
        return all(self.results.values())


def ms_bijection(check: Check, mine: pd.DataFrame,
                 ref: pd.DataFrame) -> dict[int, int]:
    """Recover ref-ms-int -> my-ms-int from the aligned um/dm columns and
    verify it is a bijection."""
    pairs = np.concatenate([
        np.stack([ref["um"].to_numpy(np.int64), mine["um"].to_numpy(np.int64)], 1),
        np.stack([ref["dm"].to_numpy(np.int64), mine["dm"].to_numpy(np.int64)], 1)])
    uniq = np.unique(pairs, axis=0)
    fwd = dict(zip(uniq[:, 0].tolist(), uniq[:, 1].tolist()))
    check.ok("ms_map_is_function", len(fwd) == len(uniq),
             "one reference ms id maps to multiple of ours")
    check.ok("ms_map_is_injective",
             len(set(fwd.values())) == len(fwd),
             "two reference ms ids collapse to one of ours")
    return fwd


def canonical_nodes(ms_per_node: np.ndarray) -> list[tuple[int, int]]:
    """node index -> (ms, k-th occurrence of that ms) — a labeling
    invariant to how the builder numbered the nodes."""
    seen: dict[int, int] = {}
    out = []
    for ms in np.asarray(ms_per_node).ravel().tolist():
        k = seen.get(ms, 0)
        seen[ms] = k + 1
        out.append((int(ms), k))
    return out


def edge_multiset(senders, receivers, attrs, nodes: list[tuple[int, int]],
                  msmap: dict[int, int] | None):
    """Canonical multiset of (src_node_tuple, dst_node_tuple, attr_tuple),
    with ms labels pushed through `msmap` when comparing the reference's
    labeling to ours."""
    def m(t):
        return (msmap[t[0]], t[1]) if msmap is not None else t

    rows = []
    for s, r, a in zip(np.asarray(senders).tolist(),
                       np.asarray(receivers).tolist(),
                       np.asarray(attrs).tolist()):
        rows.append((m(nodes[s]), m(nodes[r]), tuple(int(x) for x in a)))
    return sorted(rows)


def _resources_match(my_res: pd.DataFrame, ref_res: pd.DataFrame,
                     msmap: dict[int, int]) -> bool:
    """Compare the per-(timestamp, msname) 8-feature resource tables.

    Microservices that appear in surviving traces are matched through the
    recovered ms bijection; resource-only microservices (never called, so
    absent from um/dm) are matched as a multiset of per-ms fingerprints —
    their ids are unconstrained by any aligned column."""
    feat_cols = [c for c in ref_res.columns
                 if c not in ("timestamp", "msname")]
    if sorted(feat_cols) != sorted(
            c for c in my_res.columns if c not in ("timestamp", "msname")):
        return False

    def fingerprint(df):
        rows = df[["timestamp"] + feat_cols].to_numpy(np.float64)
        return tuple(map(tuple, np.round(rows[np.lexsort(rows.T[::-1])],
                                         9)))

    ref_by_ms = {int(k): fingerprint(g)
                 for k, g in ref_res.groupby("msname")}
    my_by_ms = {int(k): fingerprint(g)
                for k, g in my_res.groupby("msname")}
    if len(ref_by_ms) != len(my_by_ms):
        return False
    mapped = {r: m for r, m in msmap.items() if r in ref_by_ms}
    for r, m in mapped.items():
        if my_by_ms.get(m) != ref_by_ms[r]:
            return False
    rest_ref = sorted(v for k, v in ref_by_ms.items() if k not in mapped)
    rest_my = sorted(v for k, v in my_by_ms.items()
                     if k not in set(mapped.values()))
    return rest_ref == rest_my


def compare(root: str, check: Check) -> dict:
    import torch
    from joblib import load as joblib_load

    from pertgnn_tpu.config import Config, IngestConfig
    from pertgnn_tpu.graphs.construct import build_runtime_graphs
    from pertgnn_tpu.ingest.assemble import assemble
    from pertgnn_tpu.ingest.preprocess import preprocess

    # ---- my pipeline on the reference-aligned read ----
    raw_df, raw_res = read_like_reference(root)
    cfg = Config(ingest=IngestConfig())
    pre = preprocess(raw_df, raw_res, cfg.ingest)
    table = assemble(pre, cfg.ingest)

    # ---- reference artifacts ----
    proc = os.path.join(root, "processed")
    ref_df = pd.read_csv(os.path.join(proc, "processed_df.csv"),
                         engine="pyarrow")
    tr2data = torch.load(os.path.join(proc, "tr2data.pt"),
                         weights_only=False)
    e2r = joblib_load(os.path.join(proc, "entry2runtimes.joblib"))
    span_g = torch.load(os.path.join(proc, "runtime2spangraph_map.pt"),
                        weights_only=False)
    pert_g = torch.load(os.path.join(proc, "runtime2pertgraph_map.pt"),
                        weights_only=False)

    mine = pre.spans
    # ---- processed frame: same rows, same codes ----
    check.ok("row_count", len(mine) == len(ref_df),
             f"{len(mine)} vs {len(ref_df)}")
    if len(mine) == len(ref_df):
        for col in ("traceid", "timestamp", "rpcid", "rpctype", "interface",
                    "entryid", "rt"):
            check.ok(f"col_{col}",
                     np.array_equal(mine[col].to_numpy(),
                                    ref_df[col].to_numpy()),
                     f"column {col} differs")
        msmap = ms_bijection(check, mine, ref_df)
    else:
        return {"fatal": "row count mismatch"}
    inv = {v: k for k, v in msmap.items()}  # my-ms -> ref-ms

    # ---- resource table (feeds find_most_recent_fts / get_x) ----
    ref_res = pd.read_csv(os.path.join(proc, "processed_resource_df.csv"),
                          engine="pyarrow")
    check.ok("resource_table", _resources_match(pre.resources, ref_res,
                                                msmap))

    # ---- per-trace table ----
    meta = table.meta.set_index("traceid")
    check.ok("trace_count", len(meta) == len(tr2data),
             f"{len(meta)} vs {len(tr2data)}")
    ent_ok = rt_ok = ts_ok = y_ok = True
    missing = 0
    for tid, rec in tr2data.items():
        if int(tid) not in meta.index:
            missing += 1
            continue
        row = meta.loc[int(tid)]
        ent_ok &= int(row["entry_id"]) == int(rec["entry_id"])
        rt_ok &= int(row["runtime_id"]) == int(rec["runtime_id"])
        ts_ok &= int(row["ts_bucket"]) == int(rec["timestamp"])
        # the reference stores y through torch.tensor(...) — float32
        # (preprocess.py:308); ours stays float64 until batching. The
        # faithful comparison is exact equality in float32.
        y_ok &= np.float32(row["y"]) == np.float32(rec["y"])
    check.ok("trace_ids_aligned", missing == 0,
             f"{missing} reference traces absent from our table")
    check.ok("trace_entry_ids", ent_ok)
    check.ok("trace_runtime_ids", rt_ok)
    check.ok("trace_ts_buckets", ts_ok)
    check.ok("trace_labels", y_ok)

    # ---- mixture weights ----
    probs_ok = set(int(k) for k in e2r) == set(table.entry2runtimes)
    for ent, (rids, probs) in table.entry2runtimes.items():
        ref_probs = e2r.get(ent, {})
        probs_ok &= len(ref_probs) == len(rids)
        for rid, p in zip(rids.tolist(), probs.tolist()):
            probs_ok &= abs(ref_probs.get(rid, -1.0) - p) < 1e-12
    check.ok("entry2runtimes", probs_ok)

    # ---- graphs ----
    my_span = build_runtime_graphs(pre, table, "span")
    my_pert = build_runtime_graphs(pre, table, "pert")
    check.ok("span_runtime_ids", set(span_g) == set(my_span))
    check.ok("pert_runtime_ids", set(pert_g) == set(my_pert))

    span_ok = depth_ok = True
    for rid, g in span_g.items():
        m = my_span.get(rid)
        if m is None or int(g["num_nodes"]) != m.num_nodes:
            span_ok = False
            continue
        ref_nodes = canonical_nodes(np.asarray(g["ms_id"]).ravel())
        my_nodes = canonical_nodes(m.ms_id)
        ei = np.asarray(g["edge_index"])
        # span edge_attr: (E, 2) interface/rpctype (misc.py:177-181)
        ref_edges = edge_multiset(ei[0], ei[1], np.asarray(g["edge_attr"]),
                                  ref_nodes, msmap)
        my_edges = edge_multiset(m.senders, m.receivers, m.edge_attr,
                                 my_nodes, None)
        span_ok &= ref_edges == my_edges
        # reference node_depth is cast to long AFTER min/max normalization
        # (misc.py:215: torch.tensor(node_depth, dtype=torch.long)) — i.e.
        # 1 on max-depth nodes, 0 elsewhere. Compare in that domain.
        ref_d = {n: int(d) for n, d in zip(
            ref_nodes, np.asarray(g["node_depth"]).ravel().tolist())}
        my_d = {(inv.get(n[0], -1 - n[0]), n[1]): int(d) for n, d in zip(
            my_nodes, m.node_depth.tolist())}
        depth_ok &= ref_d == my_d
    check.ok("span_graphs", span_ok)
    check.ok("span_node_depth_long", depth_ok)

    pert_ok = pdepth_ok = True
    for rid, g in pert_g.items():
        m = my_pert.get(rid)
        if m is None or int(g["num_nodes"]) != m.num_nodes:
            pert_ok = False
            continue
        ref_nodes = canonical_nodes(np.asarray(g["ms_id"]).ravel())
        my_nodes = canonical_nodes(m.ms_id)
        ei = np.asarray(g["edge_index"])
        ref_edges = edge_multiset(ei[0], ei[1], np.asarray(g["edge_attr"]),
                                  ref_nodes, msmap)
        my_edges = edge_multiset(m.senders, m.receivers, m.edge_attr,
                                 my_nodes, None)
        pert_ok &= ref_edges == my_edges
        ref_d = {n: int(d) for n, d in zip(
            ref_nodes, np.asarray(g["node_depth"]).ravel().tolist())}
        my_d = {(inv.get(n[0], -1 - n[0]), n[1]): int(d) for n, d in zip(
            my_nodes, m.node_depth.tolist())}
        pdepth_ok &= ref_d == my_d
    check.ok("pert_graphs", pert_ok)
    check.ok("pert_node_depth_long", pdepth_ok)

    return {
        "rows": len(mine),
        "traces": len(meta),
        "entries": len(table.entry2runtimes),
        "runtimes": len(my_span),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--traces", type=int, default=120,
                    help="traces per entry (>100 survives the occurrence "
                         "filter)")
    ap.add_argument("--sandbox", default=None,
                    help="keep the sandbox here instead of a temp dir")
    ap.add_argument("--seed", type=int, default=7,
                    help="synthetic-corpus seed: every seed is a fresh "
                         "randomized cross-check against the reference's "
                         "own code (topologies, timestamps, resource "
                         "gaps all resample)")
    args = ap.parse_args()

    # identical parsing semantics on both sides (see run_reference shim)
    pd.set_option("future.infer_string", False)
    root = args.sandbox or tempfile.mkdtemp(prefix="refparity_")
    os.makedirs(root, exist_ok=True)
    make_sandbox(root, args.traces, seed=args.seed)
    corpus_sha1 = fingerprint_corpus(root)
    proc = run_reference(root)
    if proc.returncode != 0:
        print(json.dumps({"fatal": "reference preprocess failed",
                          "stderr": proc.stderr[-2000:]}))
        sys.exit(2)

    check = Check()
    # A genuine divergence must still end in a printed verdict naming the
    # checks that ran (not a bare traceback), and the temp sandbox must
    # not leak on the failure path — this harness exists to DIAGNOSE
    # mismatches, so the failure path is the load-bearing one.
    try:
        stats = compare(root, check)
        fatal = None
    except Exception as e:  # noqa: BLE001 — report, don't crash
        import traceback
        stats = {}
        fatal = f"{type(e).__name__}: {e}"
        traceback.print_exc(file=sys.stderr)
    finally:
        ok = check.all_ok and fatal is None
        verdict = {"pass": ok, "seed": args.seed,
                   "corpus_sha1": corpus_sha1, "checks": check.results,
                   "notes": check.notes, **stats,
                   "sandbox": root if args.sandbox else "(temp, removed)"}
        if fatal:
            verdict["fatal"] = fatal
        print(json.dumps(verdict, indent=1))
        if not args.sandbox:
            import shutil
            shutil.rmtree(root, ignore_errors=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
