"""Minimal torch_geometric SHIM — just enough API for the reference's
driver (/root/reference/pert_gnn.py, model.py) to execute verbatim.

torch_geometric cannot be installed here (zero egress —
benchmarks/parity/pyg_install_attempt.log). This shim implements, from
PyG's PUBLIC documented semantics, exactly the surface the reference
imports:

- ``torch_geometric.data.Data``            (attribute container)
- ``torch_geometric.loader.DataLoader``    (graph-collating loader)
- ``torch_geometric.nn.TransformerConv``   (Shi et al. 2021 conv)
- ``torch_geometric.nn.Linear``            (lazy in_channels=-1)
- ``torch_geometric.nn.global_add_pool``

HONESTY NOTE (PARITY.md "Oracle independence"): running the reference on
this shim pins the reference's *driver* — get_x featurization, lru_cache
mixture assembly, collation, split, loss/metric semantics — against our
pipeline, because all of that is the reference's OWN code executing. It
does NOT independently pin TransformerConv: the conv here re-implements
the published equations with the same reading our other oracles use
(weight-transfer-pinned to the flax layer at 2e-4). A real PyG install
would still strengthen that one link.
"""
