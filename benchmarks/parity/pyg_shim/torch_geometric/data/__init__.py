from torch_geometric.data.data import Batch, Data  # noqa: F401
