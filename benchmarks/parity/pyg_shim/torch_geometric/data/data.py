"""Data / Batch with PyG's documented collation semantics.

Collation rule (PyG docs, torch_geometric.data.Batch.from_data_list):
every tensor attribute concatenates along dim 0, EXCEPT ``edge_index``
which concatenates along dim 1 with values incremented by the cumulative
node count of the preceding graphs; 0-d tensors stack to a 1-d tensor;
``batch`` maps each node to its graph index. num_nodes is x.shape[0].
"""

from __future__ import annotations

import torch


class Data:
    def __init__(self, **kwargs):
        self._keys = []
        for k, v in kwargs.items():
            setattr(self, k, v)
            self._keys.append(k)

    @property
    def num_nodes(self) -> int:
        return int(self.x.shape[0])

    @property
    def num_graphs(self) -> int:
        # a bare Data is one graph; Batch overrides
        return 1

    def to(self, device):
        for k in self._keys:
            v = getattr(self, k)
            if torch.is_tensor(v):
                setattr(self, k, v.to(device))
        if hasattr(self, "batch") and torch.is_tensor(self.batch):
            self.batch = self.batch.to(device)
        return self


class Batch(Data):
    @classmethod
    def from_data_list(cls, data_list):
        keys = data_list[0]._keys
        out = {}
        for k in keys:
            vals = [getattr(d, k) for d in data_list]
            if k == "edge_index":
                offsets = torch.cumsum(
                    torch.tensor([0] + [d.num_nodes for d in data_list[:-1]]),
                    dim=0)
                out[k] = torch.cat(
                    [v + off for v, off in zip(vals, offsets)], dim=1)
            elif torch.is_tensor(vals[0]) and vals[0].dim() == 0:
                out[k] = torch.stack(vals)
            elif torch.is_tensor(vals[0]):
                out[k] = torch.cat(vals, dim=0)
            else:
                out[k] = torch.tensor(vals)
        b = cls(**out)
        b.batch = torch.repeat_interleave(
            torch.arange(len(data_list)),
            torch.tensor([d.num_nodes for d in data_list]))
        b._num_graphs = len(data_list)
        return b

    @property
    def num_graphs(self) -> int:
        return self._num_graphs
