"""TransformerConv / Linear / global_add_pool for the shim.

TransformerConv implements the published operator (Shi et al. 2021,
"Masked Label Prediction", eq. 3-4 — the PyG docs' formula) for the
configuration the reference instantiates (heads=1, concat default,
root_weight default, edge_dim set; model.py:25-52):

    q_i = W3 x_i,  k_j = W4 x_j,  v_j = W2 x_j,  e_ij = W6 edge_attr_ij
    alpha_ij = softmax_over_j->i( q_i . (k_j + e_ij) / sqrt(d) )
    out_i = W1 x_i + sum_j alpha_ij (v_j + e_ij)

Messages flow source -> target: edge_index[0] = source j,
edge_index[1] = target i (PyG default flow). Math identical to
bench.make_torch_reference's Conv, which is weight-transfer-pinned to
the flax GraphTransformerLayer at 2e-4 (tests/test_model.py).
"""

from __future__ import annotations

import math

import torch


def global_add_pool(x, batch):
    num_graphs = int(batch.max().item()) + 1 if batch.numel() else 0
    out = torch.zeros(num_graphs, x.shape[-1], dtype=x.dtype,
                      device=x.device)
    return out.index_add(0, batch, x)


class Linear(torch.nn.Module):
    """PyG's Linear: supports lazy in_channels=-1 (model.py:68)."""

    def __init__(self, in_channels: int, out_channels: int, bias: bool = True):
        super().__init__()
        if in_channels == -1:
            self.lin = torch.nn.LazyLinear(out_channels, bias=bias)
        else:
            self.lin = torch.nn.Linear(in_channels, out_channels, bias=bias)

    @property
    def weight(self):
        return self.lin.weight

    @property
    def bias(self):
        return self.lin.bias

    def forward(self, x):
        return self.lin(x)


class TransformerConv(torch.nn.Module):
    def __init__(self, in_channels: int, out_channels: int, heads: int = 1,
                 edge_dim: int | None = None, **kwargs):
        super().__init__()
        assert heads == 1, "shim supports the reference's heads=1 only"
        self.out_channels = out_channels
        self.lin_query = torch.nn.Linear(in_channels, out_channels)
        self.lin_key = torch.nn.Linear(in_channels, out_channels)
        self.lin_value = torch.nn.Linear(in_channels, out_channels)
        self.lin_edge = (torch.nn.Linear(edge_dim, out_channels, bias=False)
                         if edge_dim is not None else None)
        self.lin_skip = torch.nn.Linear(in_channels, out_channels)

    def reset_parameters(self):
        for m in (self.lin_query, self.lin_key, self.lin_value,
                  self.lin_edge, self.lin_skip):
            if m is not None:
                m.reset_parameters()

    def forward(self, x, edge_index, edge_attr=None):
        src, dst = edge_index[0], edge_index[1]
        n = x.shape[0]
        q = self.lin_query(x)[dst]
        k = self.lin_key(x)[src]
        v = self.lin_value(x)[src]
        if self.lin_edge is not None and edge_attr is not None:
            e = self.lin_edge(edge_attr)
            k = k + e
            v = v + e
        score = (q * k).sum(-1) / math.sqrt(self.out_channels)
        smax = torch.full((n,), -torch.inf,
                          device=x.device).scatter_reduce(
            0, dst, score, reduce="amax")
        # smax is only gathered at dst positions that HAVE edges, so it is
        # always finite there — subtract the true max (softmax is
        # shift-invariant; clamping at 0 would forfeit stabilization for
        # all-negative score groups)
        ex = torch.exp(score - smax[dst])
        den = torch.zeros(n, device=x.device).index_add(0, dst, ex)
        alpha = ex / den.clamp_min(1e-16)[dst]
        out = torch.zeros(n, self.out_channels,
                          device=x.device).index_add(0, dst,
                                                     v * alpha[:, None])
        return out + self.lin_skip(x)
