from torch_geometric.nn.modules import (Linear, TransformerConv,  # noqa: F401
                                        global_add_pool)
