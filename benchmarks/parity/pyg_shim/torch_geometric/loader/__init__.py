from torch_geometric.loader.dataloader import DataLoader  # noqa: F401
